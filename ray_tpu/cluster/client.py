"""Driver-side cluster runtime: submit/get/put/wait/actors over the RPC plane.

Reference analog: the submit path of the core worker
(src/ray/core_worker/core_worker.cc:2475 SubmitTask ->
transport/normal_task_submitter.h:74 — lease request, spillback retry,
PushNormalTask to the leased worker) and the actor submit path
(transport/actor_task_submitter.h:382). Redesigned around the node
daemon's lease RPC: the driver leases from its local daemon, follows at
most a few spillback hops, pushes the task directly to the granted
worker, and releases the lease when the push returns. Results live in
node object stores; `get` pulls through the local daemon's fetch path.

Failure handling: a dead worker/node surfaces as a transport error on
the push; the task is re-leased elsewhere up to `max_retries` (the
reference's task_manager.h:260 retry loop, node-failure edition).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Optional, Sequence

import cloudpickle

from ray_tpu.cluster.rpc import (
    ClientPool,
    ReconnectingRpcClient,
    RemoteError,
    RpcClient,
    RpcError,
)
from ray_tpu.cluster.serialization import _ErrorValue, dumps_value, loads_value
from ray_tpu.chaos import harness as _chaos
from ray_tpu.util.backoff import ExponentialBackoff
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.cluster.client")


class ClusterTaskError(Exception):
    def __init__(self, desc: str, cause: BaseException, tb: str):
        super().__init__(f"{desc} failed: {cause!r}\n{tb}")
        self.cause = cause


class ActorDiedError(Exception):
    pass


class GetTimeoutError(Exception):
    pass


def _new_id() -> bytes:
    return uuid.uuid4().bytes


def _current_trace_dict() -> Optional[dict]:
    """Ambient TraceContext as an envelope-ready dict (None when the
    caller isn't tracing). Tracing must never break submission."""
    try:
        from ray_tpu.obs import context as trace_context

        ctx = trace_context.current()
        return ctx.to_dict() if ctx is not None else None
    except Exception:  # noqa: BLE001
        return None


class ClusterObjectRef:
    """A future for an object living in some node's store.

    Refs created by the OWNING client (put / task returns) participate in
    driver-side ref counting: when the last owned handle drops, the
    object is freed cluster-wide (reference: owner-based ref counting,
    src/ray/core_worker/reference_count.h:66 — here collapsed to the
    driver as sole owner; deserialized/borrowed refs never free)."""

    __slots__ = ("id", "_client", "_desc", "_owned")

    def __init__(self, object_id: bytes, client: "ClusterClient", desc: str = "",
                 owned: bool = False):
        self.id = object_id
        self._client = client
        self._desc = desc
        self._owned = owned
        if owned:
            client._mark_owned(object_id)
            client._incref(object_id)

    def get(self, timeout: Optional[float] = None):
        return self._client.get(self, timeout=timeout)

    def __reduce__(self):
        # travels as a persistent id through dumps_value; plain pickling
        # (e.g. inside foreign containers) rebuilds against the ambient
        # client on the receiving side — as a BORROWED ref
        return (_rebuild_ref, (self.id, self._desc))

    def __del__(self):
        if getattr(self, "_owned", False):
            try:
                self._client._decref(self.id)
            except Exception:
                pass

    def __repr__(self):
        return f"ClusterObjectRef({self.id.hex()[:12]}, {self._desc})"


def _rebuild_ref(object_id: bytes, desc: str) -> "ClusterObjectRef":
    return ClusterObjectRef(object_id, _ambient_client(), desc)


_AMBIENT: list = [None]


def _ambient_client():
    c = _AMBIENT[0]
    if c is None:
        raise RuntimeError("no ClusterClient in this process")
    return c


class ClusterActorHandle:
    """Location-transparent actor handle (actor_id + GCS lookup)."""

    def __init__(self, actor_id: bytes, client: "ClusterClient", desc: str = "actor"):
        self._actor_id = actor_id
        self._client = client
        self._desc = desc

    def __getattr__(self, name: str) -> "_ActorMethod":
        if name.startswith("_"):
            raise AttributeError(name)
        return _ActorMethod(self, name)

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_id, self._desc))

    def kill(self) -> None:
        self._client.kill_actor(self._actor_id)

    @property
    def state(self) -> str:
        info = self._client.gcs.call("get_actor", {"actor_id": self._actor_id})
        return info["state"] if info else "UNKNOWN"


def _rebuild_handle(actor_id: bytes, desc: str) -> ClusterActorHandle:
    return ClusterActorHandle(actor_id, _ambient_client(), desc)


class _ActorMethod:
    def __init__(self, handle: ClusterActorHandle, name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs):
        h = self._handle
        return h._client.submit_actor_task(
            h._actor_id, self._name, args, kwargs
        )

    def bind(self, *args, **kwargs):
        """Compiled-DAG node construction (reference: actor method .bind
        building a ClassMethodNode, python/ray/dag/class_node.py)."""
        from ray_tpu.dag.nodes import bind_actor_method

        return bind_actor_method(self._handle, self._name)(*args, **kwargs)

    def options(self, num_returns: int = 1):
        method = self

        class _Opts:
            def remote(self_o, *args, **kwargs):
                h = method._handle
                return h._client.submit_actor_task(
                    h._actor_id, method._name, args, kwargs,
                    num_returns=num_returns,
                )

        return _Opts()


class ClusterClient:
    """One per driver process. `local_daemon` is the colocated node daemon
    the driver leases from and fetches through (the head node's raylet)."""

    def __init__(self, gcs_addr: tuple, local_daemon_addr: tuple):
        # reconnecting: survives a GCS restart (FT snapshot + same port)
        self.gcs = ReconnectingRpcClient(*gcs_addr, timeout=60.0).connect(retries=20)
        self.local_daemon_addr = tuple(local_daemon_addr)
        self.pool = ClientPool(timeout=120.0)
        self._lock = threading.Lock()
        # ref-count ops flow through a lock-free deque consumed by ONE
        # accountant thread: __del__ may fire from cyclic GC while this
        # thread holds any lock, so the hot path must only deque.append
        # (GIL-atomic) — taking a client lock there can self-deadlock
        from collections import deque as _deque

        self._rc_ops: "_deque[tuple[str, bytes]]" = _deque()
        self._spans: "_deque[dict]" = _deque(maxlen=10000)  # task tracing
        # drivers own their objects and free on last handle drop; worker
        # processes only BORROW (their task returns are owned by the
        # submitting driver) — worker_main flips this off so a worker
        # dropping a ref it created for a nested submit can't free an
        # object some caller still holds
        self.auto_free = True
        self._closed = False
        # lineage: return-oid -> shared task record, enough to RE-EXECUTE
        # the producing task when its stored result is lost with the node
        # that held it (reference: lineage reconstruction driven by the
        # ownership table, core_worker object recovery). Depth 1: a
        # reconstruction whose ARGS were also lost fails over to the
        # normal task-lost error. Bounded; entries drop with the ref.
        self._lineage: dict[bytes, dict] = {}
        self._lineage_cap = 8192
        self._lineage_guard = threading.Lock()  # check-then-act on records
        self._freer = threading.Thread(
            target=self._rc_loop, name="ray_tpu-freer", daemon=True
        )
        self._freer.start()
        # bounded submitter pool: thread-per-task melts down under wide
        # fan-out (thousands of threads fighting the GIL); a pool sized to
        # the host caps that while keeping pushes concurrent. Long-running
        # pushes hold a pool thread, so size it generously.
        import concurrent.futures
        import os as _os

        self._submitter = concurrent.futures.ThreadPoolExecutor(
            max_workers=int(
                _os.environ.get(
                    "RAY_TPU_SUBMIT_THREADS", min(64, 8 * (_os.cpu_count() or 4))
                )
            ),
            thread_name_prefix="ray_tpu-submit",
        )
        # plasma-client role: attach the local daemon's shm store READ side
        # so get() of same-node sealed objects never round-trips the RPC
        # plane (reference: the driver IS a plasma client; round-5 profile:
        # the daemon->driver pickle+TCP copy was the large-return ceiling)
        self._shm = None
        self._shm_tried = False
        # worker-lease cache (reference: normal_task_submitter.h keeps
        # leased workers ~1s for queued tasks of the same spec): plain
        # resource-only leases are RETURNED here after a task instead of
        # released, and reused by the next submit — 2 of the 4 RPCs per
        # small task gone. Swept by the accountant thread on TTL expiry.
        self._lease_cache: dict = {}
        self._lease_cache_lock = threading.Lock()
        self._lease_waiters: dict = {}  # key -> {"cond", "leader"}
        # default OFF: on a single-core host the daemon's server-side FIFO
        # queue beats client-side lease reuse (measured round 5: 449/s
        # plain vs 253/s naive cache vs 174/s leader-multiplexed cache —
        # the GIL serializes the extra client machinery); revisit on
        # multi-core hosts where submitter threads actually run parallel
        self._lease_ttl = float(
            _os.environ.get("RAY_TPU_LEASE_CACHE_TTL", "0")
        )
        _AMBIENT[0] = self

    @property
    def local_daemon(self) -> RpcClient:
        return self.pool.get(self.local_daemon_addr)

    def _local_shm(self):
        if not self._shm_tried:
            self._shm_tried = True
            try:
                info = self.local_daemon.call("shm_info", None, timeout=10)
                path = (info or {}).get("shm_path")
                if path:
                    from ray_tpu.native.shm import ShmObjectStore

                    self._shm = ShmObjectStore.open(path)
            except Exception:  # noqa: BLE001 — store unavailable: RPC path
                self._shm = None
        return self._shm

    def _shm_get(self, object_id: bytes):
        """Zero-RPC read of a same-node sealed object, or None."""
        shm = self._local_shm()
        if shm is None:
            return None
        try:
            return shm.get_bytes(object_id)
        except OSError:
            return None

    def close(self) -> None:
        self._closed = True  # _return_lease now releases instead of caching
        self._submitter.shutdown(wait=False, cancel_futures=True)
        try:
            self._sweep_lease_cache(release_all=True)
        except Exception:  # noqa: BLE001
            pass
        self.gcs.close()
        self.pool.close_all()
        if self._shm is not None:
            try:
                self._shm.close()
            except Exception:  # noqa: BLE001
                pass
            self._shm = None
        if _AMBIENT[0] is self:
            _AMBIENT[0] = None

    # -- driver-side ref counting ---------------------------------------------
    # Only OWNED ids ("own" op: put / task returns created here) are ever
    # freed; borrowed refs pinned as task args inc/dec without freeing.

    def _incref(self, object_id: bytes) -> None:
        self._rc_ops.append(("inc", object_id))

    def _decref(self, object_id: bytes) -> None:
        self._rc_ops.append(("dec", object_id))

    def _mark_owned(self, object_id: bytes) -> None:
        self._rc_ops.append(("own", object_id))

    def free(self, refs) -> None:
        """Explicitly free objects cluster-wide (ray._private free analog)."""
        if not isinstance(refs, (list, tuple)):
            refs = [refs]
        for r in refs:
            self._rc_ops.append(("free", r.id))

    def _rc_loop(self) -> None:
        """The accountant: applies ref-count ops, frees owned objects on
        their last decref (reference: ReferenceCounter's delete callback,
        reference_count.h:66). A ref dropped BEFORE its task stored the
        result has no locations yet — those frees retry until the object
        appears (else fire-and-forget results would leak forever)."""
        counts: dict[bytes, int] = {}
        owned: set[bytes] = set()
        retries: dict[bytes, tuple[float, int]] = {}  # oid -> (due, attempts)
        last_sweep = 0.0
        while not self._closed:
            now = time.monotonic()
            if now - last_sweep > 0.25:
                # periodic, NOT only-when-idle: sustained refcount traffic
                # must not starve TTL-expired cached leases of release
                last_sweep = now
                try:
                    self._sweep_lease_cache()
                except Exception:  # noqa: BLE001
                    pass
            for oid, (due, attempts) in list(retries.items()):
                if due <= now:
                    if self._free_everywhere(oid) or attempts >= 120:
                        retries.pop(oid, None)
                    else:
                        retries[oid] = (now + 1.0, attempts + 1)
            if not self._rc_ops:
                time.sleep(0.05)
                continue
            try:
                op, oid = self._rc_ops.popleft()
            except IndexError:
                continue
            if op == "inc":
                counts[oid] = counts.get(oid, 0) + 1
            elif op == "own":
                owned.add(oid)
            elif op == "dec":
                n = counts.get(oid, 0) - 1
                if n > 0:
                    counts[oid] = n
                else:
                    counts.pop(oid, None)
                    if oid in owned and self.auto_free:
                        owned.discard(oid)
                        self._lineage.pop(oid, None)  # freed: never rebuild
                        if not self._free_everywhere(oid):
                            retries[oid] = (time.monotonic() + 1.0, 1)
            elif op == "free":
                owned.discard(oid)
                counts.pop(oid, None)
                retries.pop(oid, None)
                self._lineage.pop(oid, None)
                self._free_everywhere(oid)

    def _free_everywhere(self, oid: bytes) -> bool:
        """Free on every holder; returns True when at least one holder
        existed (False = object not stored anywhere yet)."""
        try:
            locs = self.gcs.call("locate_object", {"object_id": oid}, timeout=10)
        except Exception:
            return False
        freed = False
        for addr in locs or ():
            freed = True
            try:
                self.pool.get(tuple(addr)).call(
                    "free_object", {"object_id": oid}, timeout=10
                )
            except (RpcError, RemoteError):
                pass
        return freed

    # -- kv -------------------------------------------------------------------

    def kvtier_update(self, payload: dict, timeout: float = 5.0) -> dict:
        """Ship one engine's prefix-index snapshot to the GCS
        (llm/kvtier; epoch-banked — a dropped or delayed snapshot can
        only cost freshness, the next one supersedes it)."""
        return self.gcs.call("kvtier_update", payload, timeout=timeout)

    def kvtier_lookup(self, hashes: list, timeout: float = 5.0) -> dict:
        """Longest indexed KV prefix per engine for these chain hashes
        (prefix-aware routing; callers treat failure as a dark index
        and fall back to their queue-depth ladder)."""
        return self.gcs.call("kvtier_lookup", {"hashes": list(hashes)},
                             timeout=timeout)

    def kvtier_stats(self, timeout: float = 5.0) -> dict:
        return self.gcs.call("kvtier_stats", None, timeout=timeout)

    def kv_put(self, key: bytes, value: bytes, ns: str = "default") -> None:
        self.gcs.call("kv_put", {"ns": ns, "key": key, "value": value})

    def kv_get(self, key: bytes, ns: str = "default"):
        return self.gcs.call("kv_get", {"ns": ns, "key": key})

    def kv_del(self, key: bytes, ns: str = "default") -> None:
        self.gcs.call("kv_del", {"ns": ns, "key": key})

    def kv_wait(self, key: bytes, ns: str = "default",
                timeout: float = 120.0):
        """Block until `key` exists (server-side long-poll loop); returns
        its value, or raises TimeoutError."""
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(f"kv_wait({ns}/{key!r}) after {timeout}s")
            v = self.gcs.call(
                "kv_wait", {"ns": ns, "key": key, "wait": min(remaining, 5.0)}
            )
            if v is not None:
                return v

    # -- objects --------------------------------------------------------------

    def put(self, value: Any) -> ClusterObjectRef:
        oid = _new_id()
        self.local_daemon.call(
            "put_object", {"object_id": oid, "data": dumps_value(value)}
        )
        return ClusterObjectRef(oid, self, "put", owned=True)

    def get(self, ref: "ClusterObjectRef | Sequence[ClusterObjectRef]",
            timeout: Optional[float] = None):
        if isinstance(ref, (list, tuple)):
            return type(ref)(self._get_many(list(ref), timeout))
        deadline = time.monotonic() + (timeout if timeout is not None else 300.0)
        t0 = time.monotonic()
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise GetTimeoutError(f"get({ref!r}) timed out")
            data = self._shm_get(ref.id)
            if data is None:
                data = self.local_daemon.call(
                    "fetch_object",
                    {"object_id": ref.id, "timeout": min(remaining, 5.0)},
                    timeout=min(remaining, 5.0) + 10,
                )
            if data is None and time.monotonic() - t0 > 2.0:
                self._maybe_reconstruct(ref.id)
            if data is not None:
                value = loads_value(data, self._resolve)
                if isinstance(value, _ErrorValue):
                    raise ClusterTaskError(value.task_desc, value.exc, value.tb)
                return value

    def _get_many(self, refs: list, timeout: Optional[float]) -> list:
        """Batched get: pipelined fetch_object frames on one connection
        (not one blocking round-trip per ref)."""
        deadline = time.monotonic() + (timeout if timeout is not None else 300.0)
        out: dict[int, Any] = {}
        pending = list(enumerate(refs))
        t0 = time.monotonic()
        while pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise GetTimeoutError(f"get of {len(pending)} refs timed out")
            # shm fast path first: same-node sealed results cost zero RPCs
            rpc_pending = []
            for i, r in pending:
                data = self._shm_get(r.id)
                if data is not None:
                    value = loads_value(data, self._resolve)
                    if isinstance(value, _ErrorValue):
                        raise ClusterTaskError(
                            value.task_desc, value.exc, value.tb
                        )
                    out[i] = value
                else:
                    rpc_pending.append((i, r))
            pending = rpc_pending
            if not pending:
                break
            step = min(remaining, 5.0)
            datas = self.local_daemon.call(
                "fetch_objects",
                {"object_ids": [r.id for _, r in pending], "timeout": step,
                 "shm_direct": self._local_shm() is not None},
                timeout=step + 30,
            )
            still = []
            reconstruct = time.monotonic() - t0 > 2.0
            for (i, r), data in zip(pending, datas):
                if data is None:
                    if reconstruct:
                        self._maybe_reconstruct(r.id)
                    still.append((i, r))
                    continue
                if isinstance(data, dict) and data.get("__shm__"):
                    data = self._shm_get(r.id)
                    if data is None:  # evicted between marker and read
                        step2 = max(0.1, min(deadline - time.monotonic(), 5.0))
                        data = self.local_daemon.call(
                            "fetch_object",
                            {"object_id": r.id, "timeout": step2},
                            timeout=step2 + 10,
                        )
                    if data is None:
                        still.append((i, r))
                        continue
                value = loads_value(data, self._resolve)
                if isinstance(value, _ErrorValue):
                    raise ClusterTaskError(value.task_desc, value.exc, value.tb)
                out[i] = value
            pending = still
        return [out[i] for i in range(len(refs))]

    def _resolve(self, object_id: bytes):
        data = self._shm_get(object_id)
        if data is None:
            data = self.local_daemon.call(
                "fetch_object", {"object_id": object_id, "timeout": 30.0},
                timeout=40,
            )
        if data is None:
            raise RuntimeError(f"object {object_id.hex()} unavailable")
        value = loads_value(data, self._resolve)
        if isinstance(value, _ErrorValue):
            raise ClusterTaskError(value.task_desc, value.exc, value.tb)
        return value

    def wait(self, refs: Sequence[ClusterObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: list = []
        pending = list(refs)
        t0 = time.monotonic()
        while len(ready) < num_returns:
            # one batched probe per poll (not one RPC per ref)
            have = self.gcs.call(
                "locate_many", {"object_ids": [r.id for r in pending]}
            )
            still = []
            reconstruct = time.monotonic() - t0 > 2.0
            for r in pending:
                if have.get(r.id):
                    ready.append(r)
                else:
                    if reconstruct:
                        self._maybe_reconstruct(r.id)
                    still.append(r)
            pending = still
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.02)
        return ready, pending

    # -- task submission ------------------------------------------------------

    def submit(
        self,
        func,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        *,
        resources: Optional[dict] = None,
        num_returns: int = 1,
        max_retries: int = 3,
        pg_id: Optional[bytes] = None,
        bundle_index: int = 0,
        desc: Optional[str] = None,
        affinity_node_id: Optional[str] = None,
        affinity_soft: bool = False,
        runtime_env: Optional[dict] = None,
    ) -> "ClusterObjectRef | list[ClusterObjectRef]":
        desc = desc or getattr(func, "__name__", "task")
        return_ids = [_new_id() for _ in range(num_returns)]
        # pin argument objects until the task completes: user code may drop
        # its handles while the task is still pending/retrying
        arg_refs: list[bytes] = []
        payload = {
            "task_id": _new_id(),
            "desc": desc,
            "func": self._dumps_func(func),
            "args": dumps_value((args, dict(kwargs or {})), arg_refs.append),
            "return_ids": return_ids,
            "num_returns": num_returns,
            # trace context rides the envelope: captured HERE (the caller
            # thread) because _drive_task runs on the submitter pool where
            # the contextvar is gone
            "trace": _current_trace_dict(),
        }
        for oid in arg_refs:
            self._incref(oid)
        spec = {
            # None -> default 1 CPU; an explicit {} means "costs nothing"
            "resources": dict({"num_cpus": 1} if resources is None else resources),
            "pg_id": pg_id,
            "bundle_index": bundle_index,
            "affinity_node_id": affinity_node_id,
            "affinity_soft": affinity_soft,
            "runtime_env": self._package_runtime_env(runtime_env),
            # the daemon's memory monitor prefers killing retriable work
            # (reference: worker_killing_policy retriable-first)
            "retriable": max_retries > 0,
        }
        if (self.auto_free and max_retries > 0
                and len(self._lineage) < self._lineage_cap):
            # max_retries=0 means the caller forbids re-execution (side
            # effects); such tasks are never rebuilt from lineage either
            record = {
                "payload": payload, "spec": spec, "arg_refs": list(arg_refs),
                "attempts": 2, "done": False, "inflight": True,
                "max_retries": max_retries,
            }
            for rid in return_ids:
                self._lineage[rid] = record
        else:
            record = None
        fut = self._submitter.submit(
            self._drive_task, payload, spec, max_retries, arg_refs
        )
        if record is not None:
            def _done(_f, rec=record):
                rec["done"] = True
                rec["inflight"] = False

            fut.add_done_callback(_done)
        refs = [ClusterObjectRef(rid, self, desc, owned=True) for rid in return_ids]
        return refs[0] if num_returns == 1 else refs

    def _maybe_reconstruct(self, object_id: bytes) -> bool:
        """If `object_id` is a finished task's return that no node holds
        anymore, re-execute the producing task (same return ids). Returns
        True when a reconstruction was dispatched."""
        rec = self._lineage.get(object_id)
        if rec is None or rec["inflight"] or not rec["done"] or rec["attempts"] <= 0:
            return False
        try:
            locs = self.gcs.call(
                "locate_object", {"object_id": object_id}, timeout=10
            )
        except Exception:  # noqa: BLE001 — treat a flaky GCS as "not lost"
            return False
        if locs:
            return False  # stored somewhere; the fetch path will find it
        with self._lineage_guard:
            # re-check under the lock: concurrent get()/wait() callers on
            # the same lost task must dispatch exactly ONE re-execution
            if rec["inflight"] or not rec["done"] or rec["attempts"] <= 0:
                return False
            rec["attempts"] -= 1
            rec["inflight"] = True
            rec["done"] = False
        logger.warning(
            "object %s lost with its node; re-executing task %r via lineage",
            object_id.hex()[:12], rec["payload"]["desc"],
        )
        for oid in rec["arg_refs"]:
            self._incref(oid)
        fut = self._submitter.submit(
            self._drive_task, rec["payload"], rec["spec"],
            rec.get("max_retries", 3), rec["arg_refs"],
        )

        def _done(_f, r=rec):
            r["done"] = True
            r["inflight"] = False

        fut.add_done_callback(_done)
        return True

    _FUNC_PICKLE_CACHE_MAX = 256

    def _dumps_func(self, func) -> bytes:
        """Memoized cloudpickle of the task function: a task storm over
        one function pays the (closure-walking) pickle once, not per
        submit. Keyed by identity — a redefined function is a new
        object.

        Semantics note (matches the reference): ray exports a remote
        function ONCE and reuses the pickled form, so globals/closure
        cells are snapshotted at first submission — mutating a captured
        global between submits does not reach later tasks. Pass changing
        values as ARGUMENTS."""
        cache = getattr(self, "_func_pickles", None)
        if cache is None:
            cache = self._func_pickles = {}
        key = id(func)
        hit = cache.get(key)
        # id() recycles after GC: keep a strong ref to the function in
        # the cache entry so the key can't be reused by a different one
        if hit is not None and hit[0] is func:
            return hit[1]
        data = cloudpickle.dumps(func)
        if len(cache) >= self._FUNC_PICKLE_CACHE_MAX:
            cache.clear()
        cache[key] = (func, data)
        return data

    def _drive_task(self, payload: dict, spec: dict, max_retries: int,
                    arg_refs: Sequence[bytes] = ()) -> None:
        attempt = 0
        exclude: list = []
        # jittered exponential retry delay: N submitters whose tasks died
        # with one node must not re-lease in synchronized 0.1s waves
        backoff = ExponentialBackoff(base=0.1, cap=2.0)
        try:
            while True:
                try:
                    self._run_once(payload, spec, exclude)
                    return
                except (RpcError, RemoteError) as e:
                    attempt += 1
                    if attempt > max_retries:
                        err = _ErrorValue(
                            RuntimeError(f"task lost after {max_retries} retries: {e}"),
                            "", payload["desc"],
                        )
                        for rid in payload["return_ids"]:
                            try:
                                self.local_daemon.call(
                                    "put_object",
                                    {"object_id": rid, "data": dumps_value(err)},
                                )
                            except Exception:
                                logger.exception("cannot store task-lost error")
                        return
                    logger.warning(
                        "%s attempt %d failed (%s); retrying", payload["desc"],
                        attempt, e,
                    )
                    backoff.sleep()
        finally:
            for oid in arg_refs:  # unpin the task's argument objects
                self._decref(oid)

    def _lease(self, spec: dict, exclude: list) -> tuple[dict, RpcClient]:
        """Lease a worker, following spillback hops. Nodes that refused
        this lease are excluded for subsequent hops (prevents ping-pong on
        stale availability views); the visited set resets when the whole
        cluster is saturated and we fall back to waiting."""
        addr = self.local_daemon_addr
        pinned = False
        if spec.get("affinity_node_id") is not None:
            # NodeAffinity: lease directly on the named node (reference:
            # scheduling_strategies.py NodeAffinitySchedulingStrategy)
            nodes = {n["node_id"]: n for n in self.gcs.call("list_nodes", None)}
            target = nodes.get(spec["affinity_node_id"])
            if target is None or not target["alive"]:
                if not spec.get("affinity_soft"):
                    raise RemoteError(RuntimeError(
                        f"node {spec['affinity_node_id']} not alive (hard affinity)"
                    ))
            else:
                addr = tuple(target["addr"])
                pinned = not spec.get("affinity_soft", False)
        if spec.get("pg_id") is not None:
            # placement-group tasks go straight to the node holding the
            # reserved bundle (reference: PG scheduling strategy bypasses
            # the hybrid policy); bundle_index -1 = any bundle that fits
            # (reference wildcard semantics, placement_group.py)
            return self._lease_pg(spec)
        deadline = time.monotonic() + 120.0
        visited: set = set()
        hops = 0
        # lease re-poll: jittered exponential (floored by the daemon's
        # retry_after hint) so saturated-cluster waiters decorrelate
        # instead of hammering the daemon queue in phase
        backoff = ExponentialBackoff(base=0.05, cap=1.0)
        while time.monotonic() < deadline:
            daemon = self.pool.get(addr)
            r = daemon.call(
                "request_worker_lease",
                {**spec, "exclude": list(set(exclude) | visited),
                 "pinned": pinned},
                timeout=90,
            )
            if "grant" in r:
                return r["grant"], daemon
            if "node_id" in r:
                visited.add(r["node_id"])
            if "spillback" in r and hops < 16 and not pinned:
                addr = tuple(r["spillback"])
                hops += 1
                continue
            if "error" in r:
                raise RemoteError(RuntimeError(r["error"]))
            backoff.sleep(floor=r.get("retry_after", 0.0))
            visited.clear()  # capacity may have freed anywhere
            hops = 0
            if not pinned:
                addr = self.local_daemon_addr  # re-evaluate from home
        raise RpcError("lease request timed out")

    def _lease_pg(self, spec: dict) -> tuple[dict, RpcClient]:
        """Lease inside a placement group: a fixed bundle (index >= 0) or
        any bundle that grants (index -1), sweeping until the deadline."""
        deadline = time.monotonic() + 120.0
        backoff = ExponentialBackoff(base=0.05, cap=1.0)
        while time.monotonic() < deadline:
            info = self.gcs.call("get_pg", {"pg_id": spec["pg_id"]})
            if info is None:
                raise RemoteError(RuntimeError("placement group removed"))
            idx = spec.get("bundle_index", 0)
            candidates = [idx] if idx >= 0 else list(range(len(info["bundles"])))
            nodes = {n["node_id"]: tuple(n["addr"]) for n in
                     self.gcs.call("list_nodes", None)}
            delay = 0.05
            # a fixed bundle queues server-side for the full window; a
            # wildcard sweep queues briefly per bundle so it keeps rotating
            queue_timeout = 30.0 if idx >= 0 else 0.5
            for i in candidates:
                bundle = info["bundles"][i]
                if bundle["node_id"] is None:
                    continue  # not (re)placed yet
                daemon = self.pool.get(nodes[bundle["node_id"]])
                r = daemon.call(
                    "request_worker_lease",
                    {**spec, "bundle_index": i, "queue_timeout": queue_timeout},
                    timeout=90,
                )
                if "grant" in r:
                    return r["grant"], daemon
                if "error" in r and idx >= 0:
                    raise RemoteError(RuntimeError(r["error"]))
                delay = min(delay, r.get("retry_after", 0.05))
            backoff.sleep(floor=delay)
        raise RpcError("placement-group lease timed out")

    def _lease_cache_key(self, spec: dict):
        """Only plain resource-only leases are cacheable: pg / affinity /
        runtime_env leases carry placement semantics a later task of the
        same shape must re-resolve."""
        if (
            self._lease_ttl <= 0
            or spec.get("pg_id") is not None
            or spec.get("affinity_node_id") is not None
            or spec.get("runtime_env")
        ):
            return None
        # retriable-ness is part of the key: the daemon records the flag
        # per LEASE, so a non-retriable task must not inherit a cached
        # lease the OOM policy would treat as retriable
        return (
            spec.get("retriable", True),
            tuple(sorted((spec.get("resources") or {}).items())),
        )

    def _pop_cached_lease(self, key, exclude=()):
        if key is None:
            return None
        stale = []
        hit = None
        with self._lease_cache_lock:
            entries = self._lease_cache.get(key)
            while entries:
                grant, daemon_addr, expiry = entries.pop()
                if time.monotonic() >= expiry or grant.get("node_id") in exclude:
                    # expired, or the retry path just failed on that node
                    stale.append((grant, daemon_addr))
                    continue
                hit = (grant, daemon_addr)
                break
        # release OUTSIDE the lock: a dead daemon's 10s RPC timeout must
        # not freeze every submitter blocked on the cache lock
        for grant, daemon_addr in stale:
            self._release_lease_now(grant, daemon_addr)
        if hit is not None:
            return hit[0], self.pool.get(hit[1])
        return None

    def _return_lease(self, key, grant, daemon_addr) -> None:
        if self._closed:
            # close() already swept; caching now would leak the lease
            self._release_lease_now(grant, daemon_addr)
            return
        with self._lease_cache_lock:
            self._lease_cache.setdefault(key, []).append(
                (grant, daemon_addr, time.monotonic() + self._lease_ttl)
            )
            state = self._lease_waiters.get(key)
        if state is not None:
            with state["cond"]:
                state["cond"].notify_all()  # hand off to a waiting submitter

    def _acquire_lease(self, key, spec, exclude):
        """Get a worker lease, multiplexing submitters of the same spec:
        at most ONE daemon lease request in flight per key (the 'leader'
        rides the daemon's server-side FIFO queue); everyone else waits
        client-side and consumes leases RETURNED by completing tasks.
        Without this, returned leases would sit in the cache while peer
        submitters block inside the daemon queue — the naive version
        measured SLOWER than no cache at all (reference analog: one
        pipelined lease request per scheduling key,
        normal_task_submitter.h:74)."""
        if key is None:
            return self._lease(spec, exclude)
        with self._lease_cache_lock:
            state = self._lease_waiters.setdefault(
                key, {"cond": threading.Condition(), "leader": False}
            )
        deadline = time.monotonic() + 120.0
        while True:
            got = self._pop_cached_lease(key, exclude)
            if got is not None:
                return got
            with state["cond"]:
                if not state["leader"]:
                    state["leader"] = True
                    break
                state["cond"].wait(0.05)
            if time.monotonic() >= deadline:
                raise RpcError("lease wait timed out")
        try:
            return self._lease(spec, exclude)
        finally:
            with state["cond"]:
                state["leader"] = False
                state["cond"].notify_all()

    def _release_lease_now(self, grant, daemon_addr, kill: bool = False):
        try:
            self.pool.get(daemon_addr).call(
                "release_lease",
                {"lease_id": grant["lease_id"], "kill": kill},
                timeout=10,
            )
        except (RpcError, RemoteError):
            pass  # daemon died with its node; lease died with it

    def _sweep_lease_cache(self, release_all: bool = False) -> None:
        now = time.monotonic()
        to_release = []
        with self._lease_cache_lock:
            for key in list(self._lease_cache):
                keep = []
                for grant, daemon_addr, expiry in self._lease_cache[key]:
                    if not release_all and now < expiry:
                        keep.append((grant, daemon_addr, expiry))
                    else:
                        to_release.append((grant, daemon_addr))
                if keep:
                    self._lease_cache[key] = keep
                else:
                    del self._lease_cache[key]
                    # drop the waiter state with the last cached lease —
                    # per-shape Condition objects must not accumulate on a
                    # long-lived driver with many distinct resource tags
                    state = self._lease_waiters.get(key)
                    if state is not None and not state["leader"]:
                        del self._lease_waiters[key]
        for grant, daemon_addr in to_release:  # RPCs outside the lock
            self._release_lease_now(grant, daemon_addr)

    def _run_once(self, payload: dict, spec: dict, exclude: list) -> None:
        t0 = time.monotonic()
        key = self._lease_cache_key(spec)
        grant, daemon = self._acquire_lease(key, spec, exclude)
        t_leased = time.monotonic()
        worker_addr = tuple(grant["worker_addr"])
        kill = False
        try:
            if _chaos.ACTIVE is not None:
                for _f in _chaos.fire(
                    "cluster.push",
                    kinds=(_chaos.KILL_WORKER, _chaos.DROP_RPC,
                           _chaos.DELAY_RPC),
                    desc=payload.get("desc", "task"),
                    node_id=grant.get("node_id", ""),
                ):
                    if _f.kind == _chaos.KILL_WORKER:
                        # kill the granted worker out from under the push:
                        # the connection error below is exactly what a real
                        # worker death mid-lease looks like to the driver
                        self._release_lease_now(
                            grant,
                            tuple(grant.get("node_addr")
                                  or self.local_daemon_addr),
                            kill=True,
                        )
                    elif _f.kind == _chaos.DROP_RPC:
                        raise RpcError(
                            f"chaos: dropped push of {payload.get('desc')!r}"
                        )
                    elif _f.kind == _chaos.DELAY_RPC:
                        time.sleep(_f.delay_s)
            w = self.pool.get(worker_addr)
            r = w.call("push_task", payload, timeout=3600)
            if not r.get("ok"):
                # user-level failure: error value already stored; done
                return
        except (RpcError, RemoteError):
            kill = True
            exclude.append(grant["node_id"])
            self.pool.invalidate(worker_addr)
            raise
        finally:
            self._record_span(
                payload.get("desc", "task"), grant.get("node_id"), t0,
                t_leased, time.monotonic(), trace=payload.get("trace"),
            )
            daemon_addr = tuple(grant.get("node_addr") or self.local_daemon_addr)
            if kill or key is None:
                # the daemon queues lease requests and its idle-worker pool
                # makes re-grant instant, so non-cacheable leases release
                # immediately rather than starve queued submitters
                self._release_lease_now(grant, daemon_addr, kill=kill)
            else:
                # reference normal_task_submitter behavior: keep the leased
                # worker briefly for the next task of the same shape
                self._return_lease(key, grant, daemon_addr)

    # -- tracing --------------------------------------------------------------

    def _record_span(self, desc: str, node_id, t0: float, t_leased: float,
                     t_done: float, trace: Optional[dict] = None) -> None:
        """Per-task spans (lease wait + execution), bounded buffer.
        Reference analog: per-task ProfileEvents batched into
        GcsTaskManager powering `ray timeline` (core_worker/
        task_event_buffer.h); here driver-side, exported Chrome-trace."""
        span = {"desc": desc, "node": node_id, "start": t0,
                "leased": t_leased, "end": t_done}
        if trace:
            span["trace_id"] = trace.get("trace_id")
            span["span_id"] = trace.get("span_id")
        self._spans.append(span)

    def timeline(self) -> list:
        """Chrome-trace events (chrome://tracing / Perfetto) for this
        driver's cluster tasks: a `lease` slice and an `exec` slice per
        task, rows grouped by node (the `ray timeline` analog for the
        cluster plane)."""
        spans = list(getattr(self, "_spans", ()))
        events = []
        for i, s in enumerate(spans):
            trace_args = (
                {"trace_id": s["trace_id"], "span_id": s.get("span_id")}
                if s.get("trace_id") else {}
            )
            for name, a, b in (("lease", "start", "leased"),
                               ("exec", "leased", "end")):
                events.append({
                    "name": f"{s['desc']}:{name}",
                    "ph": "X",
                    "ts": s[a] * 1e6,
                    "dur": max(0.0, (s[b] - s[a])) * 1e6,
                    "pid": s["node"] or "cluster",
                    "tid": i % 64,
                    "cat": name,
                    **({"args": trace_args} if trace_args else {}),
                })
        return events

    def task_stats(self) -> dict:
        """Aggregate latency split across recorded spans (ms)."""
        spans = list(getattr(self, "_spans", ()))
        if not spans:
            return {"tasks": 0}
        lease = [(s["leased"] - s["start"]) * 1e3 for s in spans]
        ex = [(s["end"] - s["leased"]) * 1e3 for s in spans]
        lease.sort()
        ex.sort()

        def pct(a, p):
            return round(a[min(len(a) - 1, int(len(a) * p))], 2)

        return {
            "tasks": len(spans),
            "lease_ms_p50": pct(lease, 0.5), "lease_ms_p99": pct(lease, 0.99),
            "exec_ms_p50": pct(ex, 0.5), "exec_ms_p99": pct(ex, 0.99),
        }

    # -- actors ---------------------------------------------------------------

    def create_actor(
        self,
        cls,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        *,
        resources: Optional[dict] = None,
        name: Optional[str] = None,
        namespace: str = "default",
        max_restarts: int = 0,
        pg_id: Optional[bytes] = None,
        bundle_index: int = 0,
        runtime_env: Optional[dict] = None,
    ) -> ClusterActorHandle:
        actor_id = _new_id()
        # ctor-arg objects must outlive the actor (restarts replay the
        # creation_spec); pin them until kill_actor
        ctor_refs: list[bytes] = []
        creation_spec = dumps_value(
            (cls, args, dict(kwargs or {})), ctor_refs.append
        )
        for oid in ctor_refs:
            self._incref(oid)
        spec = {
            # None -> default 1 CPU; an explicit {} means "costs nothing"
            "resources": dict({"num_cpus": 1} if resources is None else resources),
            "pg_id": pg_id,
            "bundle_index": bundle_index,
            "runtime_env": self._package_runtime_env(runtime_env),
            # OOM victim policy: a max_restarts=0 actor is NOT retriable
            "retriable": max_restarts > 0,
        }
        grant, daemon = self._lease(spec, [])
        worker_addr = tuple(grant["worker_addr"])
        w = self.pool.get(worker_addr)
        r = w.call(
            "create_actor",
            {"actor_id": actor_id, "creation_spec": creation_spec,
             # registration metadata rides to the worker too: the node's
             # reconcile report can then resurrect this actor (name and
             # all) on a GCS whose snapshot predates it
             "meta": {"name": name, "namespace": namespace,
                      "max_restarts": max_restarts,
                      "lease_resources": dict(spec["resources"])}},
            timeout=300,
        )
        if not r.get("ok"):
            daemon.call("release_lease", {"lease_id": grant["lease_id"], "kill": True})
            raise ClusterTaskError(
                f"actor {getattr(cls, '__name__', cls)}",
                RuntimeError(r.get("error", "creation failed")),
                r.get("tb", ""),
            )
        reg = self.gcs.call(
            "register_actor",
            {
                "actor_id": actor_id,
                "name": name,
                "namespace": namespace,
                "node_id": grant["node_id"],
                "worker_addr": worker_addr,
                "state": "ALIVE",
                "max_restarts": max_restarts,
                "creation_spec": creation_spec,
                "lease": {"resources": spec["resources"]},
                "lease_id": grant["lease_id"],
                "node_addr": grant.get("node_addr"),
            },
        )
        if not reg.get("ok"):
            raise ValueError(reg.get("error", "actor registration failed"))
        # NOTE: the lease stays held for the actor's lifetime (the worker is
        # dedicated to it); kill_actor releases it.
        self._lock_actor_meta(actor_id, grant, worker_addr, ctor_refs)
        return ClusterActorHandle(
            actor_id, self, desc=getattr(cls, "__name__", "actor")
        )

    def _lock_actor_meta(self, actor_id, grant, worker_addr, ctor_refs=()):
        with self._lock:
            if not hasattr(self, "_actor_meta"):
                self._actor_meta = {}
            self._actor_meta[actor_id] = {
                "grant": grant, "worker_addr": worker_addr,
                "ctor_refs": list(ctor_refs),
            }

    def _actor_worker(self, actor_id: bytes, wait_restart: float = 30.0) -> tuple:
        """Resolve the actor's current worker address (GCS lookup with
        restart-aware waiting)."""
        with self._lock:
            meta = getattr(self, "_actor_meta", {}).get(actor_id)
        if meta is not None:
            return meta["worker_addr"]
        deadline = time.monotonic() + wait_restart
        backoff = ExponentialBackoff(base=0.05, cap=0.5)
        while time.monotonic() < deadline:
            info = self.gcs.call("get_actor", {"actor_id": actor_id})
            if info is None:
                raise ActorDiedError(f"actor {actor_id.hex()} unknown")
            if info["state"] == "ALIVE" and info["worker_addr"]:
                return tuple(info["worker_addr"])
            if info["state"] == "DEAD":
                raise ActorDiedError(f"actor {actor_id.hex()} is dead")
            backoff.sleep()
        raise ActorDiedError(f"actor {actor_id.hex()} not available (restarting?)")

    def submit_actor_task(
        self, actor_id: bytes, method: str, args: tuple, kwargs: dict,
        num_returns: int = 1,
    ):
        return_ids = [_new_id() for _ in range(num_returns)]
        arg_refs: list[bytes] = []
        payload = {
            "actor_id": actor_id,
            "method": method,
            "args": dumps_value((args, dict(kwargs or {})), arg_refs.append),
            "return_ids": return_ids,
            "num_returns": num_returns,
            "trace": _current_trace_dict(),
        }
        for oid in arg_refs:
            self._incref(oid)
        self._submitter.submit(self._drive_actor_task, actor_id, payload, arg_refs)
        refs = [
            ClusterObjectRef(rid, self, f"actor.{method}", owned=True)
            for rid in return_ids
        ]
        return refs[0] if num_returns == 1 else refs

    def _drive_actor_task(self, actor_id: bytes, payload: dict,
                          arg_refs: Sequence[bytes] = ()) -> None:
        backoff = ExponentialBackoff(base=0.2, cap=1.0)
        try:
            for attempt in range(2):
                try:
                    addr = self._actor_worker(actor_id)
                    w = self.pool.get(addr)
                    r = w.call("actor_call", payload, timeout=3600)
                    if r.get("actor_missing") and attempt == 0:
                        # stale address (restart happened): force GCS lookup
                        self._forget_actor_addr(actor_id)
                        continue
                    return
                except (RpcError, RemoteError):
                    self._forget_actor_addr(actor_id)
                    if attempt == 1:
                        break
                    backoff.sleep()
                except ActorDiedError as e:
                    self._store_actor_error(payload, e)
                    return
            self._store_actor_error(
                payload, ActorDiedError(f"actor {actor_id.hex()} unreachable")
            )
        finally:
            for oid in arg_refs:
                self._decref(oid)

    def _forget_actor_addr(self, actor_id: bytes) -> None:
        with self._lock:
            getattr(self, "_actor_meta", {}).pop(actor_id, None)

    def _store_actor_error(self, payload: dict, exc: Exception) -> None:
        err = _ErrorValue(exc, "", f"actor.{payload['method']}")
        for rid in payload["return_ids"]:
            try:
                self.local_daemon.call(
                    "put_object", {"object_id": rid, "data": dumps_value(err)}
                )
            except Exception:
                pass

    def get_named_actor(self, name: str, namespace: str = "default") -> ClusterActorHandle:
        info = self.gcs.call(
            "get_named_actor", {"name": name, "namespace": namespace}
        )
        if info is None or info["state"] == "DEAD":
            raise ValueError(f"no live actor named {name!r}")
        return ClusterActorHandle(info["actor_id"], self, desc=name)

    def kill_actor(self, actor_id: bytes) -> None:
        with self._lock:
            meta = getattr(self, "_actor_meta", {}).pop(actor_id, None)
        for oid in (meta or {}).get("ctor_refs", ()):
            self._decref(oid)  # unpin the ctor args (no more restarts)
        info = self.gcs.call("get_actor", {"actor_id": actor_id})
        if info and info["worker_addr"]:
            try:
                self.pool.get(tuple(info["worker_addr"])).call(
                    "destroy_actor", {"actor_id": actor_id}, timeout=5
                )
            except (RpcError, RemoteError):
                pass
        self.gcs.call(
            "update_actor", {"actor_id": actor_id, "state": "DEAD"}
        )
        # release the backing lease on the daemon that GRANTED it — the
        # GCS entry is authoritative (it tracks restarts onto new nodes;
        # a locally cached grant would go stale after the first restart)
        if info and info.get("lease_id") and info.get("node_addr"):
            try:
                self.pool.get(tuple(info["node_addr"])).call(
                    "release_lease",
                    {"lease_id": info["lease_id"], "kill": True},
                    timeout=5,
                )
            except (RpcError, RemoteError):
                pass

    # -- runtime envs ---------------------------------------------------------

    def _package_runtime_env(self, runtime_env: Optional[dict]) -> Optional[dict]:
        """Zip + stage a runtime env's directories, memoizing the WHOLE
        wire form by (spec, directory fingerprints) so a task storm pays
        one stat-walk per submit instead of a re-zip; staged packages are
        PINNED for the client's lifetime (workers fetch them on every
        env-dedicated worker spawn)."""
        if not runtime_env:
            return None
        import hashlib
        import json
        import os as _os

        from ray_tpu.cluster.runtime_env import (
            package_runtime_env,
            validate_keys,
            walk_dir,
        )

        # validate BEFORE the cache: a cached wire form must not let a
        # later request smuggle a rejected key (pip/conda) past the check
        validate_keys(runtime_env)
        if not hasattr(self, "_env_packages"):
            self._env_packages: dict[str, ClusterObjectRef] = {}
            self._env_wire_cache: dict[str, dict] = {}

        def fingerprint(path: str) -> tuple:
            # mirrors _zip_dir's walk (cycle-safe, __pycache__-free) so
            # pyc churn can't invalidate a byte-identical package
            out = []
            for root, dirs, files in walk_dir(path):
                for f in sorted(files):
                    try:
                        st = _os.stat(_os.path.join(root, f))
                        out.append((_os.path.relpath(_os.path.join(root, f), path),
                                    st.st_size, st.st_mtime_ns))
                    except OSError:
                        pass
            return tuple(out)

        spec_key = json.dumps(
            {
                "env_vars": runtime_env.get("env_vars", {}),
                "working_dir": [runtime_env.get("working_dir"),
                                fingerprint(runtime_env["working_dir"])
                                if runtime_env.get("working_dir") else None],
                "py_modules": [(m, fingerprint(m))
                               for m in runtime_env.get("py_modules", ())],
            },
            sort_keys=True, default=str,
        )
        cached = self._env_wire_cache.get(spec_key)
        if cached is not None:
            return cached

        def put_pkg(data: bytes) -> bytes:
            key = hashlib.sha256(data).hexdigest()
            ref = self._env_packages.get(key)
            if ref is None:
                ref = self.put(data)
                self._env_packages[key] = ref  # pinned until close
            return ref.id

        wire = package_runtime_env(runtime_env, put_pkg)
        self._env_wire_cache[spec_key] = wire
        return wire

    # -- placement groups -----------------------------------------------------

    def create_placement_group(
        self, bundles: list, strategy: str = "PACK", name: Optional[str] = None,
        timeout: float = 30.0,
    ) -> dict:
        pg_id = _new_id()
        deadline = time.monotonic() + timeout
        info = self.gcs.call(
            "create_pg",
            {"pg_id": pg_id, "bundles": bundles, "strategy": strategy, "name": name},
        )
        while info["state"] not in ("CREATED",):
            if time.monotonic() > deadline:
                raise TimeoutError(f"placement group not placed: {info['state']}")
            time.sleep(0.05)
            info = self.gcs.call("get_pg", {"pg_id": pg_id})
        # reserve the bundles on their nodes. The GCS placed against its
        # availability view, which can run ~1 heartbeat ahead of the node
        # (e.g. a just-removed PG's resources flight back) — retry briefly
        # before declaring the reservation failed.
        nodes = {n["node_id"]: tuple(n["addr"]) for n in self.gcs.call("list_nodes", None)}
        for i, b in enumerate(info["bundles"]):
            addr = nodes[b["node_id"]]
            # jittered backoff up to the remaining deadline: under load the
            # daemon's availability can trail the GCS view by several
            # heartbeats (freed resources still in flight), and the old
            # fixed 6x0.2s budget gave up inside that window
            backoff = ExponentialBackoff(base=0.1, cap=1.0)
            while True:
                r = self.pool.get(addr).call(
                    "reserve_pg_bundle",
                    {"pg_id": pg_id, "bundle_index": i, "resources": b["resources"]},
                )
                if r.get("ok") or time.monotonic() >= deadline:
                    break
                backoff.sleep()
            if not r.get("ok"):
                raise RuntimeError(
                    f"bundle {i} reservation failed on {b['node_id']}: {r}"
                )
        return info

    def remove_placement_group(self, pg_id: bytes) -> None:
        nodes = {n["node_id"]: tuple(n["addr"]) for n in self.gcs.call("list_nodes", None)}
        info = self.gcs.call("get_pg", {"pg_id": pg_id})
        if info:
            for b in info["bundles"]:
                addr = nodes.get(b["node_id"])
                if addr:
                    try:
                        self.pool.get(addr).call(
                            "release_pg_all", {"pg_id": pg_id}, timeout=5
                        )
                    except (RpcError, RemoteError):
                        pass
        self.gcs.call("remove_pg", {"pg_id": pg_id})

    # -- cluster state --------------------------------------------------------

    def nodes(self) -> list:
        return self.gcs.call("list_nodes", None)

    # -- telemetry plane (ray_tpu.obs.telemetry) ------------------------------

    def cluster_metrics(self) -> dict:
        """GCS-aggregated cluster metrics: counter sums + windowed rates,
        gauge rollups, merged histograms, per-reporter staleness."""
        return self.gcs.call("telemetry_cluster", {})

    def slo_report(self, thresholds: Optional[dict] = None) -> dict:
        """Per-model-tag green/yellow/red grades from the MERGED SLO
        histograms (the autoscaler's input)."""
        return self.gcs.call(
            "telemetry_slo",
            {"thresholds": thresholds} if thresholds else {},
        )

    def telemetry_status(self, thresholds: Optional[dict] = None) -> dict:
        """Everything `ray_tpu status` prints, in ONE GCS query."""
        return self.gcs.call(
            "telemetry_status",
            {"thresholds": thresholds} if thresholds else {},
        )

    def cluster_resources(self) -> dict:
        total: dict[str, float] = {}
        for n in self.nodes():
            if n["alive"]:
                for k, v in n["resources"].items():
                    total[k] = total.get(k, 0.0) + v
        return total
