"""Driver-side cluster runtime: submit/get/put/wait/actors over the RPC plane.

Reference analog: the submit path of the core worker
(src/ray/core_worker/core_worker.cc:2475 SubmitTask ->
transport/normal_task_submitter.h:74 — lease request, spillback retry,
PushNormalTask to the leased worker) and the actor submit path
(transport/actor_task_submitter.h:382). Redesigned around the node
daemon's lease RPC: the driver leases from its local daemon, follows at
most a few spillback hops, pushes the task directly to the granted
worker, and releases the lease when the push returns. Results live in
node object stores; `get` pulls through the local daemon's fetch path.

Failure handling: a dead worker/node surfaces as a transport error on
the push; the task is re-leased elsewhere up to `max_retries` (the
reference's task_manager.h:260 retry loop, node-failure edition).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Optional, Sequence

import cloudpickle

from ray_tpu.cluster.rpc import ClientPool, RemoteError, RpcClient, RpcError
from ray_tpu.cluster.serialization import _ErrorValue, dumps_value, loads_value
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.cluster.client")


class ClusterTaskError(Exception):
    def __init__(self, desc: str, cause: BaseException, tb: str):
        super().__init__(f"{desc} failed: {cause!r}\n{tb}")
        self.cause = cause


class ActorDiedError(Exception):
    pass


class GetTimeoutError(Exception):
    pass


def _new_id() -> bytes:
    return uuid.uuid4().bytes


class ClusterObjectRef:
    """A future for an object living in some node's store."""

    __slots__ = ("id", "_client", "_desc")

    def __init__(self, object_id: bytes, client: "ClusterClient", desc: str = ""):
        self.id = object_id
        self._client = client
        self._desc = desc

    def get(self, timeout: Optional[float] = None):
        return self._client.get(self, timeout=timeout)

    def __reduce__(self):
        # travels as a persistent id through dumps_value; plain pickling
        # (e.g. inside foreign containers) rebuilds against the ambient
        # client on the receiving side
        return (_rebuild_ref, (self.id, self._desc))

    def __repr__(self):
        return f"ClusterObjectRef({self.id.hex()[:12]}, {self._desc})"


def _rebuild_ref(object_id: bytes, desc: str) -> "ClusterObjectRef":
    return ClusterObjectRef(object_id, _ambient_client(), desc)


_AMBIENT: list = [None]


def _ambient_client():
    c = _AMBIENT[0]
    if c is None:
        raise RuntimeError("no ClusterClient in this process")
    return c


class ClusterActorHandle:
    """Location-transparent actor handle (actor_id + GCS lookup)."""

    def __init__(self, actor_id: bytes, client: "ClusterClient", desc: str = "actor"):
        self._actor_id = actor_id
        self._client = client
        self._desc = desc

    def __getattr__(self, name: str) -> "_ActorMethod":
        if name.startswith("_"):
            raise AttributeError(name)
        return _ActorMethod(self, name)

    def __reduce__(self):
        return (_rebuild_handle, (self._actor_id, self._desc))

    def kill(self) -> None:
        self._client.kill_actor(self._actor_id)

    @property
    def state(self) -> str:
        info = self._client.gcs.call("get_actor", {"actor_id": self._actor_id})
        return info["state"] if info else "UNKNOWN"


def _rebuild_handle(actor_id: bytes, desc: str) -> ClusterActorHandle:
    return ClusterActorHandle(actor_id, _ambient_client(), desc)


class _ActorMethod:
    def __init__(self, handle: ClusterActorHandle, name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs):
        h = self._handle
        return h._client.submit_actor_task(
            h._actor_id, self._name, args, kwargs
        )

    def options(self, num_returns: int = 1):
        method = self

        class _Opts:
            def remote(self_o, *args, **kwargs):
                h = method._handle
                return h._client.submit_actor_task(
                    h._actor_id, method._name, args, kwargs,
                    num_returns=num_returns,
                )

        return _Opts()


class ClusterClient:
    """One per driver process. `local_daemon` is the colocated node daemon
    the driver leases from and fetches through (the head node's raylet)."""

    def __init__(self, gcs_addr: tuple, local_daemon_addr: tuple):
        self.gcs = RpcClient(*gcs_addr, timeout=60.0).connect(retries=20)
        self.local_daemon_addr = tuple(local_daemon_addr)
        self.pool = ClientPool(timeout=120.0)
        self._lock = threading.Lock()
        _AMBIENT[0] = self

    @property
    def local_daemon(self) -> RpcClient:
        return self.pool.get(self.local_daemon_addr)

    def close(self) -> None:
        self.gcs.close()
        self.pool.close_all()
        if _AMBIENT[0] is self:
            _AMBIENT[0] = None

    # -- objects --------------------------------------------------------------

    def put(self, value: Any) -> ClusterObjectRef:
        oid = _new_id()
        self.local_daemon.call(
            "put_object", {"object_id": oid, "data": dumps_value(value)}
        )
        return ClusterObjectRef(oid, self, "put")

    def get(self, ref: "ClusterObjectRef | Sequence[ClusterObjectRef]",
            timeout: Optional[float] = None):
        if isinstance(ref, (list, tuple)):
            return type(ref)(self.get(r, timeout=timeout) for r in ref)
        deadline = time.monotonic() + (timeout if timeout is not None else 300.0)
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise GetTimeoutError(f"get({ref!r}) timed out")
            data = self.local_daemon.call(
                "fetch_object",
                {"object_id": ref.id, "timeout": min(remaining, 5.0)},
                timeout=min(remaining, 5.0) + 10,
            )
            if data is not None:
                value = loads_value(data, self._resolve)
                if isinstance(value, _ErrorValue):
                    raise ClusterTaskError(value.task_desc, value.exc, value.tb)
                return value

    def _resolve(self, object_id: bytes):
        data = self.local_daemon.call(
            "fetch_object", {"object_id": object_id, "timeout": 30.0}, timeout=40
        )
        if data is None:
            raise RuntimeError(f"object {object_id.hex()} unavailable")
        value = loads_value(data, self._resolve)
        if isinstance(value, _ErrorValue):
            raise ClusterTaskError(value.task_desc, value.exc, value.tb)
        return value

    def wait(self, refs: Sequence[ClusterObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        deadline = None if timeout is None else time.monotonic() + timeout
        ready: list = []
        pending = list(refs)
        while len(ready) < num_returns:
            still = []
            for r in pending:
                locs = self.gcs.call("locate_object", {"object_id": r.id})
                if locs:
                    ready.append(r)
                else:
                    still.append(r)
            pending = still
            if len(ready) >= num_returns:
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            time.sleep(0.02)
        return ready, pending

    # -- task submission ------------------------------------------------------

    def submit(
        self,
        func,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        *,
        resources: Optional[dict] = None,
        num_returns: int = 1,
        max_retries: int = 3,
        pg_id: Optional[bytes] = None,
        bundle_index: int = 0,
        desc: Optional[str] = None,
        affinity_node_id: Optional[str] = None,
        affinity_soft: bool = False,
    ) -> "ClusterObjectRef | list[ClusterObjectRef]":
        desc = desc or getattr(func, "__name__", "task")
        return_ids = [_new_id() for _ in range(num_returns)]
        payload = {
            "task_id": _new_id(),
            "desc": desc,
            "func": cloudpickle.dumps(func),
            "args": dumps_value((args, dict(kwargs or {}))),
            "return_ids": return_ids,
            "num_returns": num_returns,
        }
        spec = {
            "resources": dict(resources or {"num_cpus": 1}),
            "pg_id": pg_id,
            "bundle_index": bundle_index,
            "affinity_node_id": affinity_node_id,
            "affinity_soft": affinity_soft,
        }
        t = threading.Thread(
            target=self._drive_task,
            args=(payload, spec, max_retries),
            name=f"submit-{desc}",
            daemon=True,
        )
        t.start()
        refs = [ClusterObjectRef(rid, self, desc) for rid in return_ids]
        return refs[0] if num_returns == 1 else refs

    def _drive_task(self, payload: dict, spec: dict, max_retries: int) -> None:
        attempt = 0
        exclude: list = []
        while True:
            try:
                self._run_once(payload, spec, exclude)
                return
            except (RpcError, RemoteError) as e:
                attempt += 1
                if attempt > max_retries:
                    err = _ErrorValue(
                        RuntimeError(f"task lost after {max_retries} retries: {e}"),
                        "", payload["desc"],
                    )
                    for rid in payload["return_ids"]:
                        try:
                            self.local_daemon.call(
                                "put_object",
                                {"object_id": rid, "data": dumps_value(err)},
                            )
                        except Exception:
                            logger.exception("cannot store task-lost error")
                    return
                logger.warning(
                    "%s attempt %d failed (%s); retrying", payload["desc"],
                    attempt, e,
                )
                time.sleep(0.1)

    def _lease(self, spec: dict, exclude: list) -> tuple[dict, RpcClient]:
        """Lease a worker, following spillback hops. Nodes that refused
        this lease are excluded for subsequent hops (prevents ping-pong on
        stale availability views); the visited set resets when the whole
        cluster is saturated and we fall back to waiting."""
        addr = self.local_daemon_addr
        pinned = False
        if spec.get("affinity_node_id") is not None:
            # NodeAffinity: lease directly on the named node (reference:
            # scheduling_strategies.py NodeAffinitySchedulingStrategy)
            nodes = {n["node_id"]: n for n in self.gcs.call("list_nodes", None)}
            target = nodes.get(spec["affinity_node_id"])
            if target is None or not target["alive"]:
                if not spec.get("affinity_soft"):
                    raise RemoteError(RuntimeError(
                        f"node {spec['affinity_node_id']} not alive (hard affinity)"
                    ))
            else:
                addr = tuple(target["addr"])
                pinned = not spec.get("affinity_soft", False)
        if spec.get("pg_id") is not None:
            # placement-group tasks go straight to the node holding the
            # reserved bundle (reference: PG scheduling strategy bypasses
            # the hybrid policy)
            info = self.gcs.call("get_pg", {"pg_id": spec["pg_id"]})
            if info is None:
                raise RemoteError(RuntimeError("placement group removed"))
            bundle = info["bundles"][spec.get("bundle_index", 0)]
            if bundle["node_id"] is None:
                raise RemoteError(RuntimeError("bundle not placed yet"))
            nodes = {n["node_id"]: tuple(n["addr"]) for n in
                     self.gcs.call("list_nodes", None)}
            addr = nodes[bundle["node_id"]]
        deadline = time.monotonic() + 120.0
        visited: set = set()
        hops = 0
        while time.monotonic() < deadline:
            daemon = self.pool.get(addr)
            r = daemon.call(
                "request_worker_lease",
                {**spec, "exclude": list(set(exclude) | visited),
                 "pinned": pinned},
                timeout=90,
            )
            if "grant" in r:
                return r["grant"], daemon
            if "node_id" in r:
                visited.add(r["node_id"])
            if "spillback" in r and hops < 16 and not pinned:
                addr = tuple(r["spillback"])
                hops += 1
                continue
            if "error" in r:
                raise RemoteError(RuntimeError(r["error"]))
            time.sleep(r.get("retry_after", 0.05))
            visited.clear()  # capacity may have freed anywhere
            hops = 0
            if not pinned:
                addr = self.local_daemon_addr  # re-evaluate from home
        raise RpcError("lease request timed out")

    def _run_once(self, payload: dict, spec: dict, exclude: list) -> None:
        grant, daemon = self._lease(spec, exclude)
        worker_addr = tuple(grant["worker_addr"])
        kill = False
        try:
            w = self.pool.get(worker_addr)
            r = w.call("push_task", payload, timeout=3600)
            if not r.get("ok"):
                # user-level failure: error value already stored; done
                return
        except (RpcError, RemoteError):
            kill = True
            exclude.append(grant["node_id"])
            self.pool.invalidate(worker_addr)
            raise
        finally:
            try:
                daemon.call(
                    "release_lease",
                    {"lease_id": grant["lease_id"], "kill": kill},
                    timeout=10,
                )
            except (RpcError, RemoteError):
                pass  # daemon died with its node; lease died with it

    # -- actors ---------------------------------------------------------------

    def create_actor(
        self,
        cls,
        args: tuple = (),
        kwargs: Optional[dict] = None,
        *,
        resources: Optional[dict] = None,
        name: Optional[str] = None,
        namespace: str = "default",
        max_restarts: int = 0,
        pg_id: Optional[bytes] = None,
        bundle_index: int = 0,
    ) -> ClusterActorHandle:
        actor_id = _new_id()
        creation_spec = dumps_value((cls, args, dict(kwargs or {})))
        spec = {
            "resources": dict(resources or {"num_cpus": 1}),
            "pg_id": pg_id,
            "bundle_index": bundle_index,
        }
        grant, daemon = self._lease(spec, [])
        worker_addr = tuple(grant["worker_addr"])
        w = self.pool.get(worker_addr)
        r = w.call(
            "create_actor",
            {"actor_id": actor_id, "creation_spec": creation_spec},
            timeout=300,
        )
        if not r.get("ok"):
            daemon.call("release_lease", {"lease_id": grant["lease_id"], "kill": True})
            raise ClusterTaskError(
                f"actor {getattr(cls, '__name__', cls)}",
                RuntimeError(r.get("error", "creation failed")),
                r.get("tb", ""),
            )
        reg = self.gcs.call(
            "register_actor",
            {
                "actor_id": actor_id,
                "name": name,
                "namespace": namespace,
                "node_id": grant["node_id"],
                "worker_addr": worker_addr,
                "state": "ALIVE",
                "max_restarts": max_restarts,
                "creation_spec": creation_spec,
                "lease": {"resources": spec["resources"]},
                "lease_id": grant["lease_id"],
                "node_addr": grant.get("node_addr"),
            },
        )
        if not reg.get("ok"):
            raise ValueError(reg.get("error", "actor registration failed"))
        # NOTE: the lease stays held for the actor's lifetime (the worker is
        # dedicated to it); kill_actor releases it.
        self._lock_actor_meta(actor_id, grant, worker_addr)
        return ClusterActorHandle(
            actor_id, self, desc=getattr(cls, "__name__", "actor")
        )

    def _lock_actor_meta(self, actor_id, grant, worker_addr):
        with self._lock:
            if not hasattr(self, "_actor_meta"):
                self._actor_meta = {}
            self._actor_meta[actor_id] = {
                "grant": grant, "worker_addr": worker_addr,
            }

    def _actor_worker(self, actor_id: bytes, wait_restart: float = 30.0) -> tuple:
        """Resolve the actor's current worker address (GCS lookup with
        restart-aware waiting)."""
        with self._lock:
            meta = getattr(self, "_actor_meta", {}).get(actor_id)
        if meta is not None:
            return meta["worker_addr"]
        deadline = time.monotonic() + wait_restart
        while time.monotonic() < deadline:
            info = self.gcs.call("get_actor", {"actor_id": actor_id})
            if info is None:
                raise ActorDiedError(f"actor {actor_id.hex()} unknown")
            if info["state"] == "ALIVE" and info["worker_addr"]:
                return tuple(info["worker_addr"])
            if info["state"] == "DEAD":
                raise ActorDiedError(f"actor {actor_id.hex()} is dead")
            time.sleep(0.1)
        raise ActorDiedError(f"actor {actor_id.hex()} not available (restarting?)")

    def submit_actor_task(
        self, actor_id: bytes, method: str, args: tuple, kwargs: dict,
        num_returns: int = 1,
    ):
        return_ids = [_new_id() for _ in range(num_returns)]
        payload = {
            "actor_id": actor_id,
            "method": method,
            "args": dumps_value((args, dict(kwargs or {}))),
            "return_ids": return_ids,
            "num_returns": num_returns,
        }
        t = threading.Thread(
            target=self._drive_actor_task, args=(actor_id, payload),
            name=f"actor-call-{method}", daemon=True,
        )
        t.start()
        refs = [ClusterObjectRef(rid, self, f"actor.{method}") for rid in return_ids]
        return refs[0] if num_returns == 1 else refs

    def _drive_actor_task(self, actor_id: bytes, payload: dict) -> None:
        for attempt in range(2):
            try:
                addr = self._actor_worker(actor_id)
                w = self.pool.get(addr)
                r = w.call("actor_call", payload, timeout=3600)
                if r.get("actor_missing") and attempt == 0:
                    # stale address (restart happened): force GCS lookup
                    self._forget_actor_addr(actor_id)
                    continue
                return
            except (RpcError, RemoteError):
                self._forget_actor_addr(actor_id)
                if attempt == 1:
                    break
                time.sleep(0.2)
            except ActorDiedError as e:
                self._store_actor_error(payload, e)
                return
        self._store_actor_error(
            payload, ActorDiedError(f"actor {actor_id.hex()} unreachable")
        )

    def _forget_actor_addr(self, actor_id: bytes) -> None:
        with self._lock:
            getattr(self, "_actor_meta", {}).pop(actor_id, None)

    def _store_actor_error(self, payload: dict, exc: Exception) -> None:
        err = _ErrorValue(exc, "", f"actor.{payload['method']}")
        for rid in payload["return_ids"]:
            try:
                self.local_daemon.call(
                    "put_object", {"object_id": rid, "data": dumps_value(err)}
                )
            except Exception:
                pass

    def get_named_actor(self, name: str, namespace: str = "default") -> ClusterActorHandle:
        info = self.gcs.call(
            "get_named_actor", {"name": name, "namespace": namespace}
        )
        if info is None or info["state"] == "DEAD":
            raise ValueError(f"no live actor named {name!r}")
        return ClusterActorHandle(info["actor_id"], self, desc=name)

    def kill_actor(self, actor_id: bytes) -> None:
        self._forget_actor_addr(actor_id)
        info = self.gcs.call("get_actor", {"actor_id": actor_id})
        if info and info["worker_addr"]:
            try:
                self.pool.get(tuple(info["worker_addr"])).call(
                    "destroy_actor", {"actor_id": actor_id}, timeout=5
                )
            except (RpcError, RemoteError):
                pass
        self.gcs.call(
            "update_actor", {"actor_id": actor_id, "state": "DEAD"}
        )
        # release the backing lease on the daemon that GRANTED it — the
        # GCS entry is authoritative (it tracks restarts onto new nodes;
        # a locally cached grant would go stale after the first restart)
        if info and info.get("lease_id") and info.get("node_addr"):
            try:
                self.pool.get(tuple(info["node_addr"])).call(
                    "release_lease",
                    {"lease_id": info["lease_id"], "kill": True},
                    timeout=5,
                )
            except (RpcError, RemoteError):
                pass

    # -- placement groups -----------------------------------------------------

    def create_placement_group(
        self, bundles: list, strategy: str = "PACK", name: Optional[str] = None,
        timeout: float = 30.0,
    ) -> dict:
        pg_id = _new_id()
        deadline = time.monotonic() + timeout
        info = self.gcs.call(
            "create_pg",
            {"pg_id": pg_id, "bundles": bundles, "strategy": strategy, "name": name},
        )
        while info["state"] not in ("CREATED",):
            if time.monotonic() > deadline:
                raise TimeoutError(f"placement group not placed: {info['state']}")
            time.sleep(0.05)
            info = self.gcs.call("get_pg", {"pg_id": pg_id})
        # reserve the bundles on their nodes
        nodes = {n["node_id"]: tuple(n["addr"]) for n in self.gcs.call("list_nodes", None)}
        for i, b in enumerate(info["bundles"]):
            addr = nodes[b["node_id"]]
            r = self.pool.get(addr).call(
                "reserve_pg_bundle",
                {"pg_id": pg_id, "bundle_index": i, "resources": b["resources"]},
            )
            if not r.get("ok"):
                raise RuntimeError(
                    f"bundle {i} reservation failed on {b['node_id']}: {r}"
                )
        return info

    def remove_placement_group(self, pg_id: bytes) -> None:
        nodes = {n["node_id"]: tuple(n["addr"]) for n in self.gcs.call("list_nodes", None)}
        info = self.gcs.call("get_pg", {"pg_id": pg_id})
        if info:
            for b in info["bundles"]:
                addr = nodes.get(b["node_id"])
                if addr:
                    try:
                        self.pool.get(addr).call(
                            "release_pg_all", {"pg_id": pg_id}, timeout=5
                        )
                    except (RpcError, RemoteError):
                        pass
        self.gcs.call("remove_pg", {"pg_id": pg_id})

    # -- cluster state --------------------------------------------------------

    def nodes(self) -> list:
        return self.gcs.call("list_nodes", None)

    def cluster_resources(self) -> dict:
        total: dict[str, float] = {}
        for n in self.nodes():
            if n["alive"]:
                for k, v in n["resources"].items():
                    total[k] = total.get(k, 0.0) + v
        return total
