"""Cluster value serialization: cloudpickle + persistent-id object refs.

Reference analog: python/ray/_private/serialization.py
(SerializationContext) — ObjectRefs embedded anywhere in a value travel
as persistent ids and are re-materialized through the deserializer's
resolver (the daemon fetch path), so values never need the refs inlined
at submission time.
"""

from __future__ import annotations

import io
import pickle
from typing import Any

import cloudpickle


class _ErrorValue:
    """Stored under a return id when a task failed; get() re-raises."""

    def __init__(self, exc: BaseException, tb: str, task_desc: str):
        self.exc = exc
        self.tb = tb
        self.task_desc = task_desc


def dumps_value(value: Any, collect_refs=None) -> bytes:
    """Pickle a value, turning embedded cluster refs into persistent ids.

    `collect_refs(object_id)` is called for every embedded ref — the
    submit path uses it to pin argument objects until the task finishes
    (a slim slice of the reference's ReferenceCounter "submitted task
    references", reference_count.h:66)."""
    from ray_tpu.cluster.client import ClusterObjectRef

    buf = io.BytesIO()

    class _P(cloudpickle.CloudPickler):
        def persistent_id(self, o):
            if isinstance(o, ClusterObjectRef):
                if collect_refs is not None:
                    collect_refs(o.id)
                return ("objref", o.id)
            return None

    _P(buf, protocol=5).dump(value)
    return buf.getvalue()


def loads_value(data: bytes, resolver) -> Any:
    """Unpickle, materializing ("objref", id) through `resolver(id)`."""

    class _U(pickle.Unpickler):
        def persistent_load(self, pid):
            kind, oid = pid
            if kind == "objref":
                return resolver(oid)
            raise pickle.UnpicklingError(f"unknown pid {kind!r}")

    return _U(io.BytesIO(data)).load()
