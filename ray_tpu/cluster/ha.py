"""Control-plane HA: warm-standby GCS with lease-based fenced failover.

Reference analog: GCS fault tolerance in the Ray survey's L0 lesson —
GCS availability IS cluster availability. The r13 work made the control
plane *restart*-tolerant (write-ahead ack + reconcile-on-restart), but a
KILL_GCS was still a full blackout until the dead process came back.
This module removes the restart from the critical path: a warm standby
tails the primary's replication log (gcs_service.py: every critical
mutation as a ``(seq, term, op, data)`` entry over ``repl_since``,
bootstrapped/resynced via ``repl_snapshot``) and promotes itself when
the primary's lease expires — a control-plane death costs a heartbeat,
not a blackout.

Split-brain safety is epoch fencing, not consensus: promotion bumps the
fencing term, every client RPC carries the highest term seen (rpc.py's
envelope + shared TermTracker), and a zombie primary that receives one
post-promotion request fences itself — late acks are discarded client-
side (StaleTermError) and late snapshot persists are rejected in
``_write_snapshot``. The promoted standby then runs the exact r13
restart-restore discipline (nodes as reconcile claims, actors pending
confirmation, the shared sweeper loop), so anything the log missed
converges through reconciliation instead of being trusted.

What is NOT replicated, deliberately: telemetry, the kvtier prefix
index, and the object directory — all freshness surfaces that the
cluster repopulates within one reporting/heartbeat interval after
failover (the same contract they have across a GCS restart). During the
promotion window (one lease timeout + one reconcile round) clients see
connect errors / NotPrimaryError and ride them out with the existing
bounded-failover backoff; nothing is lost, some calls are late.
"""

from __future__ import annotations

import argparse
import threading
import time
from typing import Optional

from ray_tpu.cluster.gcs_service import GcsService, register_metrics, start_sweeper
from ray_tpu.cluster.rpc import (
    NotPrimaryError,
    RemoteError,
    RpcClient,
    RpcError,
    RpcServer,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.cluster.ha")


class _StandbyFacade:
    """RPC handler fronting the standby's GcsService.

    Until promotion, only the replication/diagnostic plane is served;
    everything else answers ``NotPrimaryError`` so multi-endpoint
    clients fail over to the primary. After promotion the facade is a
    transparent pass-through to the (now-primary) service."""

    # methods an UNPROMOTED standby serves: the replication plane (a
    # chained standby could tail us), diagnostics, and the chaos
    # partition control hook
    _STANDBY_ALLOWED = frozenset({
        "rpc_ha_status", "rpc_repl_since", "rpc_repl_snapshot",
        "rpc_gcs_ft", "rpc_ha_partition", "rpc_telemetry_status",
        "rpc_telemetry_prometheus",
    })

    def __init__(self, server: "StandbyGcsServer"):
        self._server = server

    # explicit forwards so RpcServer._dispatch's getattr(handler, ...)
    # probes find them without tripping __getattr__'s rpc_-only guard
    def ha_term(self) -> int:
        return self._server.service.ha_term()

    def ha_fence(self, hterm: int, method: str):
        return self._server.service.ha_fence(hterm, method)

    def rpc_ha_status(self, payload, peer):
        out = self._server.service.rpc_ha_status(payload, peer)
        out.update(self._server.status_extra())
        return out

    def rpc_ha_partition(self, payload, peer):
        """Chaos control hook (PARTITION_GCS_PAIR): stop seeing the
        primary for window_s seconds, as if the pair link was cut."""
        self._server.force_partition(float((payload or {}).get("window_s", 0.0)))
        return {"ok": True}

    def _reject(self, payload, peer):
        term = self._server.service.ha_term()
        raise NotPrimaryError(
            f"standby GCS at term {term} is not serving "
            "(primary lease still valid)",
            term=term,
        )

    def __getattr__(self, name: str):
        if not name.startswith("rpc_"):
            raise AttributeError(name)
        fn = getattr(self._server.service, name)  # AttributeError propagates
        if self._server.promoted.is_set() or name in self._STANDBY_ALLOWED:
            return fn
        return self._reject


class StandbyGcsServer:
    """Warm-standby GCS process: GcsService(role="standby") + RpcServer
    + the tail/lease thread. Promotes in-place when the primary's lease
    expires; after promotion it IS the primary (same address the clients
    already hold as their second endpoint)."""

    def __init__(self, primary_addr: tuple, host: str = "127.0.0.1",
                 port: int = 0, lease_timeout_s: float = 2.0,
                 poll_wait_s: float = 1.0,
                 node_death_timeout_s: float = 5.0,
                 persist_path: Optional[str] = None):
        self.primary_addr = (primary_addr[0], int(primary_addr[1]))
        self.service = GcsService(
            node_death_timeout_s=node_death_timeout_s,
            persist_path=persist_path,
            role="standby",
        )
        self.facade = _StandbyFacade(self)
        self.rpc = RpcServer(self.facade, host=host, port=port)
        self.lease_timeout_s = float(lease_timeout_s)
        self.poll_wait_s = float(poll_wait_s)
        self.promoted = threading.Event()
        self.address: Optional[tuple] = None
        self._stop = threading.Event()
        self._tail: Optional[threading.Thread] = None
        self._sweeper: Optional[threading.Thread] = None
        self._promote_lock = threading.Lock()
        self._synced = False        # current tail position is snapshot-anchored
        self._synced_once = False   # ever installed a snapshot (promotion gate)
        self._cursor = 1
        self._last_primary_ok: Optional[float] = None
        self._partition_until = 0.0

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> tuple:
        self.address = self.rpc.start()
        # the lease clock starts NOW: a primary that never answers at all
        # still expires it, but promotion additionally requires one
        # successful snapshot sync (promoting empty tables helps nobody)
        self._last_primary_ok = time.monotonic()
        self._tail = threading.Thread(
            target=self._tail_loop, name="gcs-ha-tail", daemon=True
        )
        self._tail.start()
        return self.address

    def stop(self) -> None:
        self._stop.set()
        self.rpc.stop()
        if self._tail is not None:
            self._tail.join(timeout=5)

    # -- chaos ---------------------------------------------------------------

    def force_partition(self, window_s: float) -> None:
        """PARTITION_GCS_PAIR server side: pretend the pair link is cut
        for window_s seconds — the tail loop stops polling the primary,
        so the lease expires and promotion happens WHILE the primary is
        still alive (the split-brain the fencing term must resolve)."""
        self._partition_until = time.monotonic() + float(window_s)
        logger.warning(
            "standby partitioned from primary for %.2fs (chaos)", window_s
        )

    def status_extra(self) -> dict:
        now = time.monotonic()
        return {
            "standby_synced": self._synced,
            "primary_addr": self.primary_addr,
            "primary_silence_s": (
                now - self._last_primary_ok
                if self._last_primary_ok is not None else None
            ),
            "lease_timeout_s": self.lease_timeout_s,
        }

    # -- tail + lease ---------------------------------------------------------

    def _tail_loop(self) -> None:
        client: Optional[RpcClient] = None
        # tight: a dead-but-not-RST primary (half-open socket) must be
        # detected within the lease bound, not after a generous RPC
        # timeout — the long-poll budget plus half a lease of grace
        call_timeout = self.poll_wait_s + max(0.5, self.lease_timeout_s / 2)
        while not self._stop.is_set() and not self.promoted.is_set():
            if time.monotonic() < self._partition_until:
                if client is not None:
                    client.close()
                    client = None
                self._check_lease()
                self._stop.wait(0.05)
                continue
            try:
                if client is None or not client.connected:
                    if client is not None:
                        client.close()
                    client = RpcClient(
                        *self.primary_addr, timeout=call_timeout
                    ).connect(retries=0)
                if not self._synced:
                    r = client.call("repl_snapshot", {}, timeout=10.0)
                    self.service.repl_install_snapshot(
                        r["doc"], int(r["cursor"]), int(r["term"])
                    )
                    self._cursor = int(r["cursor"])
                    self._synced = True
                    self._synced_once = True
                    self._mark_primary_ok(lag_s=0.0)
                    logger.info(
                        "standby synced snapshot at cursor %d (term %d)",
                        self._cursor, int(r["term"]),
                    )
                    continue
                r = client.call(
                    "repl_since",
                    {"cursor": self._cursor, "wait": self.poll_wait_s},
                    timeout=call_timeout,
                )
                # the primary answered: its lease renews even on a
                # resync verdict (it is alive, we just fell behind)
                if r.get("resync"):
                    self._synced = False
                    self._mark_primary_ok(lag_s=None)
                    logger.warning(
                        "standby fell off the replication window; "
                        "re-syncing from snapshot"
                    )
                    continue
                self.service.repl_apply(r.get("entries", ()))
                self._cursor = int(r["cursor"])
                behind = int(r.get("head", 0)) - (self._cursor - 1)
                self._mark_primary_ok(lag_s=0.0 if behind <= 0 else None)
            except (RpcError, RemoteError, OSError):
                # primary unreachable: drop the connection and keep the
                # lease clock running — expiry is what promotes us
                if client is not None:
                    client.close()
                    client = None
                self._stop.wait(0.05)
            self._check_lease()
        if client is not None:
            client.close()

    def _mark_primary_ok(self, lag_s: Optional[float]) -> None:
        self._last_primary_ok = time.monotonic()
        if lag_s is not None:
            register_metrics()[0].set(lag_s)

    def _check_lease(self) -> None:
        if self.promoted.is_set() or self._stop.is_set():
            return
        last = self._last_primary_ok
        if last is None or not self._synced_once:
            return
        if time.monotonic() - last > self.lease_timeout_s:
            self._promote()

    def _promote(self) -> None:
        with self._promote_lock:
            if self.promoted.is_set():
                return
            silence = (
                time.monotonic() - self._last_primary_ok
                if self._last_primary_ok is not None else -1.0
            )
            term = self.service.promote()
            # the new primary needs the serving sweeps (health, reconcile,
            # restart, pg_reserve, persist): exactly GcsServer's loop
            self._sweeper = start_sweeper(self.service, self._stop)
            # flip the facade LAST: the first admitted client call must
            # see the bumped term and the restore-discipline tables
            self.promoted.set()
            logger.warning(
                "standby at %s promoted to primary (term %d, primary "
                "silent %.2fs > lease %.2fs)",
                self.address, term, silence, self.lease_timeout_s,
            )


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--primary", required=True,
                   help="host:port of the primary GCS to tail")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--death-timeout", type=float, default=5.0)
    p.add_argument("--lease-timeout", type=float, default=2.0,
                   help="seconds of primary silence before promotion")
    p.add_argument("--poll-wait", type=float, default=1.0,
                   help="repl_since long-poll budget per tail round")
    p.add_argument("--persist", default=None,
                   help="snapshot path for the (post-promotion) primary")
    args = p.parse_args()
    h, pr = args.primary.rsplit(":", 1)
    server = StandbyGcsServer(
        (h, int(pr)), host=args.host, port=args.port,
        lease_timeout_s=args.lease_timeout,
        poll_wait_s=args.poll_wait,
        node_death_timeout_s=args.death_timeout,
        persist_path=args.persist,
    )
    host, port = server.start()
    # same banner tag as gcs_service.main: the parent's _read_banner
    # discovers the bound port identically for both roles
    print(f"GCS_ADDRESS {host}:{port}", flush=True)
    try:
        # bounded parks only (check_timeouts): the entry thread idles in
        # slices instead of a forever-wait
        while not server._stop.wait(60.0):
            pass
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
