"""User-facing metrics: Counter / Gauge / Histogram + Prometheus export.

Reference analogs: python/ray/util/metrics.py (the user API) and the
node metrics agent pipeline (C++ opencensus -> _private/metrics_agent.py
-> Prometheus exposition). Single-host collapse: one process-wide
registry rendering Prometheus text directly (served by
ray_tpu.dashboard); no agent hop.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid
from bisect import bisect_right
from typing import Callable, Optional, Sequence

_REGISTRY_LOCK = threading.Lock()
_REGISTRY: dict[str, "Metric"] = {}

# Process-epoch id: a restarted process re-registers every counter at 0.
# Snapshots carry this id so a consumer (the ray_tpu.obs.telemetry plane)
# can tell "the counter went backwards" (impossible) from "the process
# restarted" (totals from the dead epoch are banked, the new epoch counts
# from zero — never a negative or double-counted delta).
PROCESS_EPOCH = uuid.uuid4().hex[:12]

# Monotonic per-process snapshot sequence: lets a consumer ignore a
# delayed/re-ordered snapshot without comparing wall clocks.
_SNAPSHOT_SEQ = itertools.count(1)

DEFAULT_HISTOGRAM_BOUNDARIES = [
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 50, 100,
]


def _fq(name: str) -> str:
    return name if name.startswith("ray_tpu_") else f"ray_tpu_{name}"


class Metric:
    """Base: named metric with optional tag keys; one time series per
    observed tag-value combination."""

    TYPE = "untyped"

    def __init__(
        self,
        name: str,
        description: str = "",
        tag_keys: Optional[Sequence[str]] = None,
    ):
        if not name:
            raise ValueError("metric name required")
        self.name = _fq(name)
        self.description = description
        self.tag_keys = tuple(tag_keys or ())
        self._default_tags: dict = {}
        self._series: dict[tuple, float] = {}
        self._lock = threading.Lock()
        with _REGISTRY_LOCK:
            existing = _REGISTRY.get(self.name)
            if existing is not None:
                if existing.TYPE != self.TYPE:
                    raise ValueError(
                        f"metric {self.name!r} already registered as {existing.TYPE}"
                    )
                # same name+type: SHARE storage so every instance's records
                # land in the one exported time series (silently shadowing
                # would lose the first instance's counts)
                self._series = existing._series
                self._lock = existing._lock
                if isinstance(existing, Histogram) and isinstance(self, Histogram):
                    self._buckets = existing._buckets
                    self._sums = existing._sums
                    self._counts = existing._counts
                    self.boundaries = existing.boundaries
                return
            _REGISTRY[self.name] = self

    def set_default_tags(self, tags: dict) -> "Metric":
        unknown = set(tags) - set(self.tag_keys)
        if unknown:
            raise ValueError(f"unknown tag keys: {sorted(unknown)}")
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[dict]) -> tuple:
        merged = {**self._default_tags, **(tags or {})}
        unknown = set(merged) - set(self.tag_keys)
        if unknown:
            raise ValueError(f"unknown tag keys: {sorted(unknown)}")
        return tuple(merged.get(k, "") for k in self.tag_keys)

    # subclasses implement record semantics over self._series

    def series(self) -> dict[tuple, float]:
        with self._lock:
            return dict(self._series)

    def remove_series(self, tags: Optional[dict] = None) -> None:
        """Retract one tag combination entirely. Without this, a gauge
        for a deleted entity (replica pool, reporter) keeps exporting its
        last value forever — downstream sum rollups then count phantoms."""
        k = self._key(tags)
        with self._lock:
            self._series.pop(k, None)
            if isinstance(self, Histogram):
                self._buckets.pop(k, None)
                self._sums.pop(k, None)
                self._counts.pop(k, None)


class Counter(Metric):
    TYPE = "counter"

    def inc(self, value: float = 1.0, tags: Optional[dict] = None) -> None:
        if value < 0:
            raise ValueError("counters only increase")
        k = self._key(tags)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value


class Gauge(Metric):
    TYPE = "gauge"

    def set(self, value: float, tags: Optional[dict] = None) -> None:
        with self._lock:
            self._series[self._key(tags)] = float(value)

    def inc(self, value: float = 1.0, tags: Optional[dict] = None) -> None:
        k = self._key(tags)
        with self._lock:
            self._series[k] = self._series.get(k, 0.0) + value

    def dec(self, value: float = 1.0, tags: Optional[dict] = None) -> None:
        self.inc(-value, tags)


class Histogram(Metric):
    TYPE = "histogram"

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Optional[Sequence[float]] = None,
        tag_keys: Optional[Sequence[str]] = None,
    ):
        # set BEFORE super().__init__: the base class's same-name sharing
        # branch replaces these with the registered instance's storage —
        # assigning after it would clobber the share and this instance
        # would read/write a private empty histogram
        self.boundaries = sorted(boundaries or DEFAULT_HISTOGRAM_BOUNDARIES)
        self._buckets: dict[tuple, list] = {}
        self._sums: dict[tuple, float] = {}
        self._counts: dict[tuple, int] = {}
        super().__init__(name, description, tag_keys)

    def observe(self, value: float, tags: Optional[dict] = None) -> None:
        k = self._key(tags)
        with self._lock:
            buckets = self._buckets.setdefault(k, [0] * (len(self.boundaries) + 1))
            buckets[bisect_right(self.boundaries, value)] += 1
            self._sums[k] = self._sums.get(k, 0.0) + value
            self._counts[k] = self._counts.get(k, 0) + 1

    def hist_data(self) -> dict:
        with self._lock:
            return {
                k: (list(b), self._sums.get(k, 0.0), self._counts.get(k, 0))
                for k, b in self._buckets.items()
            }


def registry_snapshot() -> list[Metric]:
    with _REGISTRY_LOCK:
        return list(_REGISTRY.values())


def snapshot_meta() -> dict:
    """Timestamp + epoch header every serialized snapshot carries.

    ``ts_monotonic`` orders snapshots from ONE process; ``ts_wall`` places
    them on the cluster timeline; ``epoch`` detects process restarts
    (counter resets); ``seq`` detects re-ordered/duplicated deliveries."""
    return {
        "epoch": PROCESS_EPOCH,
        "seq": next(_SNAPSHOT_SEQ),
        "ts_monotonic": time.monotonic(),
        "ts_wall": time.time(),
    }


def snapshot_registry(
    series_filter: Optional[Callable[[str, dict], bool]] = None,
) -> dict:
    """Serializable point-in-time snapshot of the whole registry.

    Counters ship as monotonic totals (not deltas) and histograms as full
    bucket vectors: a consumer that misses N snapshots loses freshness,
    never counts — re-sends can only be ignored (by ``seq``) or replace
    state, so drops/delays are staleness, not corruption.

    ``series_filter(name, tags_dict) -> bool`` narrows the snapshot (a
    node daemon colocated with other subsystems ships only the series it
    owns)."""
    out = snapshot_meta()
    out["metrics"] = []
    for m in registry_snapshot():
        entry: dict = {
            "name": m.name,
            "type": m.TYPE,
            "description": m.description,
            "tag_keys": list(m.tag_keys),
        }
        series: list[dict] = []
        if isinstance(m, Histogram):
            entry["boundaries"] = list(m.boundaries)
            for k, (buckets, total, count) in m.hist_data().items():
                tags = dict(zip(m.tag_keys, k))
                if series_filter is not None and not series_filter(m.name, tags):
                    continue
                series.append({
                    "tags": list(k), "buckets": list(buckets),
                    "sum": total, "count": count,
                })
        else:
            for k, v in m.series().items():
                tags = dict(zip(m.tag_keys, k))
                if series_filter is not None and not series_filter(m.name, tags):
                    continue
                series.append({"tags": list(k), "value": v})
        if series:
            entry["series"] = series
            out["metrics"].append(entry)
    return out


def clear_registry() -> None:
    """Test hook."""
    with _REGISTRY_LOCK:
        _REGISTRY.clear()


def _escape_label_value(v) -> str:
    """Prometheus exposition escaping for label values: backslash, double
    quote, and newline must be escaped or one prompt/path-derived tag
    value corrupts every line after it in the scrape."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_tags(keys: Sequence[str], vals: tuple, extra: str = "") -> str:
    # empty values are emitted explicitly (`k=""`): dropping them made a
    # series tagged {model: ""} collide with an untagged sibling series
    parts = [f'{k}="{_escape_label_value(v)}"' for k, v in zip(keys, vals)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def prometheus_text() -> str:
    """Render the whole registry in Prometheus exposition format
    (reference: metrics_agent.py's opencensus->Prometheus conversion)."""
    lines = []
    for m in registry_snapshot():
        lines.append(f"# HELP {m.name} {m.description}")
        lines.append(f"# TYPE {m.name} {m.TYPE}")
        if isinstance(m, Histogram):
            for k, (buckets, total, count) in m.hist_data().items():
                cum = 0
                for b, n in zip(m.boundaries, buckets):
                    cum += n
                    le = f'le="{b}"'
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_tags(m.tag_keys, k, le)} {cum}"
                    )
                cum += buckets[-1]
                le_inf = 'le="+Inf"'
                lines.append(
                    f"{m.name}_bucket"
                    f"{_fmt_tags(m.tag_keys, k, le_inf)} {cum}"
                )
                lines.append(f"{m.name}_sum{_fmt_tags(m.tag_keys, k)} {total}")
                lines.append(f"{m.name}_count{_fmt_tags(m.tag_keys, k)} {count}")
        else:
            for k, v in m.series().items():
                lines.append(f"{m.name}{_fmt_tags(m.tag_keys, k)} {v}")
    return "\n".join(lines) + "\n"
