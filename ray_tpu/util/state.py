"""State API: inspect live tasks, actors, objects, placement groups.

Reference analog: python/ray/util/state/ (`ray list tasks/actors/...`,
summarize, get_log) backed by GCS + agents. Single-host: read straight
from the runtime's Gcs, ObjectStore, and TaskEventBuffer.
"""

from __future__ import annotations

from dataclasses import asdict, dataclass
from typing import Optional

from ray_tpu.core import runtime as rt


@dataclass
class TaskRow:
    task_id: str
    name: str
    state: str
    kind: str
    actor_id: Optional[str]
    ts: float
    error: Optional[str]
    trace_id: Optional[str] = None  # ray_tpu.obs request trace, if any


def list_tasks(state: Optional[str] = None, limit: int = 1000) -> list[TaskRow]:
    runtime = rt.get_runtime()
    return [
        TaskRow(
            task_id=e.task_id, name=e.name, state=e.state, kind=e.kind,
            actor_id=e.actor_id, ts=e.ts, error=e.error,
            trace_id=getattr(e, "trace_id", None),
        )
        for e in runtime.task_events.tasks(state=state, limit=limit)
    ]


def list_actors(limit: int = 1000) -> list[dict]:
    runtime = rt.get_runtime()
    out = []
    for actor in runtime.gcs.list_actors()[:limit]:
        out.append(
            {
                "actor_id": str(actor.actor_id),
                "class_name": actor.cls.__name__,
                "state": actor.state,
                "name": getattr(actor, "registered_name", None),
                "num_restarts": getattr(actor, "num_restarts", 0),
            }
        )
    return out


def list_objects(limit: int = 1000) -> list[dict]:
    runtime = rt.get_runtime()
    store = runtime.object_store
    with store._lock:
        rows = [
            {
                "object_id": str(oid),
                "ready": e.ready.is_set(),
                "ref_count": e.ref_count,
                "nbytes": e.nbytes,
                "error": type(e.error).__name__ if e.error else None,
            }
            for oid, e in list(store._entries.items())[:limit]
        ]
    return rows


def list_placement_groups(limit: int = 1000) -> list[dict]:
    runtime = rt.get_runtime()
    return [
        {
            "placement_group_id": str(pg.id),
            "name": pg.name,
            "strategy": getattr(pg, "strategy", ""),
            "state": getattr(pg, "_state", "UNKNOWN"),
        }
        for pg in runtime.gcs.list_placement_groups()[:limit]
    ]


def list_nodes() -> list[dict]:
    runtime = rt.get_runtime()
    return [
        {
            "node_id": str(info.node_id),
            "resources_total": dict(info.resources.total),
            "resources_available": dict(info.resources._available),
            "alive": True,
        }
        for info in runtime.gcs.alive_nodes()
    ]


def summarize_tasks() -> dict:
    counts: dict[str, int] = {}
    for row in list_tasks(limit=100_000):
        counts[row.state] = counts.get(row.state, 0) + 1
    return counts


def timeline(filename: Optional[str] = None) -> list[dict]:
    """Chrome trace of recorded task spans (reference: ray.timeline())."""
    runtime = rt.get_runtime()
    trace = runtime.task_events.chrome_trace()
    if filename:
        import json

        with open(filename, "w") as f:
            json.dump(trace, f)
    return trace
