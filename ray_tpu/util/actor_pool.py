"""ActorPool: round-robin work distribution over a fixed set of actors.

Reference analog: python/ray/util/actor_pool.py (same API surface:
map/map_unordered/submit/get_next/get_next_unordered/has_next).
"""

from __future__ import annotations

from typing import Any, Callable, Iterable


class ActorPool:
    def __init__(self, actors: list):
        self._idle = list(actors)
        self._future_to_actor: dict = {}
        self._index_to_future: dict[int, Any] = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._pending_submits: list = []

    def submit(self, fn: Callable, value: Any) -> None:
        """fn(actor, value) -> ObjectRef."""
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = actor
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def _return_actor(self, actor) -> None:
        self._idle.append(actor)
        if self._pending_submits:
            self.submit(*self._pending_submits.pop(0))

    def get_next(self, timeout: float | None = None) -> Any:
        """Next result in submission order."""
        import ray_tpu

        if self._next_return_index >= self._next_task_index:
            raise StopIteration("no pending results")
        future = self._index_to_future[self._next_return_index]
        try:
            value = ray_tpu.get(future, timeout=timeout)
        except ray_tpu.GetTimeoutError:
            raise  # state untouched: the caller can retry the same slot
        except Exception:
            # task FAILED (completed with error): consume the slot and
            # recycle the actor, then surface the error
            del self._index_to_future[self._next_return_index]
            self._next_return_index += 1
            self._return_actor(self._future_to_actor.pop(future))
            raise
        del self._index_to_future[self._next_return_index]
        self._next_return_index += 1
        self._return_actor(self._future_to_actor.pop(future))
        return value

    def get_next_unordered(self, timeout: float | None = None) -> Any:
        """Next result in completion order."""
        import ray_tpu

        if not self._index_to_future:
            raise StopIteration("no pending results")
        ready, _ = ray_tpu.wait(
            list(self._index_to_future.values()), num_returns=1, timeout=timeout
        )
        if not ready:
            raise TimeoutError("get_next_unordered timed out")
        future = ready[0]
        for idx, f in list(self._index_to_future.items()):
            if f is future:
                del self._index_to_future[idx]
                break
        self._return_actor(self._future_to_actor.pop(future))
        return ray_tpu.get(future)

    def map(self, fn: Callable, values: Iterable) -> Iterable:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable, values: Iterable) -> Iterable:
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def pop_idle(self):
        return self._idle.pop() if self._idle else None

    def push(self, actor) -> None:
        self._return_actor(actor)
