"""ray_tpu.util: user-facing utilities (reference: python/ray/util/).

metrics (Counter/Gauge/Histogram + Prometheus), state API (list_tasks/
actors/objects/nodes, timeline), ActorPool, Queue.
"""

from ray_tpu.util.actor_pool import ActorPool
from ray_tpu.util.backoff import ExponentialBackoff
from ray_tpu.util.queue import Empty, Full, Queue

__all__ = ["ActorPool", "Empty", "ExponentialBackoff", "Full", "Queue"]
