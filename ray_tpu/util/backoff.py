"""Jittered exponential backoff — the one retry-delay policy.

Reference analog: the exponential backoff the reference sprinkles through
its RPC retry paths (src/ray/common/ray_config_def.h's
``*_retry_delay_ms`` knobs + ExponentialBackoff in gcs_rpc_client.h).
Before this helper every retry loop slept a fixed constant
(``time.sleep(0.05)`` and friends), which under a saturated daemon turns
N waiting submitters into a synchronized thundering herd: all of them
re-poll in the same tick, serialize on the server, fail together, and
sleep in phase again. Exponential growth spreads re-polls over time;
jitter decorrelates the herd; the cap bounds worst-case added latency.
"""

from __future__ import annotations

import random
import time
from typing import Optional


class ExponentialBackoff:
    """Iterative jittered-exponential delay source.

    ``next_delay()`` returns ``base * multiplier**n`` capped at ``cap``,
    scattered uniformly over ``[(1 - jitter) * d, d]`` (full-ish jitter:
    never longer than the deterministic ladder, so worst-case retry
    latency stays the un-jittered bound). A seeded ``rng`` makes the
    sequence reproducible (chaos tests); the default shares the module
    RNG."""

    def __init__(
        self,
        base: float = 0.05,
        cap: float = 2.0,
        multiplier: float = 2.0,
        jitter: float = 0.5,
        rng: Optional[random.Random] = None,
    ):
        if base <= 0:
            raise ValueError("base must be > 0")
        if cap < base:
            raise ValueError("cap must be >= base")
        if multiplier < 1.0:
            raise ValueError("multiplier must be >= 1")
        if not (0.0 <= jitter <= 1.0):
            raise ValueError("jitter must be in [0, 1]")
        self.base = base
        self.cap = cap
        self.multiplier = multiplier
        self.jitter = jitter
        self._rng = rng
        self._attempt = 0

    @property
    def attempt(self) -> int:
        return self._attempt

    def reset(self) -> None:
        self._attempt = 0

    def next_delay(self) -> float:
        d = min(self.cap, self.base * (self.multiplier ** self._attempt))
        self._attempt += 1
        if self.jitter > 0.0:
            lo = d * (1.0 - self.jitter)
            r = self._rng.random() if self._rng is not None else random.random()
            d = lo + (d - lo) * r
        return d

    def sleep(self, floor: float = 0.0) -> float:
        """Sleep the next jittered delay, never less than ``floor`` (a
        server-provided retry_after hint wins over a smaller ladder
        rung). Returns the slept duration."""
        d = max(float(floor), self.next_delay())
        time.sleep(d)
        return d
