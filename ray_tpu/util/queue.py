"""Distributed queue backed by an actor.

Reference analog: python/ray/util/queue.py (Queue wrapping an _QueueActor;
Empty/Full re-exported with the same semantics).
"""

from __future__ import annotations

import asyncio
from typing import Any, Optional


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int = 0):
        self._q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)

    async def put(self, item, timeout: Optional[float] = None) -> bool:
        try:
            await asyncio.wait_for(self._q.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    def put_nowait(self, item) -> bool:
        try:
            self._q.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    async def get(self, timeout: Optional[float] = None):
        try:
            return True, await asyncio.wait_for(self._q.get(), timeout)
        except asyncio.TimeoutError:
            return False, None

    def get_nowait(self):
        try:
            return True, self._q.get_nowait()
        except asyncio.QueueEmpty:
            return False, None

    def qsize(self) -> int:
        return self._q.qsize()

    def empty(self) -> bool:
        return self._q.empty()

    def full(self) -> bool:
        return self._q.full()


class Queue:
    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        import ray_tpu

        opts = dict(actor_options or {"num_cpus": 0})
        # the queue actor must serve get() while a put() blocks on a full
        # queue (and vice versa) — concurrency 1 would deadlock both sides
        opts.setdefault("max_concurrency", 1000)
        self.actor = ray_tpu.remote(_QueueActor).options(**opts).remote(maxsize)

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None) -> None:
        import ray_tpu

        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full("queue is full")
            return
        if not ray_tpu.get(self.actor.put.remote(item, timeout)):
            raise Full(f"put timed out after {timeout}s")

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        import ray_tpu

        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty("queue is empty")
            return item
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty(f"get timed out after {timeout}s")
        return item

    def put_nowait(self, item: Any) -> None:
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def qsize(self) -> int:
        import ray_tpu

        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        import ray_tpu

        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self) -> None:
        import ray_tpu

        ray_tpu.kill(self.actor)
