"""Pallas flash attention for TPU: tiled online-softmax, custom VJP.

The reference has no attention kernels of its own — it delegates model
execution to vLLM/torch inside workers (python/ray/llm/_internal/serve/
deployments/llm/vllm/vllm_engine.py); SURVEY §5.7 assigns the TPU
flash/ragged lineage to this framework. Design:

 * every kernel is fully blocked: the grid walks (batch, head, q-block,
   kv-block) and VMEM holds only [block, head_dim] tiles plus fp32
   scratch carries, so VMEM use is independent of sequence length
   (a full-sequence [S, D] residency OOMs scoped VMEM at S=8k);
 * forward: online-softmax recurrence (running max `m`, normalizer
   `l`, fp32 accumulator) carried in scratch across the kv-block grid
   dim; the output block is revisited and written once per q-block;
 * causal: off-diagonal programs skip their compute via pl.when (the
   block fetch still happens — compute, not bandwidth, dominates);
 * GQA folds naturally: kv BlockSpec index maps divide the q-head
   index by the group size;
 * backward: dQ accumulates over kv blocks; dK/dV accumulate over
   (q-heads in the group x q-blocks) with the grid ordered so the
   kv-block output is revisited until the group finishes — the
   standard flash-2 recomputation from the stored log-sum-exp;
 * segment ids (packed sequences) and right-padding are handled by
   masking; fully-masked rows produce zeros (matching xla_attention);
 * off-TPU the same kernels run under the Pallas interpreter, so CPU
   tests exercise the real code path.

TPU layout notes: Mosaic requires each block's last two dims to be
tile-aligned (8x128) or span the full array, so per-row scalars ride in
TPU-friendly shapes — q segments [B, Sq, 1], kv segments [B, 1, Sk],
log-sum-exp and delta [B, H, Sq, 1].
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.ad_checkpoint
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# v5e-tuned (round-5 sweep, benchmarks/flash_tune.py at B=8/H=16/KVH=8/
# D=64). Two structural facts drive the defaults:
#  * the FUSED backward (whole kv sequence in one block, nk == 1) beats
#    the two-kernel path at every sequence length once sub-tiling gives
#    it back block-causal skipping: S=2048 7.06ms vs 8.74, S=4096
#    12.4 vs 14.5 (fwd+bwd per layer; XLA attention 24.4 / 47.0);
#  * VMEM bounds the fused block: dk/dv fp32 scratch is block_k*D*8
#    bytes, so block_k caps at 4096 (S=8192: bk=4096 27.8ms, bk=8192
#    fails to compile).
DEFAULT_BLOCK_Q = 512
MAX_BLOCK_K = 4096  # fused whole-sequence kv block, VMEM-capped
NEG_INF = -1e30  # true -inf breeds NaN via (-inf) - (-inf)


def _fold_rows_cap(block_k: int) -> int:
    """VMEM-safe rows-per-program for a given kv block (measured: rows
    1024 compiles at bk<=2048, only 512 at bk=4096)."""
    return 1024 if block_k <= 2048 else 512


def _fold_factor(group: int, block_q: int, block_k: int,
                 override: Optional[int]) -> int:
    """GQA head folding: process F q-heads sharing one kv head in ONE
    program, stacked along the row (sublane) dim — the kv tile is
    fetched once per group instead of once per q-head, and at head_dim
    64 a lone [Bq, 64] tile wastes half the 128-lane width. F is the
    largest divisor of `group` keeping F*block_q inside the VMEM-safe
    row cap (fold=2 at S>=2048 measured 0.9-1.5ms/layer faster)."""
    cap = _fold_rows_cap(block_k)
    if override is not None:
        if group % override != 0:
            raise ValueError(f"fold_heads {override} must divide group {group}")
        if override * block_q > cap:
            raise ValueError(
                f"fold_heads {override} x block_q {block_q} = "
                f"{override * block_q} rows exceeds the VMEM-safe cap {cap} "
                f"at block_k {block_k} (measured Mosaic compile limit)"
            )
        return override
    f = 1
    for cand in range(1, group + 1):
        if group % cand == 0 and cand * block_q <= cap:
            f = cand
    return f


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# forward kernel: grid (B, H, nq, nk), kv-block fastest
# ---------------------------------------------------------------------------


def _block_mask(i, k_base, Bq, Tk, *, causal, q_offset, sq_valid, sk_valid,
                has_segments, kpad, qpad, qseg_ref, kseg):
    """[Bq, Tk] validity mask for q-block i vs kv positions starting at
    k_base, or None.

    Every term depends only on the position WITHIN the q block, so with
    head folding the folded [F*Bq, Tk] tile reuses one [Bq, Tk] mask
    broadcast across the F stacked heads. Terms are STATICALLY gated:
    each skipped term saves VPU passes over the tile and the kernel is
    VPU-bound — on the common path (causal, no packing, no pad) only
    the triangle compare survives.
    """
    mask = None
    if causal or kpad:
        k_pos = k_base + jax.lax.broadcasted_iota(jnp.int32, (1, Tk), 1)
    if causal or qpad:
        q_pos = (
            q_offset + i * Bq
            + jax.lax.broadcasted_iota(jnp.int32, (Bq, 1), 0)
        )
    if kpad:
        mask = k_pos < sk_valid
    if qpad:
        qm = q_pos - q_offset < sq_valid
        mask = qm if mask is None else mask & qm
    if causal:
        cm = q_pos >= k_pos
        mask = cm if mask is None else mask & cm
    if has_segments:
        sm = qseg_ref[0] == kseg  # [Bq,1] == [1,Tk]
        mask = sm if mask is None else mask & sm
    return mask


def _expand_mask(mask, F, Bq, Bk):
    """Tile a [Bq, Bk] mask across the F folded heads -> [F*Bq, Bk]."""
    if mask is None or F == 1:
        return mask
    return jnp.broadcast_to(mask[None], (F, Bq, Bk)).reshape(F * Bq, Bk)


def _fwd_kernel(
    q_ref,      # [1, F, Bq, D]  (F q-heads sharing this kv head)
    k_ref,      # [1, 1, Bk, D]
    v_ref,      # [1, 1, Bk, D]
    qseg_ref,   # [1, Bq, 1]
    kseg_ref,   # [1, 1, Bk]
    o_ref,      # [1, F, Bq, D]   (revisited across kv blocks)
    lse_ref,    # [1, F, Bq, 1]
    m_scr,      # [F*Bq, 1] fp32
    l_scr,      # [F*Bq, 1] fp32
    acc_scr,    # [F*Bq, D] fp32
    *,
    scale: float,
    causal: bool,
    q_offset: int,
    sk_valid: int,
    has_segments: bool,
    kpad: bool,
    sub_k: int = 512,
):
    i = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)
    F, Bq, D = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    Bk = k_ref.shape[2]
    rows = F * Bq
    Tk = sub_k if Bk % sub_k == 0 else Bk  # sub-tiles must cover Bk exactly
    nt = Bk // Tk

    @pl.when(j == 0)
    def _():
        m_scr[...] = jnp.full_like(m_scr, NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # The kv block is walked in sub-tiles of Tk with a PER-SUB-TILE
    # causal skip: with the whole kv sequence in one block (the layout
    # the fused backward wants), block-level skipping can't act and
    # ~half the softmax VPU work lands on masked entries — sub-tiling
    # restores causal-proportional cost while keeping nk == 1.
    def tile(t: int):
        lo = t * Tk
        k_base = j * Bk + lo
        run = True
        if causal:
            run = q_offset + (i + 1) * Bq - 1 >= k_base

        def body():
            # matmuls stay in the INPUT dtype (bf16 on the training path)
            # with fp32 ACCUMULATION: a v5e MXU runs bf16xbf16->f32 at full
            # rate but f32xf32 several times slower — upcasting operands
            # here was the single biggest flash-vs-XLA perf gap. Softmax
            # math stays fp32.
            q = q_ref[0].reshape(rows, D)  # folded heads stacked along rows
            k = k_ref[0, 0, lo:lo + Tk]
            v = v_ref[0, 0, lo:lo + Tk]
            s = jax.lax.dot_general(
                q, k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [rows, Tk] fp32
            if scale != 1.0:  # hot path pre-scales q; kernel mul only if not
                s = s * scale
            mask = _expand_mask(
                _block_mask(i, k_base, Bq, Tk, causal=causal,
                            q_offset=q_offset, sq_valid=0, sk_valid=sk_valid,
                            has_segments=has_segments, kpad=kpad, qpad=False,
                            qseg_ref=qseg_ref,
                            kseg=kseg_ref[0, :, lo:lo + Tk]),
                F, Bq, Tk,
            )
            if mask is not None:
                s = jnp.where(mask, s, NEG_INF)

            m_prev = m_scr[...]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)  # masked entries: exp(NEG_INF - m) == 0
            alpha = jnp.exp(m_prev - m_new)
            m_scr[...] = m_new
            l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
                p.astype(v.dtype), v,
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )

        pl.when(run)(body)

    for t in range(nt):
        tile(t)

    @pl.when(j == nk - 1)
    def _():
        l = l_scr[...]
        safe_l = jnp.where(l == 0.0, 1.0, l)  # fully-masked rows -> zeros
        o_ref[0] = (acc_scr[...] / safe_l).astype(o_ref.dtype).reshape(F, Bq, D)
        # fully-masked rows end with m ~= NEG_INF (and rows no tile ever
        # ran keep l == 0, m == NEG_INF), so lse lands at ~NEG_INF either
        # way — the "weigh nothing" value ring attention's blockwise
        # (o, lse) merge requires
        lse_ref[0] = (m_scr[...] + jnp.log(safe_l)).reshape(F, Bq, 1)


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _dq_kernel(
    q_ref, k_ref, v_ref, qseg_ref, kseg_ref, do_ref, lse_ref, delta_ref,
    dq_ref,     # [1, F, Bq, D] (revisited across kv blocks)
    dq_scr,     # [F*Bq, D] fp32
    *,
    scale: float,
    causal: bool,
    q_offset: int,
    sk_valid: int,
    has_segments: bool,
    kpad: bool,
):
    i = pl.program_id(2)
    j = pl.program_id(3)
    nk = pl.num_programs(3)
    F, Bq, D = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    Bk = k_ref.shape[2]
    rows = F * Bq

    @pl.when(j == 0)
    def _():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    run = True
    if causal:
        run = q_offset + (i + 1) * Bq - 1 >= j * Bk

    @pl.when(run)
    def _():
        q = q_ref[0].reshape(rows, D)
        do = do_ref[0].reshape(rows, D)
        lse = lse_ref[0].reshape(rows, 1)
        delta = delta_ref[0].reshape(rows, 1)
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        # input-dtype matmuls, fp32 accumulation (see _fwd_kernel note)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if scale != 1.0:
            s = s * scale
        # explicit where: exp(s - lse) is garbage on fully-masked rows
        p = jnp.exp(s - lse)
        mask = _expand_mask(
            _block_mask(i, j * Bk, Bq, Bk, causal=causal,
                        q_offset=q_offset, sq_valid=0, sk_valid=sk_valid,
                        has_segments=has_segments, kpad=kpad, qpad=False,
                        qseg_ref=qseg_ref, kseg=kseg_ref[0]),
            F, Bq, Bk,
        )
        if mask is not None:
            p = jnp.where(mask, p, 0.0)  # [rows, Bk]
        dp = jax.lax.dot_general(
            do, v,
            (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta)
        if scale != 1.0:
            ds = ds * scale
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k,
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == nk - 1)
    def _():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype).reshape(F, Bq, D)


def _dkv_kernel(
    q_ref,      # [1, F, Bq, D]
    k_ref,      # [1, 1, Bk, D]  (resident across the h-group and q blocks)
    v_ref,      # [1, 1, Bk, D]
    qseg_ref,   # [1, Bq, 1]
    kseg_ref,   # [1, 1, Bk]
    do_ref,     # [1, F, Bq, D]
    lse_ref,    # [1, F, Bq, 1]
    delta_ref,  # [1, F, Bq, 1]
    dk_ref,     # [1, 1, Bk, D]  (revisited: written once per kv block)
    dv_ref,
    dk_scr,     # [Bk, D] fp32
    dv_scr,
    *,
    scale: float,
    causal: bool,
    q_offset: int,
    sq_valid: int,
    sk_valid: int,
    group: int,  # head-group PROGRAMS per kv head = G // F
    has_segments: bool,
    kpad: bool,
    qpad: bool,
    fused_dq: bool = False,
    dq_ref=None,  # fused mode only: [1, F, Bq, D], written per (h, i)
    dq_scr=None,  # fused mode only: [F*Bq, D] fp32 (sub-tile accumulator)
    sub_k: int = 512,
):
    # grid (B, nk, H/F, nq): q-blocks fastest, then the head groups
    # sharing this kv head; scratch accumulates until both inner dims
    # finish. With folding the F q-heads of a group ride ONE program
    # stacked along rows — the p^T@do / ds^T@q contractions then sum
    # over the group for free. In FUSED mode (nk == 1, the whole kv
    # sequence in one block) this kernel also emits dq — a q-block's dq
    # needs no cross-j accumulation then, which deletes the separate dq
    # kernel's full s/p/dp recompute. Like the forward, the kv block is
    # walked in causally-skipped sub-tiles (see _fwd_kernel).
    jk = pl.program_id(1)
    h = pl.program_id(2)
    i = pl.program_id(3)
    nq = pl.num_programs(3)
    F, Bq, D = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    Bk = k_ref.shape[2]
    rows = F * Bq
    Tk = sub_k if Bk % sub_k == 0 else Bk
    nt = Bk // Tk

    @pl.when((h % group == 0) & (i == 0))
    def _():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    if fused_dq:
        dq_scr[...] = jnp.zeros_like(dq_scr)  # every program owns its dq

    def tile(t: int):
        lo = t * Tk
        k_base = jk * Bk + lo
        run = True
        if causal:
            run = q_offset + (i + 1) * Bq - 1 >= k_base

        def body():
            # input-dtype matmuls, fp32 accumulation (see _fwd_kernel note)
            k = k_ref[0, 0, lo:lo + Tk]
            v = v_ref[0, 0, lo:lo + Tk]
            q = q_ref[0].reshape(rows, D)
            do = do_ref[0].reshape(rows, D)
            lse = lse_ref[0].reshape(rows, 1)
            delta = delta_ref[0].reshape(rows, 1)
            s = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [rows, Tk]
            if scale != 1.0:
                s = s * scale
            p = jnp.exp(s - lse)
            mask = _expand_mask(
                _block_mask(i, k_base, Bq, Tk, causal=causal,
                            q_offset=q_offset, sq_valid=sq_valid,
                            sk_valid=sk_valid, has_segments=has_segments,
                            kpad=kpad, qpad=qpad, qseg_ref=qseg_ref,
                            kseg=kseg_ref[0, :, lo:lo + Tk]),
                F, Bq, Tk,
            )
            if mask is not None:
                p = jnp.where(mask, p, 0.0)
            dv_scr[lo:lo + Tk] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [Tk, D]
            dp = jax.lax.dot_general(
                do, v, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [rows, Tk]
            ds = p * (dp - delta)
            if scale != 1.0:
                ds = ds * scale
            dk_scr[lo:lo + Tk] += jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )  # [Tk, D]
            if fused_dq:
                dq_scr[...] += jax.lax.dot_general(
                    ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )

        pl.when(run)(body)

    for t in range(nt):
        tile(t)

    if fused_dq:
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype).reshape(F, Bq, D)

    @pl.when((h % group == group - 1) & (i == nq - 1))
    def _():
        dk_ref[0, 0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[...].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# pallas_call plumbing (padded [B, H, S, D] layout)
# ---------------------------------------------------------------------------


def _fwd_call(q, k, v, qseg, kseg, scale, causal, q_offset, block_q, block_k,
              sk_valid, interpret, has_segments, fold):
    B, H, Sq_pad, D = q.shape
    _, KVH, Sk_pad, _ = k.shape
    G = H // KVH
    F = fold  # q-heads stacked per program (divides G)
    HG = H // F
    nq = Sq_pad // block_q
    nk = Sk_pad // block_k
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        q_offset=q_offset, sk_valid=sk_valid,
        has_segments=has_segments, kpad=sk_valid != Sk_pad,
    )
    return pl.pallas_call(
        kernel,
        grid=(B, HG, nq, nk),
        in_specs=[
            pl.BlockSpec((1, F, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h * F // G, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h * F // G, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, h, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, h, i, j: (b, 0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, F, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, F, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, Sq_pad, D), q.dtype),
            jax.ShapeDtypeStruct((B, H, Sq_pad, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((F * block_q, 1), jnp.float32),
            pltpu.VMEM((F * block_q, 1), jnp.float32),
            pltpu.VMEM((F * block_q, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, qseg, kseg)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, qseg_ref, kseg_ref, do_ref,
                      lse_ref, delta_ref, dq_ref, dk_ref, dv_ref,
                      dk_scr, dv_scr, dq_scr, **statics):
    """nk == 1 backward: dq needs no cross-kv-block accumulation, so the
    dkv kernel emits it too — one s/p/dp computation instead of two."""
    return _dkv_kernel(
        q_ref, k_ref, v_ref, qseg_ref, kseg_ref, do_ref, lse_ref, delta_ref,
        dk_ref, dv_ref, dk_scr, dv_scr, fused_dq=True, dq_ref=dq_ref,
        dq_scr=dq_scr, **statics,
    )


def _bwd_call(q, k, v, qseg, kseg, o, lse, do, scale, causal, q_offset,
              block_q, block_k, sq_valid, sk_valid, interpret, has_segments,
              fold, dlse=None):
    B, H, Sq_pad, D = q.shape
    _, KVH, Sk_pad, _ = k.shape
    G = H // KVH
    F = fold
    HG = H // F
    nq = Sq_pad // block_q
    nk = Sk_pad // block_k
    kpad = sk_valid != Sk_pad
    qpad = sq_valid != Sq_pad
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1, keepdims=True
    )  # [B, H, Sq_pad, 1]
    if dlse is not None:
        # lse cotangent: d s_ij += dlse_i * p_ij, i.e. ds = p*(dp - delta
        # + dlse) — folded into the delta the kernels already subtract
        delta = delta - dlse.astype(jnp.float32)

    if nk == 1:
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_fused_kernel, scale=scale, causal=causal,
                q_offset=q_offset, sq_valid=sq_valid, sk_valid=sk_valid,
                group=G // F, has_segments=has_segments, kpad=kpad, qpad=qpad,
            ),
            grid=(B, 1, HG, nq),  # q-blocks fastest, then groups per kv head
            in_specs=[
                pl.BlockSpec((1, F, block_q, D), lambda b, j, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_k, D), lambda b, j, h, i: (b, h * F // G, j, 0)),
                pl.BlockSpec((1, 1, block_k, D), lambda b, j, h, i: (b, h * F // G, j, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, j, h, i: (b, i, 0)),
                pl.BlockSpec((1, 1, block_k), lambda b, j, h, i: (b, 0, j)),
                pl.BlockSpec((1, F, block_q, D), lambda b, j, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, F, block_q, 1), lambda b, j, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, F, block_q, 1), lambda b, j, h, i: (b, h, i, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, F, block_q, D), lambda b, j, h, i: (b, h, i, 0)),
                pl.BlockSpec((1, 1, block_k, D), lambda b, j, h, i: (b, h * F // G, j, 0)),
                pl.BlockSpec((1, 1, block_k, D), lambda b, j, h, i: (b, h * F // G, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B, H, Sq_pad, D), q.dtype),
                jax.ShapeDtypeStruct((B, KVH, Sk_pad, D), k.dtype),
                jax.ShapeDtypeStruct((B, KVH, Sk_pad, D), v.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((block_k, D), jnp.float32),
                pltpu.VMEM((F * block_q, D), jnp.float32),
            ],
            interpret=interpret,
        )(q, k, v, qseg, kseg, do, lse, delta)
        return dq, dk, dv

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal,
            q_offset=q_offset, sk_valid=sk_valid,
            has_segments=has_segments, kpad=kpad,
        ),
        grid=(B, HG, nq, nk),
        in_specs=[
            pl.BlockSpec((1, F, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h * F // G, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h * F // G, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, h, i, j: (b, i, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, h, i, j: (b, 0, j)),
            pl.BlockSpec((1, F, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, F, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, F, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_specs=pl.BlockSpec(
            (1, F, block_q, D), lambda b, h, i, j: (b, h, i, 0)
        ),
        out_shape=jax.ShapeDtypeStruct((B, H, Sq_pad, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((F * block_q, D), jnp.float32)],
        interpret=interpret,
    )(q, k, v, qseg, kseg, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal,
            q_offset=q_offset, sq_valid=sq_valid, sk_valid=sk_valid,
            group=G // F, has_segments=has_segments, kpad=kpad, qpad=qpad,
        ),
        grid=(B, nk, HG, nq),  # q-blocks fastest, then groups per kv head
        in_specs=[
            pl.BlockSpec((1, F, block_q, D), lambda b, j, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, j, h, i: (b, h * F // G, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, j, h, i: (b, h * F // G, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, h, i: (b, i, 0)),
            pl.BlockSpec((1, 1, block_k), lambda b, j, h, i: (b, 0, j)),
            pl.BlockSpec((1, F, block_q, D), lambda b, j, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, F, block_q, 1), lambda b, j, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, F, block_q, 1), lambda b, j, h, i: (b, h, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_k, D), lambda b, j, h, i: (b, h * F // G, j, 0)),
            pl.BlockSpec((1, 1, block_k, D), lambda b, j, h, i: (b, h * F // G, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, KVH, Sk_pad, D), k.dtype),
            jax.ShapeDtypeStruct((B, KVH, Sk_pad, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, qseg, kseg, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom VJP (statics leading, per custom_vjp nondiff rules)
# ---------------------------------------------------------------------------


# ONE custom-vjp pair serves both public forms: flash_attention with
# return_lse=False simply drops the lse output (its cotangent arrives
# as zeros and `delta - 0` is a no-op in the backward).
@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4, 5, 6, 7, 8, 9))
def _flash_lse(scale, causal, q_offset, block_q, block_k, sq_valid, sk_valid,
               interpret, has_segments, fold, q, k, v, qseg, kseg):
    """(o, lse) with a DIFFERENTIABLE lse — ring attention merges
    per-block results through lse, so its cotangent must reach ds."""
    (o, lse), _ = _flash_lse_fwd(
        scale, causal, q_offset, block_q, block_k, sq_valid, sk_valid,
        interpret, has_segments, fold, q, k, v, qseg, kseg,
    )
    return o, lse


def _flash_lse_fwd(scale, causal, q_offset, block_q, block_k, sq_valid,
                   sk_valid, interpret, has_segments, fold, q, k, v, qseg,
                   kseg):
    o, lse = _fwd_call(q, k, v, qseg, kseg, scale, causal, q_offset,
                       block_q, block_k, sk_valid, interpret, has_segments,
                       fold)
    # named residuals: under jax.checkpoint, the backward re-runs this
    # whole kernel just to rebuild (o, lse) unless the remat policy can
    # SAVE them — the "dots" policy recognizes dot_general outputs, not a
    # pallas_call's (llama.py pairs this with save_only_these_names)
    o = jax.ad_checkpoint.checkpoint_name(o, "attn_out")
    lse = jax.ad_checkpoint.checkpoint_name(lse, "attn_lse")
    return (o, lse), (q, k, v, qseg, kseg, o, lse)


def _flash_lse_bwd(scale, causal, q_offset, block_q, block_k, sq_valid,
                   sk_valid, interpret, has_segments, fold, residuals, cts):
    do, dlse = cts
    q, k, v, qseg, kseg, o, lse = residuals
    dq, dk, dv = _bwd_call(q, k, v, qseg, kseg, o, lse, do, scale, causal,
                           q_offset, block_q, block_k, sq_valid, sk_valid,
                           interpret, has_segments, fold, dlse=dlse)
    zero_seg = np.zeros(qseg.shape, dtype=jax.dtypes.float0)
    zero_kseg = np.zeros(kseg.shape, dtype=jax.dtypes.float0)
    return dq, dk, dv, zero_seg, zero_kseg


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------


def flash_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, KVH, D]
    v: jax.Array,  # [B, Sk, KVH, D]
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,  # [B, S] (requires Sq == Sk)
    kv_segment_ids: Optional[jax.Array] = None,  # [B, Sk] (k/v side override)
    q_offset: int | jax.Array = 0,
    softmax_scale: Optional[float] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: Optional[int] = None,  # None = fused whole-sequence (VMEM-capped)
    interpret: Optional[bool] = None,
    fold_heads: Optional[int] = None,  # None = auto (largest safe divisor of G)
    return_lse: bool = False,
) -> "jax.Array | tuple[jax.Array, jax.Array]":
    """Drop-in for ops.attention.xla_attention with O(S) memory.

    kv_segment_ids: when the k/v block carries DIFFERENT segments than q
    (ring attention's rotating kv shards), pass them here; segment_ids
    then applies to q only. return_lse: also return the per-row
    log-sum-exp [B, Sq, H] (differentiable) — the merge quantity for
    blockwise/ring composition."""
    B, Sq, H, D = q.shape
    _, Sk, KVH, _ = k.shape
    if H % KVH != 0:
        raise ValueError(f"n_heads {H} not divisible by kv heads {KVH}")
    if not isinstance(q_offset, int):
        raise ValueError(
            "flash_attention requires a static int q_offset (traced offsets "
            "belong to the paged decode path, ops/paged_attention.py)"
        )
    if segment_ids is not None and kv_segment_ids is None and Sq != Sk:
        raise ValueError("segment_ids requires Sq == Sk "
                         "(or pass kv_segment_ids separately)")
    if kv_segment_ids is not None and segment_ids is None and Sq != Sk:
        raise ValueError(
            "kv_segment_ids with Sq != Sk needs an explicit q-side "
            "segment_ids (the kv array cannot stand in for it)"
        )
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # pad sequence dims to block multiples (sublane-aligned blocks for
    # short test sequences). Default kv block = the whole padded
    # sequence up to MAX_BLOCK_K: nk == 1 selects the fused backward,
    # and in-kernel sub-tiling keeps causal skipping and VMEM bounded.
    if block_k is None:
        block_k = MAX_BLOCK_K
    bq = min(block_q, _round_up(Sq, 16))
    bk = min(block_k, _round_up(Sk, 16))
    Sq_pad = _round_up(Sq, bq)
    Sk_pad = _round_up(Sk, bk)

    # Fold the softmax scale into q OUTSIDE the custom-vjp boundary: the
    # kernels then skip the [rows, Bk] scale multiplies (one in fwd, two
    # in bwd — they're VPU-bound), and the chain rule through this mul
    # restores dq's scale automatically. fp32 mul, then back to input
    # dtype (for D a power of 4 the scale is a power of two and exact).
    q = (q.astype(jnp.float32) * scale).astype(q.dtype)
    kernel_scale = 1.0

    # [B, S, H, D] -> [B, H, S, D]
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    if Sq_pad != Sq:
        qt = jnp.pad(qt, ((0, 0), (0, 0), (0, Sq_pad - Sq), (0, 0)))
    if Sk_pad != Sk:
        kt = jnp.pad(kt, ((0, 0), (0, 0), (0, Sk_pad - Sk), (0, 0)))
        vt = jnp.pad(vt, ((0, 0), (0, 0), (0, Sk_pad - Sk), (0, 0)))

    has_segments = segment_ids is not None or kv_segment_ids is not None
    if not has_segments:
        qseg2 = jnp.zeros((B, Sq_pad), jnp.int32)
        kseg2 = jnp.zeros((B, Sk_pad), jnp.int32)
    else:
        q_side = segment_ids if segment_ids is not None else kv_segment_ids
        k_side = kv_segment_ids if kv_segment_ids is not None else segment_ids
        # padding gets segment -1: never equal to a real segment, so
        # padded kv rows mask out even when the q side padding matches
        qseg2 = jnp.pad(q_side.astype(jnp.int32), ((0, 0), (0, Sq_pad - Sq)),
                        constant_values=-1)
        kseg2 = jnp.pad(k_side.astype(jnp.int32), ((0, 0), (0, Sk_pad - Sk)),
                        constant_values=-2)
    qseg = qseg2[:, :, None]   # [B, Sq_pad, 1]
    kseg = kseg2[:, None, :]   # [B, 1, Sk_pad]

    fold = _fold_factor(H // KVH, bq, bk, fold_heads)
    statics = (kernel_scale, causal, q_offset, bq, bk, Sq, Sk, interpret,
               has_segments, fold)
    o, lse = _flash_lse(*statics, qt, kt, vt, qseg, kseg)
    o = jnp.transpose(o[:, :, :Sq, :], (0, 2, 1, 3))
    if return_lse:
        lse = jnp.transpose(lse[:, :, :Sq, 0], (0, 2, 1))  # [B, Sq, H]
        return o, lse
    return o
