"""Flash attention for TPU (Pallas kernel seam).

The tiled online-softmax Pallas kernel lands with the kernels milestone;
until then this module keeps the `impl="flash"` path honest by raising a
clear error on TPU and falling back to the XLA composite elsewhere
(XLA already fuses the composite well enough for short sequences).
"""

from __future__ import annotations

from typing import Optional

import jax


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    q_offset: int | jax.Array = 0,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    from ray_tpu.ops.attention import xla_attention

    return xla_attention(
        q, k, v, causal=causal, segment_ids=segment_ids,
        q_offset=q_offset, softmax_scale=softmax_scale,
    )
