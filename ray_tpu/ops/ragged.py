"""Ragged paged attention over a flat-slot KV cache.

One kernel serving MIXED prefill+decode batches (ragged paged
attention lineage, PAPERS.md arxiv 2604.15464): queries arrive PACKED
— variable-length rows concatenated along one token axis, delimited by
`cu_q_lens` — so a batch mixing in-flight prefill chunks (q_len up to
the chunk budget) and decode rows (q_len=1) runs as ONE program with
zero per-row bucket padding. A decode-only batch is the degenerate
case (all q_len=1, T == B) and reduces to `ops/paged_attention.py`'s
cost; spec verify's all-position logits are the ragged case proper
(q_len = 1 + draft_len per row).

Two implementations, following the `ops/paged_attention.py` precedent:

 * `ragged_attention_xla` — gather + masked softmax, pure XLA.
   Portable (CPU tests), and the identity oracle: its einsum structure
   mirrors `paged_attention_xla` / `_page_attend_prefill` so the mixed
   engine path stays bitwise token-identical to the split path.
 * `ragged_attention_pallas` — Pallas kernel, one grid step per
   (kv-head, sequence, page): block-table rows + `cu_q_lens` +
   `context_lens` are scalar-prefetched (SMEM) so the pipeline DMAs
   exactly the pages each sequence needs, fp32 online softmax, GQA by
   folding query heads into the packed row axis on the host.
   `interpret=` is plumbed through like `ops/flash.py` so CPU CI
   executes the real kernel body.

Layout (see llm/kv_cache.py): k_cache/v_cache are HEAD-MAJOR
[n_kv_heads, num_slots, head_dim] PER LAYER; slot = block_id *
block_size + offset. Query row j of sequence b sits at packed index
cu_q_lens[b] + j and attends positions <= context_lens[b] - q_len_b + j
(absolute causal over its own pages).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ray_tpu.ops.paged_attention import NEG_INF


def ragged_attention_xla(
    q: jax.Array,            # [T, n_heads, head_dim] packed query rows
    k_cache: jax.Array,      # [n_kv_heads, num_slots, head_dim]
    v_cache: jax.Array,      # [n_kv_heads, num_slots, head_dim]
    block_tables: jax.Array, # [B, max_blocks] int32 block ids (padded w/ 0)
    cu_q_lens: jax.Array,    # [B+1] int32 exclusive prefix sums of q lens
    context_lens: jax.Array, # [B] int32 valid kv tokens per sequence
    *,
    block_size: int,
) -> jax.Array:              # [T, n_heads, head_dim]
    T, H, D = q.shape
    KVH = k_cache.shape[0]
    G = H // KVH  # query heads per kv head (GQA group)
    B = context_lens.shape[0]
    MB = block_tables.shape[1]
    S = MB * block_size  # padded kv length

    # packed row -> owning sequence; rows past cu_q_lens[B] are padding
    # and clip to sequence B-1 (their outputs are ignored by callers)
    t = jnp.arange(T, dtype=jnp.int32)
    seq_id = jnp.clip(
        jnp.searchsorted(cu_q_lens, t, side="right") - 1, 0, B - 1
    )
    q_lens = (cu_q_lens[1:] - cu_q_lens[:B]).astype(jnp.int32)  # [B]
    # absolute causal position of each packed query row
    q_pos = (
        context_lens[seq_id] - q_lens[seq_id] + (t - cu_q_lens[seq_id])
    )  # [T]

    # slot indices for every (sequence, position): [B, S]
    offs = jnp.arange(S, dtype=jnp.int32)
    slots = block_tables[:, offs // block_size] * block_size + offs % block_size
    k = k_cache[:, slots][:, seq_id]  # [KVH, T, S, D] (head-major cache)
    v = v_cache[:, slots][:, seq_id]

    qg = q.reshape(T, KVH, G, D).astype(jnp.float32)
    scores = jnp.einsum("thgd,htsd->thgs", qg, k.astype(jnp.float32))
    scores *= 1.0 / jnp.sqrt(D).astype(jnp.float32)
    valid = (offs[None, :] <= q_pos[:, None]) & (
        offs[None, :] < context_lens[seq_id][:, None]
    )  # [T, S]
    scores = jnp.where(valid[:, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    probs = jnp.where(jnp.isnan(probs), 0.0, probs)  # fully-masked pad rows
    out = jnp.einsum("thgs,htsd->thgd", probs, v.astype(jnp.float32))
    return out.reshape(T, H, D).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------


def _ragged_attn_kernel(
    # scalar-prefetch
    cu_q_lens_ref,     # [B+1] SMEM
    context_lens_ref,  # [B] SMEM
    block_tables_ref,  # [B, MB] SMEM
    # inputs (blocked by grid; the PIPELINE fetches this (h, b, i)'s
    # page — the page index map reads the prefetched block table, so
    # the kernel DMAs exactly the pages sequence b owns)
    q_ref,       # [1, TG_pad, D] VMEM — kv head h's packed query rows
    k_ref,       # [1, 1, block_size, D] VMEM — page bt[b, i] of kv head h
    v_ref,
    # output
    o_ref,       # [1, TG_pad, D] VMEM (revisited across the whole h slice)
    # scratch
    acc_ref,     # [MAXQ*G, D] fp32
    m_ref,       # [MAXQ*G, 128] running max
    l_ref,       # [MAXQ*G, 128] running denom
    *,
    block_size: int,
    group: int,  # G: query heads folded per kv head
):
    from jax.experimental import pallas as pl

    b = pl.program_id(1)
    i = pl.program_id(2)  # page index within this sequence
    n_pages = pl.num_programs(2)
    MQG, D = acc_ref.shape

    @pl.when((b == 0) & (i == 0))
    def _():
        # first visit of this head's output block: zero it once — the
        # per-sequence finalize below only writes its own valid rows
        o_ref[...] = jnp.zeros_like(o_ref)

    @pl.when(i == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    ctx = context_lens_ref[b]
    q_start = cu_q_lens_ref[b] * group
    q_len = cu_q_lens_ref[b + 1] - cu_q_lens_ref[b]

    # packed row r of this sequence's window is query j = r // group;
    # its absolute causal position is ctx - q_len + j
    row = jax.lax.broadcasted_iota(jnp.int32, (MQG, block_size), 0)
    row_q = row // group

    @pl.when((i * block_size < ctx) & (q_len > 0))
    def _():
        q = q_ref[0, pl.ds(q_start, MQG)].astype(jnp.float32) * (
            1.0 / (D ** 0.5)
        )  # [MQG, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [bs, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(  # [MQG, bs]
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        kv_pos = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (MQG, block_size), 1
        )
        q_pos = ctx - q_len + row_q
        ok = (kv_pos <= q_pos) & (kv_pos < ctx) & (row_q < q_len)
        s = jnp.where(ok, s, NEG_INF)

        # online softmax update
        m_prev = m_ref[:, :1]                      # [MQG, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                     # [MQG, bs]
        alpha = jnp.exp(m_prev - m_new)            # [MQG, 1]
        l_new = alpha * l_ref[:, :1] + p.sum(axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when((i == n_pages - 1) & (q_len > 0))
    def _():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        vals = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        # masked read-modify-write: this sequence's window may overlap
        # the next sequence's rows (the window is MAXQ*G wide, the
        # sequence only q_len*G) — rows past q_len keep their current
        # contents. Safe because the output block stays VMEM-resident
        # for the whole (b, i) sweep of this head.
        cur = o_ref[0, pl.ds(q_start, MQG)]
        keep = (row_q < q_len)[:, :1]  # [MQG, 1]
        o_ref[0, pl.ds(q_start, MQG)] = jnp.where(keep, vals, cur)


def ragged_attention_pallas(
    q: jax.Array,            # [T, n_heads, head_dim] packed query rows
    k_cache: jax.Array,      # [n_kv_heads, num_slots, head_dim]
    v_cache: jax.Array,
    block_tables: jax.Array, # [B, max_blocks]
    cu_q_lens: jax.Array,    # [B+1]
    context_lens: jax.Array, # [B]
    *,
    block_size: int,
    max_q_len: int,
    interpret: bool = False,
) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, H, D = q.shape
    KVH = k_cache.shape[0]
    G = H // KVH
    B = context_lens.shape[0]
    MB = block_tables.shape[1]
    num_slots = k_cache.shape[1]
    if num_slots % block_size:
        raise ValueError(
            f"cache slots {num_slots} not a multiple of block_size {block_size}"
        )
    if max_q_len < 1:
        raise ValueError(f"max_q_len must be >= 1, got {max_q_len}")
    MQG = max_q_len * G

    # GQA folded on the HOST: [T, H, D] -> [KVH, T*G, D] so sequence
    # b's rows occupy the contiguous window [cu[b]*G, cu[b+1]*G) of one
    # clean 2D MXU operand per kv head — no in-kernel reshape. The row
    # axis is over-padded by max_q_len*G extra rows so the kernel's
    # fixed-size dynamic slice q[cu[b]*G : cu[b]*G + MQG] never runs
    # off the end for the last sequence.
    qf = q.reshape(T, KVH, G, D).swapaxes(0, 1).reshape(KVH, T * G, D)
    qf = jnp.pad(qf, ((0, 0), (0, MQG), (0, 0)))
    TG_pad = qf.shape[1]

    # caches viewed pre-blocked [KVH, num_blocks, block_size, D]: each
    # grid step's index map picks page bt[b, i] straight from the
    # scalar-prefetched block table
    kp = k_cache.reshape(KVH, num_slots // block_size, block_size, D)
    vp = v_cache.reshape(KVH, num_slots // block_size, block_size, D)

    def q_index(h, b, i, cu, cl, bt):
        return (h, 0, 0)

    def page_index(h, b, i, cu, cl, bt):
        # pages past the context read page bt[b, padding]=0 and are
        # skipped in-kernel; the table is padded with block 0
        return (h, bt[b, i], 0, 0)

    # grid: kv head OUTERMOST so the output block (whose index map
    # depends only on h) stays VMEM-resident across the whole
    # (sequence, page) sweep — the per-sequence finalize is a masked
    # read-modify-write into that resident block
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(KVH, B, MB),
        in_specs=[
            pl.BlockSpec((1, TG_pad, D), q_index),
            pl.BlockSpec((1, 1, block_size, D), page_index),
            pl.BlockSpec((1, 1, block_size, D), page_index),
        ],
        out_specs=pl.BlockSpec((1, TG_pad, D), q_index),
        scratch_shapes=[
            pltpu.VMEM((MQG, D), jnp.float32),
            pltpu.VMEM((MQG, 128), jnp.float32),
            pltpu.VMEM((MQG, 128), jnp.float32),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(
            _ragged_attn_kernel, block_size=block_size, group=G
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((KVH, TG_pad, D), q.dtype),
        interpret=interpret,
    )
    out = kernel(
        cu_q_lens.astype(jnp.int32), context_lens.astype(jnp.int32),
        block_tables.astype(jnp.int32), qf, kp, vp,
    )
    # unfold the host-side GQA packing: [KVH, T*G, D] -> [T, H, D]
    out = out[:, : T * G].reshape(KVH, T, G, D).swapaxes(0, 1)
    return out.reshape(T, H, D)


def ragged_attention(
    q, k_cache, v_cache, block_tables, cu_q_lens, context_lens, *,
    block_size: int, max_q_len: int, impl: str = "auto",
):
    """impl: auto | xla | pallas | pallas_interpret.

    auto = xla everywhere, for the same reason as `paged_attention`:
    the gather + masked softmax is a dynamic-slice stream XLA pipelines
    well, while the one-page-per-program kernel's DMA overhead
    dominates at decode-heavy shapes. The Pallas kernel stays available
    for long-prefill-heavy mixes (where one sequence touches many
    pages and the XLA gather materializes [T, S, D]) and as the Mosaic
    reference; `max_q_len` is its static row-window bucket — every
    sequence's q_len must be <= max_q_len (the mixed-batch planner
    guarantees this by construction).
    """
    if impl == "auto":
        impl = "xla"
    if impl == "xla":
        return ragged_attention_xla(
            q, k_cache, v_cache, block_tables, cu_q_lens, context_lens,
            block_size=block_size,
        )
    if impl == "pallas":
        return ragged_attention_pallas(
            q, k_cache, v_cache, block_tables, cu_q_lens, context_lens,
            block_size=block_size, max_q_len=max_q_len,
        )
    if impl == "pallas_interpret":
        return ragged_attention_pallas(
            q, k_cache, v_cache, block_tables, cu_q_lens, context_lens,
            block_size=block_size, max_q_len=max_q_len, interpret=True,
        )
    raise ValueError(f"unknown ragged attention impl {impl!r}")
