"""Paged decode attention over a flat-slot KV cache.

TPU-native replacement for the paged attention the reference borrows
from vLLM's CUDA kernels (reference delegates serving to vLLM —
python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_engine.py).
Two implementations:

 * `paged_attention_xla` — gather + masked softmax, pure XLA. Portable
   (CPU tests, interpreter), and a solid TPU baseline: the gather is a
   dynamic-slice stream XLA pipelines well at decode batch sizes.
 * `paged_attention_pallas` — Pallas kernel, one grid step per (request,
   kv-head): block table rows are scalar-prefetched (SMEM) so the
   kernel DMAs exactly the pages it needs from the HBM-resident cache
   into VMEM, fp32 online softmax, GQA by grouping query heads per
   kv-head. This is the kernel shape recommended by the TPU kernel
   playbook (ragged paged attention lineage, PAPERS.md).

Layout (see llm/kv_cache.py): k_cache/v_cache are HEAD-MAJOR
[n_kv_heads, num_slots, head_dim] PER LAYER (the caller scans layers);
slot = block_id * block_size + offset. Head-major is a Mosaic
constraint: the kernel DMAs one page per kv head, and the sliced
second-minor dim (slots, sliced in block_size chunks) must be
sublane-aligned — a size-1 slice of a middle head dim is rejected.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

# finite sentinel (not -inf): a page that is entirely masked must not
# produce exp(-inf - -inf) = nan in the online-softmax update
NEG_INF = -1e30


def paged_attention_xla(
    q: jax.Array,            # [B, n_heads, head_dim]
    k_cache: jax.Array,      # [n_kv_heads, num_slots, head_dim]
    v_cache: jax.Array,      # [n_kv_heads, num_slots, head_dim]
    block_tables: jax.Array, # [B, max_blocks] int32 block ids (padded w/ 0)
    context_lens: jax.Array, # [B] int32 valid tokens per sequence
    *,
    block_size: int,
) -> jax.Array:              # [B, n_heads, head_dim]
    B, H, D = q.shape
    KVH = k_cache.shape[0]
    G = H // KVH  # query heads per kv head (GQA group)
    MB = block_tables.shape[1]
    S = MB * block_size  # padded kv length

    # slot indices for every (batch, position): [B, S]
    offs = jnp.arange(S, dtype=jnp.int32)
    slots = block_tables[:, offs // block_size] * block_size + offs % block_size

    k = k_cache[:, slots]  # [KVH, B, S, D] (head-major cache)
    v = v_cache[:, slots]
    qg = q.reshape(B, KVH, G, D).astype(jnp.float32)
    scores = jnp.einsum("bhgd,hbsd->bhgs", qg, k.astype(jnp.float32))
    scores *= 1.0 / jnp.sqrt(D).astype(jnp.float32)
    mask = offs[None, :] < context_lens[:, None]  # [B, S]
    scores = jnp.where(mask[:, None, None, :], scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgs,hbsd->bhgd", probs, v.astype(jnp.float32))
    return out.reshape(B, H, D).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------


def _paged_attn_kernel(
    # scalar-prefetch
    block_tables_ref,  # [B, MB] SMEM
    context_lens_ref,  # [B] SMEM
    # inputs (blocked by grid; the PIPELINE fetches this (b,h,i)'s page —
    # the index map reads the prefetched block table, so no manual DMA.
    # Mosaic handles sub-128 minor dims in pipelined copies where raw
    # make_async_copy slices reject them)
    q_ref,       # [1, 1, G, D] VMEM — this (b, kvh)'s query group
    k_ref,       # [1, 1, block_size, D] VMEM — page bt[b, i] of kv head h
    v_ref,
    # output
    o_ref,       # [1, 1, G, D] VMEM (revisited across pages)
    # scratch
    acc_ref,     # [G, D] fp32
    m_ref,       # [G, 128] running max
    l_ref,       # [G, 128] running denom
    *,
    block_size: int,
):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    i = pl.program_id(2)  # page index within this sequence
    n_pages = pl.num_programs(2)
    G, D = acc_ref.shape

    @pl.when(i == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    ctx = context_lens_ref[b]

    @pl.when(i * block_size < ctx)
    def _():
        q = q_ref[0, 0].astype(jnp.float32) * (1.0 / (D ** 0.5))  # [G, D]
        k = k_ref[0, 0].astype(jnp.float32)  # [bs, D]
        v = v_ref[0, 0].astype(jnp.float32)
        s = jax.lax.dot_general(  # [G, bs]
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        pos = i * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1
        )
        s = jnp.where(pos < ctx, s, NEG_INF)

        # online softmax update
        m_prev = m_ref[:, :1]                      # [G, 1]
        m_new = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)                     # [G, bs]
        alpha = jnp.exp(m_prev - m_new)            # [G, 1]
        l_new = alpha * l_ref[:, :1] + p.sum(axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(i == n_pages - 1)
    def _():
        l = l_ref[:, :1]
        safe_l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)


def paged_attention_pallas(
    q: jax.Array,            # [B, n_heads, head_dim]
    k_cache: jax.Array,      # [n_kv_heads, num_slots, head_dim]
    v_cache: jax.Array,
    block_tables: jax.Array, # [B, max_blocks]
    context_lens: jax.Array, # [B]
    *,
    block_size: int,
    interpret: bool = False,
) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, D = q.shape
    KVH = k_cache.shape[0]
    G = H // KVH
    MB = block_tables.shape[1]
    num_slots = k_cache.shape[1]
    if num_slots % block_size:
        raise ValueError(
            f"cache slots {num_slots} not a multiple of block_size {block_size}"
        )

    # [B, KVH, G, D] query layout: one grid cell per (request, kv head);
    # caches viewed pre-blocked [KVH, num_blocks, block_size, D] so each
    # grid step's index map picks page bt[b, i] straight from the
    # scalar-prefetched block table
    qg = q.reshape(B, KVH, G, D)
    kp = k_cache.reshape(KVH, num_slots // block_size, block_size, D)
    vp = v_cache.reshape(KVH, num_slots // block_size, block_size, D)

    def page_index(b, h, i, bt, cl):
        # pages past the context read page bt[b, MB-1-padding]=0 and are
        # skipped in-kernel; the table is padded with block 0
        return (h, bt[b, i], 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, KVH, MB),
        in_specs=[
            pl.BlockSpec((1, 1, G, D), lambda b, h, i, *_: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, block_size, D), page_index),
            pl.BlockSpec((1, 1, block_size, D), page_index),
        ],
        out_specs=pl.BlockSpec((1, 1, G, D), lambda b, h, i, *_: (b, h, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((G, D), jnp.float32),
            pltpu.VMEM((G, 128), jnp.float32),
            pltpu.VMEM((G, 128), jnp.float32),
        ],
    )
    kernel = pl.pallas_call(
        functools.partial(_paged_attn_kernel, block_size=block_size),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, KVH, G, D), q.dtype),
        interpret=interpret,
    )
    out = kernel(
        block_tables.astype(jnp.int32), context_lens.astype(jnp.int32),
        qg, kp, vp,
    )
    return out.reshape(B, H, D)


def paged_attention(
    q, k_cache, v_cache, block_tables, context_lens, *, block_size: int,
    impl: str = "auto",
):
    """impl: auto | xla | pallas | pallas_interpret.

    auto = xla everywhere: the gather + masked softmax is a
    dynamic-slice stream XLA pipelines well, while the one-page-per-
    program Pallas kernel issues B*KVH*MB ~2KB DMAs whose per-program
    overhead dominates at decode shapes (round-5 v5e measurements,
    B=16/D=64/bs=16: xla 16-68ms per 400M decode step vs pallas
    59-158ms at ctx 200-1000, pallas 4x worse at ctx 4080). The kernel
    stays available for shapes where page locality wins (huge MB with
    short valid prefixes) and as the Mosaic reference implementation.
    """
    if impl == "auto":
        impl = "xla"
    if impl == "xla":
        return paged_attention_xla(
            q, k_cache, v_cache, block_tables, context_lens, block_size=block_size
        )
    if impl == "pallas":
        return paged_attention_pallas(
            q, k_cache, v_cache, block_tables, context_lens, block_size=block_size
        )
    if impl == "pallas_interpret":
        return paged_attention_pallas(
            q, k_cache, v_cache, block_tables, context_lens,
            block_size=block_size, interpret=True,
        )
    raise ValueError(f"unknown paged attention impl {impl!r}")
