"""Attention ops. XLA reference implementation + dispatch seam for Pallas kernels.

Grouped-query causal attention shaped for the MXU: contractions stay as
large einsums (bf16 in, fp32 softmax/accumulate) so XLA tiles them onto
the systolic array. `attention()` is the single entry point; `impl`
selects between the XLA composite (fused adequately by XLA for moderate
sequence lengths) and the Pallas flash kernel (ray_tpu.ops.flash).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def _repeat_kv_heads(q: jax.Array, k: jax.Array) -> int:
    n_heads = q.shape[2]
    n_kv = k.shape[2]
    if n_heads % n_kv != 0:
        raise ValueError(f"n_heads {n_heads} not divisible by kv heads {n_kv}")
    return n_heads // n_kv


def xla_attention(
    q: jax.Array,  # [B, Sq, H, D]
    k: jax.Array,  # [B, Sk, K, D]
    v: jax.Array,  # [B, Sk, K, D]
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,  # [B, S] int, same for q/k when Sq==Sk
    q_offset: int | jax.Array = 0,
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """Reference GQA attention. fp32 softmax, bf16 matmuls."""
    B, Sq, H, D = q.shape
    _, Sk, K, _ = k.shape
    group = _repeat_kv_heads(q, k)
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)

    qg = q.reshape(B, Sq, K, group, D)
    scores = jnp.einsum("bqkgd,bskd->bkgqs", qg, k, preferred_element_type=jnp.float32)
    scores = scores * scale

    mask = None
    if causal:
        q_pos = jnp.arange(Sq)[:, None] + q_offset
        k_pos = jnp.arange(Sk)[None, :]
        mask = q_pos >= k_pos  # [Sq, Sk]
        mask = mask[None, None, None, :, :]
    if segment_ids is not None:
        seg = segment_ids[:, :, None] == segment_ids[:, None, :]  # [B, Sq, Sk]
        seg = seg[:, None, None, :, :]
        mask = seg if mask is None else jnp.logical_and(mask, seg)
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)

    probs = jax.nn.softmax(scores, axis=-1).astype(v.dtype)
    out = jnp.einsum("bkgqs,bskd->bqkgd", probs, v)
    return out.reshape(B, Sq, H, D)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,
    q_offset: int | jax.Array = 0,
    softmax_scale: Optional[float] = None,
    impl: str = "xla",
) -> jax.Array:
    if impl == "xla":
        return xla_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            q_offset=q_offset, softmax_scale=softmax_scale,
        )
    if impl == "flash":
        from ray_tpu.ops.flash import flash_attention

        return flash_attention(
            q, k, v, causal=causal, segment_ids=segment_ids,
            q_offset=q_offset, softmax_scale=softmax_scale,
        )
    if impl in ("ring", "ulysses"):
        # Context-parallel paths: sequence sharded over the mesh `sp` axis
        # (ray_tpu.ops.ring_attention). Mesh comes from the ambient
        # parallel_context. A missing context is an error, not a silent
        # fallback: the mesh is read at trace time and baked into the jit
        # cache, so "sometimes sharded" would pin whichever variant traced
        # first. (Enter parallel_context before tracing; sp == 1 meshes
        # degrade to the XLA composite inside ring_attention itself.)
        from ray_tpu.ops import ring_attention as ra
        from ray_tpu.parallel.context import current_mesh

        if not (isinstance(q_offset, int) and q_offset == 0):
            raise ValueError(
                f"attention(impl={impl!r}) is a full-sequence training path and "
                "does not support q_offset (decode with a KV cache uses "
                "impl='xla' or the paged kernel)"
            )
        mesh = current_mesh()
        if mesh is None:
            raise RuntimeError(
                f"attention(impl={impl!r}) needs an ambient mesh: wrap the "
                "call (before jit tracing) in "
                "ray_tpu.parallel.context.parallel_context(mesh)"
            )
        fn = ra.ring_attention if impl == "ring" else ra.ulysses_attention
        return fn(
            q, k, v, mesh=mesh, causal=causal, segment_ids=segment_ids,
            softmax_scale=softmax_scale,
        )
    raise ValueError(f"unknown attention impl {impl!r}")
