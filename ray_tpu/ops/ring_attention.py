"""Ring attention + Ulysses attention: context parallelism over the ICI ring.

The reference has no native sequence/context parallelism (SURVEY.md §5.7 —
long context is delegated to vLLM/torch inside workers). Here it is a
first-class op: sequences shard over the mesh `sp` axis and attention runs

  * **ring**: K/V blocks rotate around the `sp` axis with
    `jax.lax.ppermute` while each device accumulates blockwise
    softmax(QK^T)V online (flash-attention-style running max/sum, fp32
    accumulators). One block of K/V is in flight per step, so the
    `ppermute` rides ICI concurrently with the MXU matmuls of the
    current block — compute/communication overlap falls out of XLA's
    async collective scheduling rather than hand-written double
    buffering.
  * **ulysses**: `jax.lax.all_to_all` swaps the sharded axis from
    sequence to heads, runs ordinary full attention locally, and swaps
    back. Cheaper for moderate sequence lengths when n_heads % sp == 0.

Both are SPMD-inner functions meant to run inside `jax.shard_map`; the
`ring_attention` / `ulysses_attention` wrappers build the shard_map over
the framework mesh (batch over (dp, fsdp), heads over tp, sequence over
sp). Gradients flow through `ppermute`/`all_to_all` transposes, so the
same code paths serve training.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import NEG_INF, _repeat_kv_heads, xla_attention


def ring_attention_spmd(
    q: jax.Array,  # [B, Sq_local, H, D]  (local sequence shard)
    k: jax.Array,  # [B, Sk_local, K, D]
    v: jax.Array,  # [B, Sk_local, K, D]
    *,
    axis_name: str = "sp",
    causal: bool = True,
    kv_segment_ids: Optional[jax.Array] = None,  # [B, Sk_local]
    q_segment_ids: Optional[jax.Array] = None,  # [B, Sq_local]
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """Ring attention body. Call inside shard_map with seq sharded on axis_name.

    Sequence is assumed contiguously sharded: device i holds global
    positions [i*S_local, (i+1)*S_local). Causal masking is applied on
    global positions, so the result equals full-sequence causal attention.
    """
    if q_segment_ids is None and kv_segment_ids is not None:
        q_segment_ids = kv_segment_ids
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    group = _repeat_kv_heads(q, k)
    Kh = k.shape[2]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)

    n = jax.lax.axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    # kv arrives from the next-higher rank each step: after t rotations the
    # local buffer holds block (my + t) mod n.
    perm = [(i, (i - 1) % n) for i in range(n)]

    qg = (q * scale).reshape(B, Sq, Kh, group, D)
    q_pos = my * Sq + jnp.arange(Sq)  # global positions of local queries

    def compute_block(o, m, l, k_cur, v_cur, seg_cur, src):
        # fp32 scores for this block: [B, Kh, G, Sq, Sk]
        s = jnp.einsum(
            "bqkgd,bskd->bkgqs", qg, k_cur, preferred_element_type=jnp.float32
        )
        k_pos = src * Sk + jnp.arange(Sk)
        mask = jnp.ones((Sq, Sk), bool)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        mask = jnp.broadcast_to(mask[None, None, None], s.shape)
        if seg_cur is not None:
            seg = q_segment_ids[:, :, None] == seg_cur[:, None, :]  # [B, Sq, Sk]
            mask = jnp.logical_and(mask, seg[:, None, None, :, :])
        s = jnp.where(mask, s, NEG_INF)

        m_new = jnp.maximum(m, s.max(axis=-1))
        # exp under explicit mask: a fully-masked block must contribute 0,
        # not exp(NEG_INF - NEG_INF) = 1.
        p = jnp.where(mask, jnp.exp(s - m_new[..., None]), 0.0)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + p.sum(axis=-1)
        pv = jnp.einsum("bkgqs,bskd->bkgqd", p.astype(v_cur.dtype), v_cur)
        o_new = o * corr[..., None] + pv.astype(jnp.float32)
        return o_new, m_new, l_new

    def masked_compute(o, m, l, k_cur, v_cur, seg_cur, src):
        if not causal:
            return compute_block(o, m, l, k_cur, v_cur, seg_cur, src)
        # Blocks strictly in the future (src > my under contiguous
        # sharding) are fully masked — skip their matmuls entirely.
        # Average saving is ~2x attention FLOPs at large sp; the
        # remaining rank imbalance (rank i computes i+1 blocks) is a
        # known cost of contiguous sharding — zigzag/striped layouts
        # would balance it at the price of position bookkeeping.
        return jax.lax.cond(
            src > my,
            lambda *_: (o, m, l),
            compute_block,
            o, m, l, k_cur, v_cur, seg_cur, src,
        )

    def body(carry, t):
        o, m, l, k_cur, v_cur, seg_cur = carry
        o, m, l = masked_compute(o, m, l, k_cur, v_cur, seg_cur, (my + t) % n)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        seg_nxt = (
            jax.lax.ppermute(seg_cur, axis_name, perm) if seg_cur is not None else None
        )
        return (o, m, l, k_nxt, v_nxt, seg_nxt), None

    o0 = jnp.zeros((B, Kh, group, Sq, D), jnp.float32)
    m0 = jnp.full((B, Kh, group, Sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((B, Kh, group, Sq), jnp.float32)
    # n-1 rotations in the scan; the last block needs no onward ppermute,
    # so it is folded in as an epilogue (saves one dead KV rotation).
    (o, m, l, k_last, v_last, seg_last), _ = jax.lax.scan(
        body, (o0, m0, l0, k, v, kv_segment_ids), jnp.arange(n - 1)
    )
    o, _, l = masked_compute(o, m, l, k_last, v_last, seg_last, (my + n - 1) % n)
    o = o / jnp.where(l == 0.0, 1.0, l)[..., None]
    # [B, Kh, G, Sq, D] -> [B, Sq, H, D]
    return o.transpose(0, 3, 1, 2, 4).reshape(B, Sq, H, D).astype(q.dtype)


def ulysses_attention_spmd(
    q: jax.Array,  # [B, S_local, H, D]
    k: jax.Array,  # [B, S_local, K, D]
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,  # [B, S_local]
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """All-to-all head/sequence swap: full attention runs locally per head group."""
    n = jax.lax.axis_size(axis_name)
    H, Kh = q.shape[2], k.shape[2]
    if H % n or Kh % n:
        raise ValueError(f"ulysses needs heads ({H}/{Kh}) divisible by axis size {n}")
    a2a = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    qf, kf, vf = a2a(q), a2a(k), a2a(v)  # [B, S_full, H/n, D]
    seg_full = (
        jax.lax.all_gather(segment_ids, axis_name, axis=1, tiled=True)
        if segment_ids is not None
        else None
    )
    o = xla_attention(
        qf, kf, vf, causal=causal, segment_ids=seg_full, softmax_scale=softmax_scale
    )
    return jax.lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2, tiled=True)


def _cp_wrapper(spmd_fn, seg_kwargs):
    """Shared shard_map wrapper for both context-parallel variants.

    seg_kwargs maps one segment-ids array to the spmd fn's kwarg name(s).
    """

    def wrapper(
        q: jax.Array,  # [B, S, H, D]  (global shapes; sharding via shard_map)
        k: jax.Array,
        v: jax.Array,
        *,
        mesh: Mesh,
        axis: str = "sp",
        causal: bool = True,
        segment_ids: Optional[jax.Array] = None,
        softmax_scale: Optional[float] = None,
        batch_axes=("dp", "fsdp"),
        heads_axis: str = "tp",
    ) -> jax.Array:
        if mesh.shape[axis] == 1:
            return xla_attention(
                q, k, v, causal=causal, segment_ids=segment_ids,
                softmax_scale=softmax_scale,
            )
        qspec = P(batch_axes, axis, heads_axis, None)
        in_specs = (qspec, qspec, qspec)
        args = (q, k, v)
        if segment_ids is not None:
            in_specs += (P(batch_axes, axis),)
            args += (segment_ids,)

        def inner(q, k, v, *maybe_seg):
            kw = {name: maybe_seg[0] for name in seg_kwargs} if maybe_seg else {}
            return spmd_fn(
                q, k, v, axis_name=axis, causal=causal,
                softmax_scale=softmax_scale, **kw,
            )

        return jax.shard_map(
            inner, mesh=mesh, in_specs=in_specs, out_specs=qspec, check_vma=False
        )(*args)

    return wrapper


ring_attention = _cp_wrapper(ring_attention_spmd, ("kv_segment_ids", "q_segment_ids"))
ring_attention.__name__ = "ring_attention"
ring_attention.__doc__ = (
    'Context-parallel causal attention over mesh axis `axis` (default "sp").'
)
ulysses_attention = _cp_wrapper(ulysses_attention_spmd, ("segment_ids",))
ulysses_attention.__name__ = "ulysses_attention"
ulysses_attention.__doc__ = "All-to-all (Ulysses) context-parallel attention."
