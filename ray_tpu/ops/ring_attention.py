"""Ring attention + Ulysses attention: context parallelism over the ICI ring.

The reference has no native sequence/context parallelism (SURVEY.md §5.7 —
long context is delegated to vLLM/torch inside workers). Here it is a
first-class op: sequences shard over the mesh `sp` axis and attention runs

  * **ring**: K/V blocks rotate around the `sp` axis with
    `jax.lax.ppermute` while each device accumulates blockwise
    softmax(QK^T)V online (flash-attention-style running max/sum, fp32
    accumulators). One block of K/V is in flight per step, so the
    `ppermute` rides ICI concurrently with the MXU matmuls of the
    current block — compute/communication overlap falls out of XLA's
    async collective scheduling rather than hand-written double
    buffering.
  * **ulysses**: `jax.lax.all_to_all` swaps the sharded axis from
    sequence to heads, runs ordinary full attention locally, and swaps
    back. Cheaper for moderate sequence lengths when n_heads % sp == 0.

Both are SPMD-inner functions meant to run inside `jax.shard_map`; the
`ring_attention` / `ulysses_attention` wrappers build the shard_map over
the framework mesh (batch over (dp, fsdp), heads over tp, sequence over
sp). Gradients flow through `ppermute`/`all_to_all` transposes, so the
same code paths serve training.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.ops.attention import xla_attention
from ray_tpu.ops.flash import NEG_INF as FLASH_NEG_INF, flash_attention



def _axis_size(axis_name) -> int:
    """jax.lax.axis_size appeared after 0.4.x; psum of 1 is the classic
    spelling and resolves to the same static mesh-axis size."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)

def ring_attention_spmd(
    q: jax.Array,  # [B, Sq_local, H, D]  (local sequence shard)
    k: jax.Array,  # [B, Sk_local, K, D]
    v: jax.Array,  # [B, Sk_local, K, D]
    *,
    axis_name: str = "sp",
    causal: bool = True,
    kv_segment_ids: Optional[jax.Array] = None,  # [B, Sk_local]
    q_segment_ids: Optional[jax.Array] = None,  # [B, Sq_local]
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """Ring attention body. Call inside shard_map with seq sharded on axis_name.

    Sequence is assumed contiguously sharded: device i holds global
    positions [i*S_local, (i+1)*S_local). Causal masking is applied on
    global positions, so the result equals full-sequence causal attention.
    """
    if q_segment_ids is None and kv_segment_ids is not None:
        q_segment_ids = kv_segment_ids
    B, Sq, H, D = q.shape
    Sk = k.shape[1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(D)

    n = _axis_size(axis_name)
    my = jax.lax.axis_index(axis_name)
    # kv arrives from the next-higher rank each step: after t rotations the
    # local buffer holds block (my + t) mod n.
    perm = [(i, (i - 1) % n) for i in range(n)]

    # Per-block compute is the FLASH kernel (ops/flash.py) returning
    # (o, lse); blocks merge through log-sum-exp. The round-5 chip
    # measurement of the previous raw-XLA online-softmax body was 17x
    # slower than flash at S=4096 (benchmarks/RINGBENCH_r05.json) — the
    # ring's job is rotation + merge, the MXU work belongs in the kernel.
    def flash_block(k_cur, v_cur, seg_cur, *, block_causal: bool):
        kw = {}
        if seg_cur is not None:
            kw = {"segment_ids": q_segment_ids, "kv_segment_ids": seg_cur}
        return flash_attention(
            q, k_cur, v_cur, causal=block_causal, softmax_scale=scale,
            return_lse=True, **kw,
        )

    def compute_block(k_cur, v_cur, seg_cur, src):
        # diagonal block (src == my): causal within the block; blocks
        # strictly behind (src < my): full attention. Both are compiled;
        # the traced src picks one. (Non-causal rings are all "full".)
        if not causal:
            return flash_block(k_cur, v_cur, seg_cur, block_causal=False)
        return jax.lax.cond(
            src == my,
            lambda kc, vc: flash_block(kc, vc, seg_cur, block_causal=True),
            lambda kc, vc: flash_block(kc, vc, seg_cur, block_causal=False),
            k_cur, v_cur,
        )

    def merge(o_run, lse_run, o_t, lse_t):
        m = jnp.maximum(lse_run, lse_t)
        w1 = jnp.exp(lse_run - m)
        w2 = jnp.exp(lse_t - m)
        denom = w1 + w2
        o = (
            o_run * w1[..., None] + o_t.astype(jnp.float32) * w2[..., None]
        ) / denom[..., None]
        return o, m + jnp.log(denom)

    def masked_compute(o_run, lse_run, k_cur, v_cur, seg_cur, src):
        if causal:
            # blocks strictly in the future (src > my under contiguous
            # sharding) are fully masked — skip their matmuls entirely.
            # Average saving is ~2x attention FLOPs at large sp; the
            # remaining rank imbalance is the known cost of contiguous
            # sharding (zigzag layouts would balance it).
            def skip(*_):
                return o_run, lse_run

            def run(kc, vc):
                o_t, lse_t = compute_block(kc, vc, seg_cur, src)
                return merge(o_run, lse_run, o_t, lse_t)

            return jax.lax.cond(src > my, skip, run, k_cur, v_cur)
        o_t, lse_t = compute_block(k_cur, v_cur, seg_cur, src)
        return merge(o_run, lse_run, o_t, lse_t)

    def body(carry, t):
        o, lse, k_cur, v_cur, seg_cur = carry
        o, lse = masked_compute(o, lse, k_cur, v_cur, seg_cur, (my + t) % n)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        seg_nxt = (
            jax.lax.ppermute(seg_cur, axis_name, perm) if seg_cur is not None else None
        )
        return (o, lse, k_nxt, v_nxt, seg_nxt), None

    o0 = jnp.zeros((B, Sq, H, D), jnp.float32)
    lse0 = jnp.full((B, Sq, H), FLASH_NEG_INF, jnp.float32)
    # n-1 rotations in the scan; the last block needs no onward ppermute,
    # so it is folded in as an epilogue (saves one dead KV rotation).
    (o, lse, k_last, v_last, seg_last), _ = jax.lax.scan(
        body, (o0, lse0, k, v, kv_segment_ids), jnp.arange(n - 1)
    )
    o, _ = masked_compute(o, lse, k_last, v_last, seg_last, (my + n - 1) % n)
    return o.astype(q.dtype)


def ulysses_attention_spmd(
    q: jax.Array,  # [B, S_local, H, D]
    k: jax.Array,  # [B, S_local, K, D]
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = True,
    segment_ids: Optional[jax.Array] = None,  # [B, S_local]
    softmax_scale: Optional[float] = None,
) -> jax.Array:
    """All-to-all head/sequence swap: full attention runs locally per head group."""
    n = _axis_size(axis_name)
    H, Kh = q.shape[2], k.shape[2]
    if H % n or Kh % n:
        raise ValueError(f"ulysses needs heads ({H}/{Kh}) divisible by axis size {n}")
    a2a = functools.partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1, tiled=True
    )
    qf, kf, vf = a2a(q), a2a(k), a2a(v)  # [B, S_full, H/n, D]
    seg_full = (
        jax.lax.all_gather(segment_ids, axis_name, axis=1, tiled=True)
        if segment_ids is not None
        else None
    )
    # local full-sequence attention runs the FLASH kernel (2-3x XLA
    # attention on v5e at these shapes; ring took the same step round 5)
    o = flash_attention(
        qf, kf, vf, causal=causal, segment_ids=seg_full,
        softmax_scale=softmax_scale,
    )
    return jax.lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2, tiled=True)


def _cp_wrapper(spmd_fn, seg_kwargs):
    """Shared shard_map wrapper for both context-parallel variants.

    seg_kwargs maps one segment-ids array to the spmd fn's kwarg name(s).
    """

    def wrapper(
        q: jax.Array,  # [B, S, H, D]  (global shapes; sharding via shard_map)
        k: jax.Array,
        v: jax.Array,
        *,
        mesh: Mesh,
        axis: str = "sp",
        causal: bool = True,
        segment_ids: Optional[jax.Array] = None,
        softmax_scale: Optional[float] = None,
        batch_axes=("dp", "fsdp"),
        heads_axis: str = "tp",
    ) -> jax.Array:
        if mesh.shape[axis] == 1:
            # sp=1 degrades to the XLA composite, NOT the flash kernel:
            # this call sits OUTSIDE shard_map on global arrays, and a
            # pallas_call has no GSPMD partitioning rule — on a dp/tp
            # mesh XLA would replicate it (all-gathering the batch)
            # instead of partitioning like the composite does
            return xla_attention(
                q, k, v, causal=causal, segment_ids=segment_ids,
                softmax_scale=softmax_scale,
            )
        qspec = P(batch_axes, axis, heads_axis, None)
        in_specs = (qspec, qspec, qspec)
        args = (q, k, v)
        if segment_ids is not None:
            in_specs += (P(batch_axes, axis),)
            args += (segment_ids,)

        def inner(q, k, v, *maybe_seg):
            kw = {name: maybe_seg[0] for name in seg_kwargs} if maybe_seg else {}
            return spmd_fn(
                q, k, v, axis_name=axis, causal=causal,
                softmax_scale=softmax_scale, **kw,
            )

        from ray_tpu.parallel.sharding import shard_map_compat

        return shard_map_compat(
            inner, mesh=mesh, in_specs=in_specs, out_specs=qspec, check_vma=False
        )(*args)

    return wrapper


ring_attention = _cp_wrapper(ring_attention_spmd, ("kv_segment_ids", "q_segment_ids"))
ring_attention.__name__ = "ring_attention"
ring_attention.__doc__ = (
    'Context-parallel causal attention over mesh axis `axis` (default "sp").'
)
ulysses_attention = _cp_wrapper(ulysses_attention_spmd, ("segment_ids",))
ulysses_attention.__name__ = "ulysses_attention"
ulysses_attention.__doc__ = "All-to-all (Ulysses) context-parallel attention."
