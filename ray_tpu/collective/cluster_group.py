"""Cluster-tier (cross-process / DCN) collective group.

Reference analog: the gloo-backed collective groups the reference uses
for CPU-side gangs (python/ray/util/collective/collective_group/
gloo_collective_group.py) — host arrays moved between worker PROCESSES,
not threads. TPU-native split:

  * device arrays never come here — they ride XLA collectives over ICI
    inside jitted programs (mesh_for_group);
  * host/control arrays (metrics, broadcast weights, rendezvous
    payloads) synchronize through the GCS KV: contributions land under
    a per-round key, rank 0 reduces and publishes the result, everyone
    else long-polls it (`kv_wait`, a server-side parked read — no
    client busy-poll).

Same collective contract as the in-process `_HostGroup`: every rank
issues the same collectives in the same order.
"""

from __future__ import annotations

import pickle
from typing import Any, Optional


class ClusterGroup:
    """One per rank PROCESS (unlike _HostGroup: one shared per host).

    All instances with the same group name rendezvous through the
    attached cluster's GCS KV (`ns="__collective__"`).
    """

    NS = "__collective__"

    def __init__(self, name: str, world_size: int, rank: int, client=None):
        if client is None:
            from ray_tpu.cluster.client import _ambient_client

            try:
                client = _ambient_client()
            except RuntimeError:
                client = None
            if client is None:
                raise RuntimeError(
                    "backend='cluster' collectives need an attached cluster "
                    "(ray_tpu.init(address=...) or a cluster worker process)"
                )
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self._client = client
        self._round = 0
        self._send_seq: dict[int, int] = {}
        self._recv_seq: dict[int, int] = {}
        if rank == 0:
            client.kv_put(
                self._key("meta"), pickle.dumps({"world_size": world_size}), self.NS
            )
        else:
            meta = pickle.loads(client.kv_wait(self._key("meta"), self.NS, 60.0))
            if meta["world_size"] != world_size:
                raise ValueError(
                    f"group {name!r} exists with world_size "
                    f"{meta['world_size']} != {world_size}"
                )

    def _key(self, *parts) -> bytes:
        return "/".join((self.name,) + tuple(str(p) for p in parts)).encode()

    # -- collective rendezvous ------------------------------------------------

    def rendezvous(self, rank: int, value: Any, compute, timeout: float = 120.0):
        """Deposit value under this round; rank 0 reduces once all ranks
        landed and publishes; everyone returns the published result."""
        rnd, self._round = self._round, self._round + 1
        kv = self._client
        kv.kv_put(self._key(rnd, "c", rank), pickle.dumps(value), self.NS)
        if rank == 0:
            vals = []
            for r in range(self.world_size):
                raw = kv.kv_wait(self._key(rnd, "c", r), self.NS, timeout)
                vals.append(pickle.loads(raw))
            result = compute(vals)
            kv.kv_put(self._key(rnd, "r"), pickle.dumps(result), self.NS)
            # garbage: contributions of this round; result of the previous
            # round (published results can only be awaited by ranks that
            # already contributed to THIS round, i.e. consumed round-1)
            for r in range(self.world_size):
                kv.kv_del(self._key(rnd, "c", r), self.NS)
            if rnd > 0:
                kv.kv_del(self._key(rnd - 1, "r"), self.NS)
            return result
        raw = kv.kv_wait(self._key(rnd, "r"), self.NS, timeout)
        return pickle.loads(raw)

    # -- p2p ------------------------------------------------------------------

    def send(self, src: int, dst: int, value: Any, timeout: float = 120.0) -> None:
        seq = self._send_seq.get(dst, 0)
        self._send_seq[dst] = seq + 1
        self._client.kv_put(
            self._key("p2p", src, dst, seq), pickle.dumps(value), self.NS
        )

    def recv(self, src: int, dst: int, timeout: float = 120.0) -> Any:
        seq = self._recv_seq.get(src, 0)
        self._recv_seq[src] = seq + 1
        key = self._key("p2p", src, dst, seq)
        raw = self._client.kv_wait(key, self.NS, timeout)
        self._client.kv_del(key, self.NS)
        return pickle.loads(raw)

    def destroy(self) -> None:
        clear_group_kv(self._client, self.name)


def clear_group_kv(client, name: str) -> None:
    """Best-effort removal of a group's GCS residue (meta, unread round
    results, unclaimed p2p payloads) — shared by rank-side destroy and
    the driver-side destroy_collective_group path."""
    try:
        for key in client.gcs.call(
            "kv_keys", {"ns": ClusterGroup.NS, "prefix": name.encode() + b"/"}
        ):
            client.kv_del(key, ClusterGroup.NS)
    except Exception:  # noqa: BLE001 — cleanup must never raise
        pass
