"""Cluster-tier (cross-process / DCN) collective group.

Reference analog: the gloo-backed collective groups the reference uses
for CPU-side gangs (python/ray/util/collective/collective_group/
gloo_collective_group.py) — host arrays moved between worker PROCESSES,
not threads. TPU-native split:

  * device arrays never come here — they ride XLA collectives over ICI
    inside jitted programs (mesh_for_group);
  * host/control arrays (metrics, broadcast weights, rendezvous
    payloads) synchronize through the GCS KV: contributions land under
    a per-round key, rank 0 reduces and publishes the result, everyone
    else long-polls it (`kv_wait`, a server-side parked read — no
    client busy-poll).

Same collective contract as the in-process `_HostGroup`: every rank
issues the same collectives in the same order.

Robustness (r12): every wait is bounded by a per-op deadline shared
across the op's KV round-trips — a peer that dies or partitions
mid-rendezvous produces ``CollectiveTimeoutError`` within the timeout,
a GCS transport failure surfaces as ``CollectivePartitionError`` (the
rank's daemon may still heartbeat — only this plane is cut), and all
round/p2p keys are scoped under the gang epoch (``gen``): a zombie rank
from a superseded generation fails its generation check with
``StaleGenerationError`` and its late deposits land under old-gen keys
nobody reads.
"""

from __future__ import annotations

import pickle
import time
from typing import Any, Optional

from ray_tpu.collective.errors import (
    DEFAULT_TIMEOUT,
    CollectiveAbortedError,
    CollectiveError,
    CollectivePartitionError,
    CollectiveTimeoutError,
    StaleGenerationError,
)


def _transport_errors() -> tuple:
    """Error types that mean 'could not reach the rendezvous plane'."""
    try:
        from ray_tpu.cluster.rpc import RpcError

        return (RpcError, ConnectionError, OSError)
    except ImportError:  # pragma: no cover — cluster extra not loaded
        return (ConnectionError, OSError)


class ClusterGroup:
    """One per rank PROCESS (unlike _HostGroup: one shared per host).

    All instances with the same group name rendezvous through the
    attached cluster's GCS KV (`ns="__collective__"`).
    """

    NS = "__collective__"
    JOIN_TIMEOUT = 60.0

    def __init__(self, name: str, world_size: int, rank: int, client=None,
                 gen: int = 0):
        if client is None:
            from ray_tpu.cluster.client import _ambient_client

            try:
                client = _ambient_client()
            except RuntimeError:
                client = None
            if client is None:
                raise RuntimeError(
                    "backend='cluster' collectives need an attached cluster "
                    "(ray_tpu.init(address=...) or a cluster worker process)"
                )
        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.gen = int(gen)
        self._client = client
        self._round = 0
        self._send_seq: dict[int, int] = {}
        self._recv_seq: dict[int, int] = {}
        try:
            cur = self._published_gen()
            if cur is not None and cur > self.gen:
                raise StaleGenerationError(
                    f"group {name!r} re-formed at gen {cur}; cannot join at "
                    f"gen {self.gen}",
                    group=name, gen=self.gen, rank=rank,
                )
            if rank == 0:
                if cur is None or cur < self.gen:
                    client.kv_put(
                        self._base_key("gen"),
                        str(self.gen).encode(),
                        self.NS,
                    )
                    if cur is not None:
                        # GC the superseded generation's residue: aborted
                        # rounds hold full gradient payloads under
                        # name/g{cur}/ that nobody will ever read (the
                        # re-formed gang is keyed g{gen}, zombies only
                        # write) — without this every recovery strands
                        # world_size gradient copies in GCS memory until
                        # group destroy
                        try:
                            for key in client.gcs.call("kv_keys", {
                                "ns": self.NS,
                                "prefix": f"{name}/g{cur}/".encode(),
                            }):
                                client.kv_del(key, self.NS)
                        except Exception:  # noqa: BLE001 — best-effort GC
                            pass
                client.kv_put(
                    self._key("meta"),
                    pickle.dumps({"world_size": world_size}),
                    self.NS,
                )
            else:
                # sliced wait, not one JOIN_TIMEOUT-long park: a
                # supervisor abort (rank 0 died before publishing meta)
                # unparks the join within one poll slice instead of
                # costing the full 60s of recovery latency
                meta = pickle.loads(self._wait(
                    self._key("meta"),
                    time.monotonic() + self.JOIN_TIMEOUT,
                    f"joining group {name!r} (gen {self.gen})",
                    rank,
                ))
                if meta["world_size"] != world_size:
                    raise ValueError(
                        f"group {name!r} (gen {self.gen}) exists with "
                        f"world_size {meta['world_size']} != {world_size}"
                    )
        except TimeoutError as e:
            raise CollectiveTimeoutError(
                f"joining group {name!r} (gen {self.gen}) as rank {rank}: "
                f"rank 0 never published meta within {self.JOIN_TIMEOUT}s",
                group=name, gen=self.gen, rank=rank,
            ) from e
        except _transport_errors() as e:
            raise CollectivePartitionError(
                f"joining group {name!r} (gen {self.gen}) as rank {rank}: "
                f"cannot reach the rendezvous plane: {e}",
                group=name, gen=self.gen, rank=rank,
            ) from e

    def _base_key(self, *parts) -> bytes:
        """Gen-independent key (group-lifetime state: the current gen)."""
        return "/".join((self.name,) + tuple(str(p) for p in parts)).encode()

    def _key(self, *parts) -> bytes:
        """Gen-scoped key: round contributions/results and p2p payloads
        of different gang epochs can never collide — a zombie's late
        deposit is invisible to the re-formed gang by construction."""
        return "/".join(
            (self.name, f"g{self.gen}") + tuple(str(p) for p in parts)
        ).encode()

    def _published_gen(self) -> Optional[int]:
        raw = self._client.kv_get(self._base_key("gen"), self.NS)
        return int(raw) if raw is not None else None

    def abort(self, reason: str) -> None:
        """Publish the abort marker for this gang epoch: every rank of
        gen <= this one parked in a sliced wait wakes with
        ``CollectiveAbortedError`` within one poll slice, instead of
        burning its full op timeout on a peer known dead."""
        publish_abort(self.name, reason, gen=self.gen, client=self._client)

    def _guard(self, op: str) -> bool:
        """Chaos hook at every op entry. Returns the drop-in-flight flag
        (see collective_chaos). Deliberately NO GCS round-trip here: the
        steady-state fast path stays at the op's own KV traffic —
        abort/stale-generation checks run inside the sliced waits, the
        only place a zombie or abandoned rank can actually linger (a
        zombie's deposits land under old-gen keys nobody reads, so an
        op that would complete without waiting is already harmless)."""
        from ray_tpu.collective.collective import collective_chaos

        return collective_chaos(self.name, self.gen, self.rank, op)

    def _check_live(self, rank: int) -> None:
        """Raise if this gang epoch was aborted or superseded (one
        kv_get each — only called between wait slices, never on the
        fast path)."""
        raw = self._client.kv_get(self._base_key("abort"), self.NS)
        if raw is not None:
            marker = pickle.loads(raw)
            if int(marker.get("gen", 0)) >= self.gen:
                raise CollectiveAbortedError(
                    f"collective group {self.name!r} (gen {self.gen}) "
                    f"aborted: {marker.get('reason', '')}",
                    group=self.name, gen=self.gen, rank=rank,
                )
        cur = self._published_gen()
        if cur is not None and cur > self.gen:
            raise StaleGenerationError(
                f"group {self.name!r} re-formed at gen {cur}; rank "
                f"{rank} joined gen {self.gen} and must exit",
                group=self.name, gen=self.gen, rank=rank,
            )

    POLL_SLICE_S = 1.0

    def _wait(self, key: bytes, deadline: float, what: str,
              rank: int) -> bytes:
        """``kv_wait`` in bounded slices, checking the abort marker and
        the published generation between slices — the cluster-tier
        analog of ``_HostGroup``'s condition-variable wake: an abort or
        a superseding re-form unparks this rank within one slice."""
        while True:
            left = deadline - time.monotonic()
            if left <= 0:
                raise CollectiveTimeoutError(
                    f"{what}: peers missing at deadline",
                    group=self.name, gen=self.gen, rank=rank,
                )
            try:
                return self._client.kv_wait(
                    key, self.NS, min(left, self.POLL_SLICE_S)
                )
            except TimeoutError:
                self._check_live(rank)

    # -- collective rendezvous ------------------------------------------------

    def rendezvous(self, rank: int, value: Any, compute,
                   timeout: Optional[float] = None):
        """Deposit value under this round; rank 0 reduces once all ranks
        landed and publishes; everyone returns the published result.

        One deadline bounds the WHOLE op (rank 0's reads across all
        peers share it — world_size stragglers cannot stack timeouts)."""
        timeout = DEFAULT_TIMEOUT if timeout is None else timeout
        deadline = time.monotonic() + timeout
        rnd, self._round = self._round, self._round + 1
        kv = self._client
        ctx = dict(group=self.name, gen=self.gen, rank=rank)
        try:
            drop = self._guard("rendezvous")
            if not drop:
                kv.kv_put(self._key(rnd, "c", rank), pickle.dumps(value), self.NS)
            if rank == 0:
                vals = []
                for r in range(self.world_size):
                    raw = self._wait(
                        self._key(rnd, "c", r), deadline,
                        f"round {rnd} gather", rank,
                    )
                    vals.append(pickle.loads(raw))
                result = compute(vals)
                kv.kv_put(self._key(rnd, "r"), pickle.dumps(result), self.NS)
                # garbage: contributions of this round; result of the previous
                # round (published results can only be awaited by ranks that
                # already contributed to THIS round, i.e. consumed round-1)
                for r in range(self.world_size):
                    kv.kv_del(self._key(rnd, "c", r), self.NS)
                if rnd > 0:
                    kv.kv_del(self._key(rnd - 1, "r"), self.NS)
                return result
            raw = self._wait(
                self._key(rnd, "r"), deadline, f"round {rnd} result", rank,
            )
            return pickle.loads(raw)
        except CollectiveError:
            raise
        except TimeoutError as e:
            raise CollectiveTimeoutError(
                f"collective group {self.name!r} (gen {self.gen}) round "
                f"{rnd}: peers missing after {timeout}s: {e}",
                **ctx,
            ) from e
        except _transport_errors() as e:
            raise CollectivePartitionError(
                f"collective group {self.name!r} (gen {self.gen}) round "
                f"{rnd}: lost the rendezvous plane: {e}",
                **ctx,
            ) from e

    # -- p2p ------------------------------------------------------------------

    def send(self, src: int, dst: int, value: Any,
             timeout: Optional[float] = None) -> None:
        ctx = dict(group=self.name, gen=self.gen, rank=src)
        try:
            drop = self._guard("send")
            seq = self._send_seq.get(dst, 0)
            self._send_seq[dst] = seq + 1
            if drop:  # lost in flight: sender believes it sent
                return
            self._client.kv_put(
                self._key("p2p", src, dst, seq), pickle.dumps(value), self.NS
            )
        except CollectiveError:
            raise
        except _transport_errors() as e:
            raise CollectivePartitionError(
                f"send {src}->{dst} in group {self.name!r}: lost the "
                f"rendezvous plane: {e}",
                **ctx,
            ) from e

    def recv(self, src: int, dst: int,
             timeout: Optional[float] = None) -> Any:
        timeout = DEFAULT_TIMEOUT if timeout is None else timeout
        deadline = time.monotonic() + timeout
        ctx = dict(group=self.name, gen=self.gen, rank=dst)
        try:
            self._guard("recv")
            seq = self._recv_seq.get(src, 0)
            self._recv_seq[src] = seq + 1
            key = self._key("p2p", src, dst, seq)
            raw = self._wait(key, deadline, f"recv from rank {src}", dst)
            self._client.kv_del(key, self.NS)
            return pickle.loads(raw)
        except CollectiveError:
            raise
        except TimeoutError as e:
            raise CollectiveTimeoutError(
                f"recv from rank {src} in group {self.name!r} timed out "
                f"after {timeout}s",
                **ctx,
            ) from e
        except _transport_errors() as e:
            raise CollectivePartitionError(
                f"recv {src}->{dst} in group {self.name!r}: lost the "
                f"rendezvous plane: {e}",
                **ctx,
            ) from e

    def destroy(self) -> None:
        clear_group_kv(self._client, self.name)


def publish_abort(name: str, reason: str, gen: Optional[int] = None,
                  client=None) -> None:
    """Publish a group's abort marker to the GCS — the driver-side abort
    primitive for cluster gangs whose ranks live in OTHER processes (a
    supervisor is not necessarily a rank). Ranks of gang epoch <= the
    marker's gen wake from their sliced waits with
    ``CollectiveAbortedError``; a re-formed gang at a higher epoch is
    untouched by it."""
    if client is None:
        from ray_tpu.cluster.client import _ambient_client

        client = _ambient_client()
        if client is None:
            return
    if gen is None:
        raw = client.kv_get(
            "/".join((name, "gen")).encode(), ClusterGroup.NS
        )
        gen = int(raw) if raw is not None else 0
    client.kv_put(
        "/".join((name, "abort")).encode(),
        pickle.dumps({"gen": int(gen), "reason": reason}),
        ClusterGroup.NS,
    )


def clear_group_kv(client, name: str) -> None:
    """Best-effort removal of a group's GCS residue (meta, current-gen
    marker, unread round results, unclaimed p2p payloads) — shared by
    rank-side destroy and the driver-side destroy_collective_group
    path."""
    try:
        for key in client.gcs.call(
            "kv_keys", {"ns": ClusterGroup.NS, "prefix": name.encode() + b"/"}
        ):
            client.kv_del(key, ClusterGroup.NS)
    except Exception:  # noqa: BLE001 — cleanup must never raise
        pass
