"""Typed collective-plane errors.

The availability contract of the training path (ISSUE r12, mirroring
what r09 did for serving): NO collective call may hang forever on a
peer that died, stalled, or partitioned. Every failure mode surfaces as
a subclass of ``CollectiveError`` within the op's bounded timeout, so a
supervisor (``ray_tpu.train.elastic.TrainerSupervisor``) can tell *how*
the gang broke and pick the right recovery:

 * ``CollectiveTimeoutError`` — a peer never arrived at the rendezvous
   (the survivor-side view of a killed/stalled/partitioned rank);
 * ``CollectiveAbortedError`` — the supervisor tore the round down
   deliberately (abort-on-first-fault, so survivors don't burn the full
   timeout waiting on a rank already known dead);
 * ``CollectivePartitionError`` — this rank can reach the GCS but not
   its peers (the ``PARTIAL_PARTITION`` chaos kind; also raised when
   peer-facing transport errors hit a collective op);
 * ``StaleGenerationError`` — the gang re-formed at a higher gang epoch
   while this rank was stalled/partitioned; the zombie's op is refused
   so it can never inject gradients into the new gang.

``CollectiveTimeoutError`` subclasses ``TimeoutError`` too, so callers
that predate the typed hierarchy (``except TimeoutError``) keep working.
"""

from __future__ import annotations

from ray_tpu.core.errors import RayTpuError

# Default bound on every collective op (rendezvous, p2p recv, join). Ops
# accept timeout= per call; None means this. Chosen large enough for
# slow control-plane reduces, small enough that a hung gang surfaces as
# a typed error instead of a wedged pod.
DEFAULT_TIMEOUT = 120.0


class CollectiveError(RayTpuError):
    """Base of all collective-plane failures. Carries the group name,
    gang epoch (generation) and rank when the raiser knows them."""

    def __init__(self, msg: str, *, group: str = "", gen: int = -1,
                 rank: int = -1):
        self.group = group
        self.gen = gen
        self.rank = rank
        super().__init__(msg)


class CollectiveTimeoutError(CollectiveError, TimeoutError):
    """A collective op's bounded wait expired: some peer never arrived."""


class CollectiveAbortedError(CollectiveError):
    """The round was aborted out from under the waiter (supervisor
    fault-recovery, or the group was superseded by a newer gang epoch)."""


class CollectivePartitionError(CollectiveError):
    """This rank cannot reach its peers (it may still reach the GCS —
    the partial-partition failure mode)."""


class StaleGenerationError(CollectiveError):
    """Op issued against a gang generation that has been superseded: the
    caller is a zombie rank from a previous gang epoch and must exit."""
