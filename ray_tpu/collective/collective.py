"""Collective communication API.

Mirrors the reference's gang-collective surface
(python/ray/util/collective/collective.py: init_collective_group:123,
create_collective_group:160, allreduce:268, broadcast:383,
allgather:433, reducescatter:482, send:541, recv:604) — but where the
reference wraps NCCL/Gloo communicators, the TPU-native story is
two-tier:

  * device tier: collectives are NOT a runtime API — they are XLA ops
    (`jax.lax.psum/all_gather/...`) emitted from jitted SPMD programs
    over a Mesh. `mesh_for_group` hands a group its Mesh; that is the
    whole "communicator".
  * host tier (this module's executable path): control-plane arrays
    (metrics, rendezvous payloads, RL weights) move through an
    in-process rendezvous over the gang's ranks — the Gloo-equivalent
    for thread workers on one host, and the seam where the DCN
    transport plugs in for multi-host.

Robustness contract (r12): every collective op is bounded — a peer that
dies, stalls, or partitions mid-allreduce produces a typed
``CollectiveError`` (collective/errors.py) within the op's timeout, and
groups carry a **gang epoch** (``gen``): when a supervisor re-forms the
gang at a higher generation, ops issued by zombie ranks of the old
generation raise ``StaleGenerationError`` instead of injecting into the
new gang. Chaos hook site ``collective.rendezvous`` fires the seeded
``KILL_RANK`` / ``STALL_COLLECTIVE`` / ``DROP_COLLECTIVE`` /
``PARTIAL_PARTITION`` fault kinds here.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Any, Optional

import numpy as np

from ray_tpu.chaos import harness as _chaos
from ray_tpu.collective.errors import (
    DEFAULT_TIMEOUT,
    CollectiveAbortedError,
    CollectiveError,
    CollectivePartitionError,
    CollectiveTimeoutError,
    StaleGenerationError,
)


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"


_REDUCERS = {
    ReduceOp.SUM: lambda vals: _tree_reduce(np.add, vals),
    ReduceOp.PRODUCT: lambda vals: _tree_reduce(np.multiply, vals),
    ReduceOp.MIN: lambda vals: _tree_reduce(np.minimum, vals),
    ReduceOp.MAX: lambda vals: _tree_reduce(np.maximum, vals),
    ReduceOp.MEAN: lambda vals: _tree_reduce(np.add, vals) / len(vals),
}


def _tree_reduce(op, vals):
    out = vals[0]
    for v in vals[1:]:
        out = op(out, v)
    return out


def collective_chaos(name: str, gen: int, rank: int, op: str) -> bool:
    """The collective-plane chaos hook (shared by the host-tier
    ``_HostGroup`` and the cluster-tier ``ClusterGroup``). Returns True
    when this rank's contribution must be DROPPED in flight — the rank
    believes it sent and keeps waiting, peers never see it (everyone's
    bounded wait then raises). ``KILL_RANK`` raises in the victim,
    ``STALL_COLLECTIVE`` sleeps ``delay_s`` before the op proceeds, and
    ``PARTIAL_PARTITION`` raises the typed partition error (the rank
    still heartbeats to GCS through its daemon — only the peer-facing
    collective plane is cut).

    ``DROP_COLLECTIVE`` is only eligible at ops that contribute data
    (rendezvous deposits, sends): a recv has nothing in flight to lose,
    and fire()'s site-kind contract says a spec must not burn its
    max_fires budget at a site that ignores its kind."""
    if _chaos.ACTIVE is None:
        return False
    kinds = (_chaos.KILL_RANK, _chaos.STALL_COLLECTIVE,
             _chaos.PARTIAL_PARTITION)
    if op != "recv":
        kinds += (_chaos.DROP_COLLECTIVE,)
    drop = False
    for f in _chaos.fire(
        "collective.rendezvous",
        kinds=kinds,
        group=name, gen=gen, rank=rank, op=op,
    ):
        if f.kind == _chaos.STALL_COLLECTIVE:
            time.sleep(f.delay_s)
        elif f.kind == _chaos.DROP_COLLECTIVE:
            drop = True
        elif f.kind == _chaos.KILL_RANK:
            raise _chaos.RankKilled(
                f"chaos: rank {rank} of group {name!r} (gen {gen}) "
                f"killed mid-{op}"
            )
        elif f.kind == _chaos.PARTIAL_PARTITION:
            raise CollectivePartitionError(
                f"chaos: rank {rank} of group {name!r} (gen {gen}) "
                "partitioned from peers (GCS heartbeats still flowing)",
                group=name, gen=gen, rank=rank,
            )
    return drop


class _HostGroup:
    """Rank-rendezvous collective group for ranks running as threads of one
    host process. Every rank must issue collectives in the same order
    (standard collective contract). Carries its gang epoch (``gen``);
    a supervisor re-forming the gang replaces this incarnation and
    ``abort()``s it so stragglers wake with a typed error instead of
    burning their full timeout."""

    def __init__(self, name: str, world_size: int, gen: int = 0):
        self.name = name
        self.world_size = world_size
        self.gen = int(gen)
        self._cv = threading.Condition()
        self._rounds: dict[int, dict] = {}  # round -> {values, result, reads}
        self._rank_round: dict[int, int] = {}
        self._p2p: dict[tuple, Any] = {}  # (src, dst, seq) -> value
        self._p2p_seq: dict[tuple, int] = {}
        self._aborted: Optional[str] = None

    def abort(self, reason: str) -> None:
        """Wake every blocked waiter with ``CollectiveAbortedError`` —
        the supervisor's abort-the-in-flight-step primitive: once one
        rank is known dead, survivors must not wait out their timeout."""
        with self._cv:
            self._aborted = reason
            self._cv.notify_all()

    def _check_live(self, rank: int, rnd: Optional[int] = None) -> None:
        if self._aborted is not None:
            raise CollectiveAbortedError(
                f"collective group {self.name!r} (gen {self.gen})"
                + (f" round {rnd}" if rnd is not None else "")
                + f" aborted: {self._aborted}",
                group=self.name, gen=self.gen, rank=rank,
            )
        with _lock:
            # _generations is _lock state: an unlocked peek could let a
            # zombie rank read a pre-re-form generation and keep waiting
            # a full timeout instead of exiting as stale NOW (the module
            # _lock regions never take a group's _cv, so cv -> _lock
            # nesting here is acyclic — lock_order-pass checked)
            current = _generations.get(self.name, self.gen)
        if current > self.gen:
            raise StaleGenerationError(
                f"collective group {self.name!r} re-formed at gen {current}; "
                f"this rank joined gen {self.gen} and must exit",
                group=self.name, gen=self.gen, rank=rank,
            )

    def _next_round(self, rank: int) -> int:
        r = self._rank_round.get(rank, 0)
        self._rank_round[rank] = r + 1
        return r

    def rendezvous(self, rank: int, value: Any, compute,
                   timeout: Optional[float] = None):
        """Deposit value; when all ranks arrive, compute(list_by_rank) once;
        everyone returns its output. Bounded: a missing peer raises
        ``CollectiveTimeoutError`` after ``timeout`` seconds."""
        timeout = DEFAULT_TIMEOUT if timeout is None else timeout
        drop = collective_chaos(self.name, self.gen, rank, "rendezvous")
        with self._cv:
            self._check_live(rank)
            rnd = self._next_round(rank)
            slot = self._rounds.setdefault(rnd, {"values": {}, "result": None, "done": False, "reads": 0})
            if not drop:
                slot["values"][rank] = value
            if len(slot["values"]) == self.world_size:
                ordered = [slot["values"][r] for r in range(self.world_size)]
                slot["result"] = compute(ordered)
                slot["done"] = True
                self._cv.notify_all()
            else:
                ok = self._cv.wait_for(
                    lambda: slot["done"] or self._aborted is not None, timeout
                )
                if not slot["done"]:
                    self._check_live(rank, rnd)  # aborted / superseded
                    assert not ok
                    raise CollectiveTimeoutError(
                        f"collective group {self.name!r} (gen {self.gen}) "
                        f"round {rnd}: only {len(slot['values'])}/"
                        f"{self.world_size} ranks arrived within {timeout}s",
                        group=self.name, gen=self.gen, rank=rank,
                    )
            result = slot["result"]
            slot["reads"] += 1
            if slot["reads"] == self.world_size:
                del self._rounds[rnd]
            return result

    # p2p ---------------------------------------------------------------

    def send(self, src: int, dst: int, value: Any,
             timeout: Optional[float] = None) -> None:
        drop = collective_chaos(self.name, self.gen, src, "send")
        with self._cv:
            self._check_live(src)
            seq = self._p2p_seq.get((src, dst, "s"), 0)
            self._p2p_seq[(src, dst, "s")] = seq + 1
            if not drop:  # dropped in flight: sender believes it sent
                self._p2p[(src, dst, seq)] = value
                self._cv.notify_all()

    def recv(self, src: int, dst: int, timeout: Optional[float] = None) -> Any:
        timeout = DEFAULT_TIMEOUT if timeout is None else timeout
        collective_chaos(self.name, self.gen, dst, "recv")
        with self._cv:
            self._check_live(dst)
            seq = self._p2p_seq.get((src, dst, "r"), 0)
            self._p2p_seq[(src, dst, "r")] = seq + 1
            ok = self._cv.wait_for(
                lambda: (src, dst, seq) in self._p2p
                or self._aborted is not None,
                timeout,
            )
            if (src, dst, seq) not in self._p2p:
                self._check_live(dst)
                assert not ok
                raise CollectiveTimeoutError(
                    f"recv from rank {src} timed out after {timeout}s",
                    group=self.name, gen=self.gen, rank=dst,
                )
            return self._p2p.pop((src, dst, seq))


_groups: dict[str, _HostGroup] = {}
_declared: dict[str, dict] = {}
_generations: dict[str, int] = {}  # group name -> current gang epoch
_lock = threading.Lock()
_local = threading.local()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "host",
    group_name: str = "default",
    gen: int = 0,
) -> None:
    """Join (creating if first) a collective group. Called by every rank.

    Backends: "host" (thread ranks of one process), "cluster" (process
    ranks rendezvousing through the attached cluster's GCS — the
    cross-process/DCN tier), "ici" (device tier: use mesh_for_group).

    ``gen`` is the gang epoch: a supervisor recovering from a lost rank
    re-forms the SAME group name at ``gen + 1`` — the old incarnation is
    aborted and superseded, and any zombie rank still holding it gets
    ``StaleGenerationError`` instead of injecting into the new gang.
    """
    if backend not in ("host", "ici", "cluster"):
        raise ValueError(f"unknown backend {backend!r}; 'host', 'cluster' or 'ici'")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    gen = int(gen)
    if backend == "cluster":
        from ray_tpu.collective.cluster_group import ClusterGroup

        with _lock:
            if gen < _generations.get(group_name, 0):
                raise StaleGenerationError(
                    f"group {group_name!r} is at gen "
                    f"{_generations[group_name]} in this process; cannot "
                    f"join at gen {gen}",
                    group=group_name, gen=gen, rank=rank,
                )
            existing = _groups.get(group_name)
            if (
                isinstance(existing, ClusterGroup)
                and existing.gen >= gen
                and existing.rank != rank
            ):
                # the rank->group fallback in _group_and_rank is per-process;
                # two ranks of one cluster group inside one process would
                # silently collapse onto the last writer. Cluster ranks are
                # process actors — use backend="host" for thread gangs.
                # (A HIGHER gen re-join may renumber this process's rank:
                # elastic re-form after eviction.)
                raise ValueError(
                    f"group {group_name!r} already has cluster rank "
                    f"{existing.rank} in this process; one cluster-backend "
                    "rank per process"
                )
        group = ClusterGroup(group_name, world_size, rank, gen=gen)
        with _lock:
            _groups[group_name] = group
            _generations[group_name] = max(_generations.get(group_name, 0), gen)
        if not hasattr(_local, "ranks"):
            _local.ranks = {}
        _local.ranks[group_name] = (group, rank)
        return
    superseded = None
    with _lock:
        if gen < _generations.get(group_name, 0):
            raise StaleGenerationError(
                f"group {group_name!r} is at gen {_generations[group_name]}; "
                f"cannot join at gen {gen}",
                group=group_name, gen=gen, rank=rank,
            )
        group = _groups.get(group_name)
        if group is None or getattr(group, "gen", 0) < gen:
            superseded = group
            group = _HostGroup(group_name, world_size, gen=gen)
            _groups[group_name] = group
            _generations[group_name] = gen
        elif group.world_size != world_size:
            raise ValueError(
                f"group {group_name!r} already exists with world_size "
                f"{group.world_size} != {world_size}"
            )
    if superseded is not None and hasattr(superseded, "abort"):
        # wake the old incarnation's stragglers NOW — they are zombies of
        # a dead gang epoch, not participants who might still arrive
        superseded.abort(f"superseded by gen {gen}")
    if not hasattr(_local, "ranks"):
        _local.ranks = {}
    # bind the rank to THIS group incarnation: after destroy/recreate, stale
    # thread-locals from the old group must not leak into the new one
    _local.ranks[group_name] = (group, rank)


def create_collective_group(
    actors: list,
    world_size: int,
    ranks: list[int],
    backend: str = "host",
    group_name: str = "default",
    gen: int = 0,
) -> None:
    """Declarative creation (reference collective.py:160): registers the
    group, then runs the rank join ON each actor's executor thread (so the
    actor's subsequent collective calls resolve their rank thread-locally).
    Blocks until every member joined."""
    from ray_tpu.core import api as _api

    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError("actors/ranks/world_size mismatch")
    try:
        from ray_tpu.cluster.client import ClusterActorHandle

        cluster_actors = all(isinstance(a, ClusterActorHandle) for a in actors)
    except ImportError:
        cluster_actors = False
    if cluster_actors and backend == "host":
        # process actors can't share a thread rendezvous — route the gang
        # through the cluster tier automatically
        backend = "cluster"
    with _lock:
        _declared[group_name] = {"world_size": world_size, "backend": backend}
        if backend != "cluster" and group_name not in _groups:
            _groups[group_name] = _HostGroup(group_name, world_size, gen=gen)
            _generations[group_name] = max(
                _generations.get(group_name, 0), int(gen)
            )
    if cluster_actors:
        from ray_tpu.cluster.client import _ActorMethod

        refs = [
            _ActorMethod(actor, "__ray_tpu_collective_init__").remote(
                world_size, rank, backend, group_name, gen
            )
            for actor, rank in zip(actors, ranks)
        ]
    else:
        refs = [
            actor._invoke(
                "__ray_tpu_collective_init__",
                (world_size, rank, backend, group_name, gen),
                {},
            )
            for actor, rank in zip(actors, ranks)
        ]
    _api.get(refs, timeout=60)


def declare_collective_group(
    world_size: int,
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Driver-side declaration WITHOUT joining: records the group's
    backend so ``abort_collective_group`` / ``destroy_collective_group``
    issued from a non-rank supervisor process reach the cluster tier
    (publish the GCS abort marker / clear the group's KV residue) even
    though no local group object exists. A supervisor whose ranks join
    via their own ``init_collective_group`` calls (the elastic trainer's
    shape) must declare, or its aborts silently no-op and a leaked GCS
    ``gen`` key poisons the next run reusing the group name."""
    if backend not in ("host", "ici", "cluster"):
        raise ValueError(f"unknown backend {backend!r}; 'host', 'cluster' or 'ici'")
    with _lock:
        _declared[group_name] = {"world_size": world_size, "backend": backend}


def destroy_collective_group(group_name: str = "default") -> None:
    with _lock:
        group = _groups.pop(group_name, None)
        declared = _declared.pop(group_name, None)
        _generations.pop(group_name, None)
    if group is not None and hasattr(group, "abort"):
        group.abort("group destroyed")
    if group is not None and hasattr(group, "destroy"):
        # cluster-tier: clear its GCS KV residue. This also deletes the
        # abort marker just published, so a REMOTE rank parked mid-op
        # may miss the one-poll-slice wake and fall back to its bounded
        # op timeout (typed CollectiveTimeoutError) — destroy is a
        # terminal cleanup, not the supervisor's abort primitive; use
        # abort_collective_group for latency-critical unparking.
        group.destroy()
    elif declared is not None and declared.get("backend") == "cluster":
        # driver declared the gang but never joined it, so no local
        # ClusterGroup exists; clear the GCS residue directly (stale
        # round results poison a recreated same-name group)
        try:
            from ray_tpu.cluster.client import _ambient_client
            from ray_tpu.collective.cluster_group import clear_group_kv

            clear_group_kv(_ambient_client(), group_name)
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass
    if hasattr(_local, "ranks"):
        _local.ranks.pop(group_name, None)


def abort_collective_group(group_name: str = "default",
                           reason: str = "aborted") -> None:
    """Abort the group's in-flight rounds WITHOUT destroying it: every
    blocked rank wakes with ``CollectiveAbortedError``. The supervisor's
    first move on detecting a dead rank — survivors must stop waiting on
    a peer that will never arrive.

    Host tier: wakes waiters via the group's condition variable. Cluster
    tier: publishes the group's GCS abort marker, which parked ranks in
    OTHER processes observe within one poll slice of their sliced waits
    — works from a driver that is not itself a rank."""
    with _lock:
        group = _groups.get(group_name)
        declared = _declared.get(group_name)
    if group is not None and hasattr(group, "abort"):
        group.abort(reason)
        return
    if declared is not None and declared.get("backend") == "cluster":
        from ray_tpu.collective.cluster_group import publish_abort

        try:
            publish_abort(group_name, reason)
        except Exception:  # noqa: BLE001 — abort is best-effort; the
            pass           # bounded op timeout remains the backstop


def _group_and_rank(group_name: str, rank: Optional[int]) -> tuple[_HostGroup, int]:
    with _lock:
        group = _groups.get(group_name)
        current_gen = _generations.get(group_name, 0)
    bound = getattr(_local, "ranks", {}).get(group_name)
    if bound is not None and bound[0] is not group:
        # this thread joined an incarnation that is no longer current.
        # HOST tier (no .rank attr — rank identity IS the thread): that
        # thread is a zombie of a superseded gang and must exit. CLUSTER
        # tier (per-process group with a fixed .rank): actor calls hop
        # executor-pool threads, so a stale thread binding after a
        # legitimate same-process re-join at gen+1 is just superseded —
        # a genuinely zombie PROCESS keeps its old group object and is
        # refused by the ClusterGroup's own published-gen check instead
        if (
            getattr(bound[0], "gen", 0) < current_gen
            and not hasattr(bound[0], "rank")
        ):
            raise StaleGenerationError(
                f"group {group_name!r} re-formed at gen {current_gen}; this "
                f"thread joined gen {getattr(bound[0], 'gen', 0)} and must "
                "exit (zombie rank)",
                group=group_name, gen=getattr(bound[0], "gen", 0),
                rank=bound[1],
            )
        bound = None  # superseded/recreated: stale binding
    if group is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized; call "
            f"init_collective_group first"
        )
    if rank is None:
        if bound is not None:
            rank = bound[1]
        elif hasattr(group, "rank"):
            # cluster-tier groups are per-process with a fixed rank, so
            # the binding survives actor method calls hopping pool threads
            rank = group.rank
        else:
            raise RuntimeError(
                f"calling thread has no rank in group {group_name!r}; pass rank= "
                f"or call init_collective_group on this thread"
            )
    return group, rank


def get_rank(group_name: str = "default") -> int:
    _, rank = _group_and_rank(group_name, None)
    return rank


def get_collective_group_size(group_name: str = "default") -> int:
    group, _ = _group_and_rank(group_name, 0)
    return group.world_size


def get_gang_epoch(group_name: str = "default") -> int:
    """The group's current gang epoch (generation) in this process."""
    with _lock:
        return _generations.get(group_name, 0)


# -- collectives -------------------------------------------------------------


def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM,
              rank: Optional[int] = None, timeout: Optional[float] = None):
    group, rank = _group_and_rank(group_name, rank)
    return group.rendezvous(rank, np.asarray(tensor), _REDUCERS[op],
                            timeout=timeout)


def allgather(tensor, group_name: str = "default", rank: Optional[int] = None,
              timeout: Optional[float] = None) -> list:
    group, rank = _group_and_rank(group_name, rank)
    return group.rendezvous(rank, np.asarray(tensor), lambda vals: list(vals),
                            timeout=timeout)


def reducescatter(tensor, group_name: str = "default",
                  op: ReduceOp = ReduceOp.SUM, rank: Optional[int] = None,
                  timeout: Optional[float] = None):
    group, rank = _group_and_rank(group_name, rank)
    reduced = group.rendezvous(rank, np.asarray(tensor), _REDUCERS[op],
                               timeout=timeout)
    shards = np.array_split(reduced, group.world_size, axis=0)
    return shards[rank]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default",
              rank: Optional[int] = None, timeout: Optional[float] = None):
    group, rank = _group_and_rank(group_name, rank)
    return group.rendezvous(rank, np.asarray(tensor),
                            lambda vals: vals[src_rank], timeout=timeout)


def barrier(group_name: str = "default", rank: Optional[int] = None,
            timeout: Optional[float] = None) -> None:
    group, rank = _group_and_rank(group_name, rank)
    group.rendezvous(rank, None, lambda vals: None, timeout=timeout)


def send(tensor, dst_rank: int, group_name: str = "default",
         rank: Optional[int] = None, timeout: Optional[float] = None) -> None:
    group, rank = _group_and_rank(group_name, rank)
    group.send(rank, dst_rank, np.asarray(tensor), timeout=timeout)


def recv(src_rank: int, group_name: str = "default",
         rank: Optional[int] = None, timeout: Optional[float] = None):
    group, rank = _group_and_rank(group_name, rank)
    return group.recv(src_rank, rank, timeout=timeout)


# -- device tier -------------------------------------------------------------


def mesh_for_group(
    spec=None,
    devices=None,
    group_name: str = "default",
):
    """The ICI-tier 'communicator': a jax Mesh over the gang's devices.
    Collectives inside jitted programs over this mesh ARE the backend
    (psum/all_gather/reduce_scatter/ppermute over ICI) — there is no
    NCCL-style call surface to wrap."""
    from ray_tpu.parallel.mesh import make_mesh

    return make_mesh(spec, devices=devices)
