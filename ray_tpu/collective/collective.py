"""Collective communication API.

Mirrors the reference's gang-collective surface
(python/ray/util/collective/collective.py: init_collective_group:123,
create_collective_group:160, allreduce:268, broadcast:383,
allgather:433, reducescatter:482, send:541, recv:604) — but where the
reference wraps NCCL/Gloo communicators, the TPU-native story is
two-tier:

  * device tier: collectives are NOT a runtime API — they are XLA ops
    (`jax.lax.psum/all_gather/...`) emitted from jitted SPMD programs
    over a Mesh. `mesh_for_group` hands a group its Mesh; that is the
    whole "communicator".
  * host tier (this module's executable path): control-plane arrays
    (metrics, rendezvous payloads, RL weights) move through an
    in-process rendezvous over the gang's ranks — the Gloo-equivalent
    for thread workers on one host, and the seam where the DCN
    transport plugs in for multi-host.
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Optional

import numpy as np


class ReduceOp(enum.Enum):
    SUM = "sum"
    PRODUCT = "product"
    MIN = "min"
    MAX = "max"
    MEAN = "mean"


_REDUCERS = {
    ReduceOp.SUM: lambda vals: _tree_reduce(np.add, vals),
    ReduceOp.PRODUCT: lambda vals: _tree_reduce(np.multiply, vals),
    ReduceOp.MIN: lambda vals: _tree_reduce(np.minimum, vals),
    ReduceOp.MAX: lambda vals: _tree_reduce(np.maximum, vals),
    ReduceOp.MEAN: lambda vals: _tree_reduce(np.add, vals) / len(vals),
}


def _tree_reduce(op, vals):
    out = vals[0]
    for v in vals[1:]:
        out = op(out, v)
    return out


class _HostGroup:
    """Rank-rendezvous collective group for ranks running as threads of one
    host process. Every rank must issue collectives in the same order
    (standard collective contract)."""

    def __init__(self, name: str, world_size: int):
        self.name = name
        self.world_size = world_size
        self._cv = threading.Condition()
        self._rounds: dict[int, dict] = {}  # round -> {values, result, reads}
        self._rank_round: dict[int, int] = {}
        self._p2p: dict[tuple, Any] = {}  # (src, dst, seq) -> value
        self._p2p_seq: dict[tuple, int] = {}

    def _next_round(self, rank: int) -> int:
        r = self._rank_round.get(rank, 0)
        self._rank_round[rank] = r + 1
        return r

    def rendezvous(self, rank: int, value: Any, compute, timeout: float = 120.0):
        """Deposit value; when all ranks arrive, compute(list_by_rank) once;
        everyone returns its output."""
        with self._cv:
            rnd = self._next_round(rank)
            slot = self._rounds.setdefault(rnd, {"values": {}, "result": None, "done": False, "reads": 0})
            slot["values"][rank] = value
            if len(slot["values"]) == self.world_size:
                ordered = [slot["values"][r] for r in range(self.world_size)]
                slot["result"] = compute(ordered)
                slot["done"] = True
                self._cv.notify_all()
            else:
                ok = self._cv.wait_for(lambda: slot["done"], timeout)
                if not ok:
                    raise TimeoutError(
                        f"collective group {self.name!r} round {rnd}: only "
                        f"{len(slot['values'])}/{self.world_size} ranks arrived"
                    )
            result = slot["result"]
            slot["reads"] += 1
            if slot["reads"] == self.world_size:
                del self._rounds[rnd]
            return result

    # p2p ---------------------------------------------------------------

    def send(self, src: int, dst: int, value: Any, timeout: float = 120.0) -> None:
        with self._cv:
            seq = self._p2p_seq.get((src, dst, "s"), 0)
            self._p2p_seq[(src, dst, "s")] = seq + 1
            self._p2p[(src, dst, seq)] = value
            self._cv.notify_all()

    def recv(self, src: int, dst: int, timeout: float = 120.0) -> Any:
        with self._cv:
            seq = self._p2p_seq.get((src, dst, "r"), 0)
            self._p2p_seq[(src, dst, "r")] = seq + 1
            ok = self._cv.wait_for(lambda: (src, dst, seq) in self._p2p, timeout)
            if not ok:
                raise TimeoutError(f"recv from rank {src} timed out")
            return self._p2p.pop((src, dst, seq))


_groups: dict[str, _HostGroup] = {}
_declared: dict[str, dict] = {}
_lock = threading.Lock()
_local = threading.local()


def init_collective_group(
    world_size: int,
    rank: int,
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Join (creating if first) a collective group. Called by every rank.

    Backends: "host" (thread ranks of one process), "cluster" (process
    ranks rendezvousing through the attached cluster's GCS — the
    cross-process/DCN tier), "ici" (device tier: use mesh_for_group).
    """
    if backend not in ("host", "ici", "cluster"):
        raise ValueError(f"unknown backend {backend!r}; 'host', 'cluster' or 'ici'")
    if not 0 <= rank < world_size:
        raise ValueError(f"rank {rank} out of range for world_size {world_size}")
    if backend == "cluster":
        from ray_tpu.collective.cluster_group import ClusterGroup

        with _lock:
            existing = _groups.get(group_name)
            if isinstance(existing, ClusterGroup) and existing.rank != rank:
                # the rank->group fallback in _group_and_rank is per-process;
                # two ranks of one cluster group inside one process would
                # silently collapse onto the last writer. Cluster ranks are
                # process actors — use backend="host" for thread gangs.
                raise ValueError(
                    f"group {group_name!r} already has cluster rank "
                    f"{existing.rank} in this process; one cluster-backend "
                    "rank per process"
                )
        group = ClusterGroup(group_name, world_size, rank)
        with _lock:
            _groups[group_name] = group
        if not hasattr(_local, "ranks"):
            _local.ranks = {}
        _local.ranks[group_name] = (group, rank)
        return
    with _lock:
        group = _groups.get(group_name)
        if group is None:
            group = _HostGroup(group_name, world_size)
            _groups[group_name] = group
        elif group.world_size != world_size:
            raise ValueError(
                f"group {group_name!r} already exists with world_size "
                f"{group.world_size} != {world_size}"
            )
    if not hasattr(_local, "ranks"):
        _local.ranks = {}
    # bind the rank to THIS group incarnation: after destroy/recreate, stale
    # thread-locals from the old group must not leak into the new one
    _local.ranks[group_name] = (group, rank)


def create_collective_group(
    actors: list,
    world_size: int,
    ranks: list[int],
    backend: str = "host",
    group_name: str = "default",
) -> None:
    """Declarative creation (reference collective.py:160): registers the
    group, then runs the rank join ON each actor's executor thread (so the
    actor's subsequent collective calls resolve their rank thread-locally).
    Blocks until every member joined."""
    from ray_tpu.core import api as _api

    if len(actors) != len(ranks) or len(actors) != world_size:
        raise ValueError("actors/ranks/world_size mismatch")
    try:
        from ray_tpu.cluster.client import ClusterActorHandle

        cluster_actors = all(isinstance(a, ClusterActorHandle) for a in actors)
    except ImportError:
        cluster_actors = False
    if cluster_actors and backend == "host":
        # process actors can't share a thread rendezvous — route the gang
        # through the cluster tier automatically
        backend = "cluster"
    with _lock:
        _declared[group_name] = {"world_size": world_size, "backend": backend}
        if backend != "cluster" and group_name not in _groups:
            _groups[group_name] = _HostGroup(group_name, world_size)
    if cluster_actors:
        from ray_tpu.cluster.client import _ActorMethod

        refs = [
            _ActorMethod(actor, "__ray_tpu_collective_init__").remote(
                world_size, rank, backend, group_name
            )
            for actor, rank in zip(actors, ranks)
        ]
    else:
        refs = [
            actor._invoke(
                "__ray_tpu_collective_init__",
                (world_size, rank, backend, group_name),
                {},
            )
            for actor, rank in zip(actors, ranks)
        ]
    _api.get(refs, timeout=60)


def destroy_collective_group(group_name: str = "default") -> None:
    with _lock:
        group = _groups.pop(group_name, None)
        declared = _declared.pop(group_name, None)
    if group is not None and hasattr(group, "destroy"):
        group.destroy()  # cluster-tier: clear its GCS KV residue
    elif declared is not None and declared.get("backend") == "cluster":
        # driver declared the gang but never joined it, so no local
        # ClusterGroup exists; clear the GCS residue directly (stale
        # round results poison a recreated same-name group)
        try:
            from ray_tpu.cluster.client import _ambient_client
            from ray_tpu.collective.cluster_group import clear_group_kv

            clear_group_kv(_ambient_client(), group_name)
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass
    if hasattr(_local, "ranks"):
        _local.ranks.pop(group_name, None)


def _group_and_rank(group_name: str, rank: Optional[int]) -> tuple[_HostGroup, int]:
    with _lock:
        group = _groups.get(group_name)
    if group is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized; call "
            f"init_collective_group first"
        )
    if rank is None:
        bound = getattr(_local, "ranks", {}).get(group_name)
        if bound is not None and bound[0] is group:
            rank = bound[1]
        elif hasattr(group, "rank"):
            # cluster-tier groups are per-process with a fixed rank, so
            # the binding survives actor method calls hopping pool threads
            rank = group.rank
        else:
            raise RuntimeError(
                f"calling thread has no rank in group {group_name!r}; pass rank= "
                f"or call init_collective_group on this thread"
            )
    return group, rank


def get_rank(group_name: str = "default") -> int:
    _, rank = _group_and_rank(group_name, None)
    return rank


def get_collective_group_size(group_name: str = "default") -> int:
    group, _ = _group_and_rank(group_name, 0)
    return group.world_size


# -- collectives -------------------------------------------------------------


def allreduce(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM, rank: Optional[int] = None):
    group, rank = _group_and_rank(group_name, rank)
    return group.rendezvous(rank, np.asarray(tensor), _REDUCERS[op])


def allgather(tensor, group_name: str = "default", rank: Optional[int] = None) -> list:
    group, rank = _group_and_rank(group_name, rank)
    return group.rendezvous(rank, np.asarray(tensor), lambda vals: list(vals))


def reducescatter(tensor, group_name: str = "default", op: ReduceOp = ReduceOp.SUM, rank: Optional[int] = None):
    group, rank = _group_and_rank(group_name, rank)
    reduced = group.rendezvous(rank, np.asarray(tensor), _REDUCERS[op])
    shards = np.array_split(reduced, group.world_size, axis=0)
    return shards[rank]


def broadcast(tensor, src_rank: int = 0, group_name: str = "default", rank: Optional[int] = None):
    group, rank = _group_and_rank(group_name, rank)
    return group.rendezvous(rank, np.asarray(tensor), lambda vals: vals[src_rank])


def barrier(group_name: str = "default", rank: Optional[int] = None) -> None:
    group, rank = _group_and_rank(group_name, rank)
    group.rendezvous(rank, None, lambda vals: None)


def send(tensor, dst_rank: int, group_name: str = "default", rank: Optional[int] = None) -> None:
    group, rank = _group_and_rank(group_name, rank)
    group.send(rank, dst_rank, np.asarray(tensor))


def recv(src_rank: int, group_name: str = "default", rank: Optional[int] = None):
    group, rank = _group_and_rank(group_name, rank)
    return group.recv(src_rank, rank)


# -- device tier -------------------------------------------------------------


def mesh_for_group(
    spec=None,
    devices=None,
    group_name: str = "default",
):
    """The ICI-tier 'communicator': a jax Mesh over the gang's devices.
    Collectives inside jitted programs over this mesh ARE the backend
    (psum/all_gather/reduce_scatter/ppermute over ICI) — there is no
    NCCL-style call surface to wrap."""
    from ray_tpu.parallel.mesh import make_mesh

    return make_mesh(spec, devices=devices)
