"""ChaosRunner: executes a schedule's orchestrated faults on a timeline.

In-process faults fire inline at hook sites; process-level faults
(``PREEMPT_NODE``, and ``KILL_WORKER`` / ``KILL_REPLICA`` specs given an
``at_s`` offset) need an executor with a handle on the blast radius.
The runner walks ``schedule.orchestrated()`` sorted by ``at_s`` on a
daemon thread, picking targets deterministically from the spec's seeded
RNG when the spec names none:

 * ``PREEMPT_NODE``  → ``LocalCluster.kill_node`` (SIGKILL daemon +
   workers; GCS learns by heartbeat timeout — the real preemption path);
 * ``KILL_WORKER``   → the target node daemon's ``chaos_kill_worker``
   RPC (newest leased worker dies mid-task);
 * ``KILL_REPLICA``  → serve controller ``kill_replica`` (actor killed
   out from under its router entry; health sweep replaces it).

Every executed fault is appended to the schedule ``log`` via a direct
record, so post-mortems read one merged sequence.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ray_tpu.chaos.schedule import (
    KILL_GCS,
    KILL_GCS_PRIMARY,
    KILL_REPLICA,
    KILL_WORKER,
    PARTITION_GCS_PAIR,
    PREEMPT_NODE,
    Fault,
    FaultSchedule,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.chaos.runner")


class ChaosRunner:
    def __init__(
        self,
        schedule: FaultSchedule,
        *,
        cluster=None,           # ray_tpu.cluster.LocalCluster (node faults)
        controller_handle=None,  # serve controller (replica faults)
    ):
        self.schedule = schedule
        self.cluster = cluster
        self.controller = controller_handle
        self.executed: list[Fault] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._restart_threads: list[threading.Thread] = []

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "ChaosRunner":
        self._thread = threading.Thread(
            target=self._run, name="chaos-runner", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        for t in self._restart_threads:
            t.join(timeout=5)

    def join(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)
        for t in self._restart_threads:
            t.join(timeout)

    # -- execution ------------------------------------------------------------

    def _run(self) -> None:
        t0 = time.monotonic()
        for idx, spec in self.schedule.orchestrated():
            wait = spec.at_s - (time.monotonic() - t0)
            if wait > 0 and self._stop.wait(wait):
                return
            if self._stop.is_set():
                return
            try:
                self._execute(idx, spec)
            except Exception:  # noqa: BLE001 — one failed kill must not end the run
                logger.exception("orchestrated fault %s failed", spec.kind)

    def _execute(self, idx, spec) -> None:
        attrs: dict = {}
        if spec.kind == PREEMPT_NODE:
            attrs = self._preempt_node(idx, spec)
        elif spec.kind == KILL_WORKER:
            attrs = self._kill_worker(idx, spec)
        elif spec.kind == KILL_REPLICA:
            attrs = self._kill_replica(idx, spec)
        elif spec.kind == KILL_GCS:
            attrs = self._kill_gcs(idx, spec)
        elif spec.kind == KILL_GCS_PRIMARY:
            attrs = self._kill_gcs_primary(idx, spec)
        elif spec.kind == PARTITION_GCS_PAIR:
            attrs = self._partition_gcs_pair(idx, spec)
        else:
            return
        with self.schedule._lock:
            fault = Fault(
                seq=self.schedule._seq, kind=spec.kind, site="runner",
                spec_index=idx, attrs=attrs, t=time.time(),
            )
            self.schedule._seq += 1
            self.schedule.log.append(fault)
        self.executed.append(fault)
        # mirror into the obs flight recorder like in-process hook fires,
        # so orchestrated kills land in Chrome-trace exports too
        from ray_tpu.chaos import harness as _harness

        _harness._record_obs_event("runner", spec.kind, attrs)
        logger.warning("chaos: executed %s %s", spec.kind, attrs)

    def _preempt_node(self, idx, spec) -> dict:
        if self.cluster is None:
            raise RuntimeError("PREEMPT_NODE needs a cluster")
        node_id = spec.target or self.schedule.pick(
            idx, list(self.cluster.nodes.keys())
        )
        self.cluster.kill_node(node_id)
        return {"node_id": node_id}

    def _kill_worker(self, idx, spec) -> dict:
        if self.cluster is None:
            raise RuntimeError("KILL_WORKER (orchestrated) needs a cluster")
        node_id = spec.target or self.schedule.pick(
            idx, list(self.cluster.nodes.keys())
        )
        node = self.cluster.nodes[node_id]
        client = self.cluster.client()
        r = client.pool.get(tuple(node.addr)).call(
            "chaos_kill_worker", {}, timeout=10
        )
        return {"node_id": node_id, **(r or {})}

    def _kill_gcs(self, idx, spec) -> dict:
        """SIGKILL the control plane; optionally schedule its restart
        ``restart_after_s`` later — the blackout window the data plane
        must serve through. The restart runs on its own thread so a long
        window never delays other orchestrated faults; the ``gcs.outage``
        obs span covers kill -> restart so Chrome-trace exports show the
        blackout instead of an unexplained metrics gap."""
        if self.cluster is None:
            raise RuntimeError("KILL_GCS needs a cluster")
        t_kill = time.time()
        self.cluster.kill_gcs()
        attrs = {"restart_after_s": spec.restart_after_s}
        if spec.restart_after_s > 0:
            def _restart():
                if self._stop.wait(spec.restart_after_s):
                    return
                try:
                    self.cluster.restart_gcs()
                except Exception:  # noqa: BLE001 — surface, don't die
                    logger.exception("chaos: scheduled GCS restart failed")
                    return
                try:
                    from ray_tpu.obs import recorder as _recorder

                    _recorder.get_recorder().record(
                        "gcs.outage", t_kill, time.time(),
                        attrs={"restart_after_s": str(spec.restart_after_s)},
                        status="error",
                    )
                except Exception:  # noqa: BLE001
                    pass
                logger.warning("chaos: restarted GCS after blackout")

            t = threading.Thread(
                target=_restart, name="chaos-gcs-restart", daemon=True
            )
            t.start()
            self._restart_threads.append(t)
        return attrs

    def _kill_gcs_primary(self, idx, spec) -> dict:
        """SIGKILL the primary GCS with NO restart (KILL_GCS_PRIMARY):
        the warm standby's lease expires and it promotes in place — the
        failover path, as opposed to _kill_gcs's blackout-then-restart.
        The promotion itself is asynchronous (lease-driven inside the
        standby); callers observe it through ha_status / the
        gcs_failovers_total counter."""
        if self.cluster is None:
            raise RuntimeError("KILL_GCS_PRIMARY needs a cluster")
        standby = getattr(self.cluster, "standby_addr", None)
        if standby is None:
            raise RuntimeError(
                "KILL_GCS_PRIMARY needs a standby GCS "
                "(LocalCluster(standby=True))"
            )
        t_kill = time.time()
        self.cluster.kill_gcs_primary()
        try:
            from ray_tpu.obs import recorder as _recorder

            _recorder.get_recorder().record(
                "gcs.failover", t_kill, time.time(),
                attrs={"standby": f"{standby[0]}:{standby[1]}"},
                status="error",
            )
        except Exception:  # noqa: BLE001
            pass
        return {"standby": tuple(standby), "restart": False}

    def _partition_gcs_pair(self, idx, spec) -> dict:
        """Open a split-brain window (PARTITION_GCS_PAIR): the standby
        stops seeing the primary for ``window_s`` (server-side
        ha_partition hook), so its lease expires and it promotes WHILE
        the primary is still alive. This process blocks its own view of
        the old primary for the same window (harness.BLOCKED_PEERS), so
        multi-endpoint clients here discover the promoted standby and
        its bumped term — after heal, the first fenced call the old
        primary sees retires it. Exactly one term wins."""
        if self.cluster is None:
            raise RuntimeError("PARTITION_GCS_PAIR needs a cluster")
        standby = getattr(self.cluster, "standby_addr", None)
        if standby is None:
            raise RuntimeError(
                "PARTITION_GCS_PAIR needs a standby GCS "
                "(LocalCluster(standby=True))"
            )
        from ray_tpu.chaos import harness as _harness
        from ray_tpu.cluster.rpc import RpcClient

        window = spec.window_s
        primary = tuple(self.cluster.gcs_addr)
        c = RpcClient(*standby, timeout=10.0).connect(retries=3)
        try:
            c.call("ha_partition", {"window_s": window}, timeout=10.0)
        finally:
            c.close()
        _harness.BLOCKED_PEERS.add(primary)

        def _heal():
            # heal even when stopped early: a blocked peer must never
            # outlive the chaos run
            self._stop.wait(window)
            _harness.BLOCKED_PEERS.discard(primary)
            logger.warning("chaos: GCS pair partition healed")

        t = threading.Thread(
            target=_heal, name="chaos-partition-heal", daemon=True
        )
        t.start()
        self._restart_threads.append(t)
        return {
            "window_s": window,
            "primary": primary,
            "standby": tuple(standby),
        }

    def _kill_replica(self, idx, spec) -> dict:
        if self.controller is None:
            raise RuntimeError("KILL_REPLICA (orchestrated) needs a controller")
        import ray_tpu

        app, _, dep = (spec.target or "").partition("/")
        if not app:
            # no target: pick the victim app from the spec's seeded RNG
            # (same contract as _preempt_node), not a silent no-op
            st = ray_tpu.get(self.controller.status.remote())
            apps = sorted(st.get("applications", {}))
            if not apps:
                raise RuntimeError("KILL_REPLICA: no serve applications")
            app = self.schedule.pick(idx, apps)
        rid = ray_tpu.get(
            self.controller.kill_replica.remote(app, dep or None)
        )
        if rid is None:
            # nothing died — surfacing this matters more than the kill:
            # a chaos run that silently skips its fault tests nothing
            raise RuntimeError(
                f"KILL_REPLICA: no running replica in app {app!r}"
            )
        return {"replica_id": rid, "app": app, "deployment": dep}
