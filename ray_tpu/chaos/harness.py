"""Chaos harness: the process-wide install point + hook the runtime calls.

Hook sites in the runtime are guarded by ``if harness.ACTIVE is not
None`` — ONE module-attribute load and an identity test when chaos is
disabled, so the production path pays nothing measurable. When a
schedule is installed, ``fire(site, **attrs)`` asks it which faults hit
this call; the call site interprets the kinds it understands (drop →
transport error, delay → sleep, corrupt → byte flip, kill → process
kill / injected crash).

Fired faults land in two places: the schedule's ``log`` (programmatic
post-mortem) and the ``ray_tpu.obs`` flight recorder as zero-duration
``chaos.<kind>`` event spans under the ambient trace — so a request's
trace shows *which* fault fired inside it and what recovered.

Cross-process: ``install(schedule, propagate_env=True)`` exports the
schedule as JSON in ``RAY_TPU_CHAOS``; node daemons and cluster workers
call ``install_from_env()`` at startup, so subprocess planes inherit the
driver's schedule deterministically (each process holds its own decision
counters — per-process call order is what determinism is defined over).
"""

from __future__ import annotations

import os
import time
from typing import Optional

from ray_tpu.chaos.schedule import (  # noqa: F401 — re-exported for hook sites
    CORRUPT_DEVICE_TRANSFER,
    CORRUPT_FRAME,
    CORRUPT_KV_TRANSFER,
    DELAY_RPC,
    DROP_CHANNEL,
    DROP_COLLECTIVE,
    DROP_DEVICE_TRANSFER,
    DROP_KV_TRANSFER,
    DROP_RPC,
    KILL_GCS,
    KILL_GCS_PRIMARY,
    KILL_RANK,
    KILL_REPLICA,
    KILL_WORKER,
    PARTIAL_PARTITION,
    PARTITION_GCS_PAIR,
    PREEMPT_ENGINE,
    PREEMPT_NODE,
    STALL_CHANNEL,
    STALL_COLLECTIVE,
    STALL_GCS,
    STALL_HEARTBEAT,
    Fault,
    FaultSchedule,
    FaultSpec,
)

ENV_VAR = "RAY_TPU_CHAOS"

# THE fast-path guard: hook sites read this attribute and skip everything
# when it is None. Installed schedules are process-wide.
ACTIVE: Optional[FaultSchedule] = None

# PARTITION_GCS_PAIR support: endpoints in this set are unreachable from
# THIS process (the chaos runner models a one-sided network partition by
# blocking the driver's view of the primary while the standby keeps its
# own partition window server-side). Multi-endpoint clients consult it
# on dial and before each call; guarded by truthiness, so the production
# path pays one falsy set check.
BLOCKED_PEERS: set[tuple[str, int]] = set()


class FaultInjected(Exception):
    """Base of injected failures (so tests/retry paths can tell chaos
    from organic faults when they need to)."""


class ReplicaCrashed(FaultInjected):
    """A serve replica crashed mid-request (KILL_REPLICA, in-process)."""


class EnginePreempted(FaultInjected):
    """The LLM engine was preempted mid-step (PREEMPT_ENGINE)."""


class RankKilled(FaultInjected):
    """A collective-gang rank died mid-op (KILL_RANK): the victim raises
    this; its peers see a typed CollectiveTimeoutError within their
    bounded wait — never a forever-hung allreduce."""


def install(schedule: FaultSchedule, *, propagate_env: bool = False) -> FaultSchedule:
    """Activate a schedule in this process. ``propagate_env`` exports it
    so subprocesses spawned from here (node daemons, cluster workers)
    pick it up via ``install_from_env``."""
    global ACTIVE
    ACTIVE = schedule
    if propagate_env:
        os.environ[ENV_VAR] = schedule.to_wire()
    return schedule


def uninstall() -> None:
    global ACTIVE
    ACTIVE = None
    BLOCKED_PEERS.clear()
    os.environ.pop(ENV_VAR, None)


def active() -> Optional[FaultSchedule]:
    return ACTIVE


def install_from_env() -> Optional[FaultSchedule]:
    """Subprocess entry hook (node_daemon / worker_main main()): adopt
    the driver's schedule if one rode in on the environment."""
    global ACTIVE
    if ACTIVE is not None:
        return ACTIVE
    wire = os.environ.get(ENV_VAR)
    if not wire:
        return None
    try:
        ACTIVE = FaultSchedule.from_wire(wire)
    except Exception:  # noqa: BLE001 — a bad env var must not kill the daemon
        return None
    return ACTIVE


def fire(site: str, kinds=None, **attrs) -> list[FaultSpec]:
    """Ask the active schedule which faults hit this call, mirroring each
    into the obs flight recorder. ``kinds``: the fault kinds this hook
    site implements (specs of other kinds are not eligible here, so they
    can't burn their budget at a site that would ignore them). Returns
    [] when chaos is disabled."""
    sched = ACTIVE
    if sched is None:
        return []
    hits = sched.fire(site, kinds=kinds, **attrs)
    for spec in hits:
        _record_obs_event(site, spec.kind, attrs)
    return hits


def _record_obs_event(site: str, kind: str, attrs: dict) -> None:
    """Zero-duration ``chaos.<kind>`` span under the ambient trace (or a
    fresh root): the post-mortem trail. Never breaks the faulted path."""
    try:
        from ray_tpu.obs import recorder as _recorder

        now = time.time()
        _recorder.get_recorder().record(
            f"chaos.{kind}", now, now,
            attrs={"site": site, **{k: str(v) for k, v in attrs.items()}},
            status="error",
        )
    except Exception:  # noqa: BLE001
        pass


def corrupt_frame(body: bytes) -> bytes:
    """Deterministic byte corruption for CORRUPT_FRAME: flip a span in
    the middle of the frame (header length stays intact so the peer
    reads a full frame and fails in deserialization, the realistic
    torn-payload failure mode)."""
    if not body:
        return body
    mid = len(body) // 2
    span = max(1, min(8, len(body) - mid))
    return body[:mid] + bytes(b ^ 0xFF for b in body[mid:mid + span]) + body[mid + span:]


def fault_log() -> list[Fault]:
    sched = ACTIVE
    return list(sched.log) if sched is not None else []
