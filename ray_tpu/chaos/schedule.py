"""Deterministic fault schedules: what breaks, where, and when.

Reference analogs: the reference repo's chaos testing utilities
(python/ray/_private/test_utils.py node/worker killer actors and the
chaos-test suite built on them) — redesigned as a *seeded, typed*
schedule instead of ad-hoc `kill_raylet` helpers: the same seed always
reproduces the same fault sequence against the same call sequence, so a
failing chaos run is a replayable artifact, not a flake.

Two fault families share one schedule:

 * in-process faults (``DROP_RPC``, ``DELAY_RPC``, ``CORRUPT_FRAME``,
   ``STALL_HEARTBEAT``, ``KILL_WORKER``, ``KILL_REPLICA``,
   ``PREEMPT_ENGINE``) fire at hook sites woven into the runtime
   (cluster/rpc.py, cluster/client.py, cluster/node_daemon.py,
   core/process_pool.py, serve/replica.py, llm/engine.py). Eligibility
   is counted per spec; probabilistic specs draw from a per-spec
   ``random.Random`` derived from the schedule seed — call order in,
   identical decisions out.
 * orchestrated faults (``PREEMPT_NODE``, and ``KILL_WORKER`` /
   ``KILL_REPLICA`` with an ``at_s`` offset) are executed by
   ``chaos.runner.ChaosRunner`` against a live LocalCluster / serve
   controller on a deterministic timeline.

Schedules serialize to JSON (``to_wire``/``from_wire``) so a driver can
propagate them to daemon/worker subprocesses through the
``RAY_TPU_CHAOS`` environment variable.
"""

from __future__ import annotations

import dataclasses
import json
import random
import threading
import time
from fnmatch import fnmatchcase
from typing import Optional, Sequence

# -- typed fault kinds --------------------------------------------------------

KILL_WORKER = "kill_worker"          # kill the worker process running a task
KILL_REPLICA = "kill_replica"        # crash a serve replica mid-request
DROP_RPC = "drop_rpc"                # transport error instead of the send
DELAY_RPC = "delay_rpc"              # inject latency before the send
STALL_HEARTBEAT = "stall_heartbeat"  # node stops heartbeating (partition)
PREEMPT_NODE = "preempt_node"        # SIGKILL a whole node (daemon+workers)
CORRUPT_FRAME = "corrupt_frame"      # flip bytes in the wire frame
PREEMPT_ENGINE = "preempt_engine"    # LLM engine dies mid-step
# disaggregated-serving KV-transfer plane (llm/disagg/connector.py): a
# handoff that vanishes in flight vs one that arrives bit-flipped — the
# two failure modes a prefill->decode transfer plane must survive
# (receiver detects corruption by checksum; both end in a re-prefill)
DROP_KV_TRANSFER = "drop_kv_transfer"        # handoff lost before the send
CORRUPT_KV_TRANSFER = "corrupt_kv_transfer"  # KV pages bit-flipped in flight
# collective/DAG plane (collective/collective.py, collective/
# cluster_group.py, plus rpc.call for process-wide partitions): the gang
# failure modes a data-parallel trainer on a preemptible pod must
# survive. All four end the same way for the survivors — a bounded wait
# raising a typed CollectiveError instead of a forever-hung allreduce.
KILL_RANK = "kill_rank"                  # a gang rank dies mid-collective
STALL_COLLECTIVE = "stall_collective"    # a rank arrives late (delay_s)
DROP_COLLECTIVE = "drop_collective"      # a contribution lost in flight
PARTIAL_PARTITION = "partial_partition"  # heartbeats reach GCS, peers don't
# control plane (r13): the one process chaos had never touched. KILL_GCS
# SIGKILLs the GCS on the runner timeline (with a scheduled restart via
# restart_after_s — the blackout window); STALL_GCS is an outage WITHOUT
# a process death: every GCS-bound rpc.call in the seeded window fails
# with DROP_RPC-style transport loss while the process stays up.
KILL_GCS = "kill_gcs"                    # SIGKILL the control plane
STALL_GCS = "stall_gcs"                  # GCS-bound RPCs get transport loss
# control-plane HA (r23, cluster/ha.py): KILL_GCS_PRIMARY SIGKILLs the
# primary GCS with NO restart ever scheduled — survival now means the
# warm standby promotes within its lease bound and clients fail over,
# not that the dead process comes back. PARTITION_GCS_PAIR opens a
# split-brain window of window_s seconds: the standby stops seeing the
# primary (server-side partition clock) while the driver's clients are
# blocked from the primary (harness.BLOCKED_PEERS) — the standby
# promotes, both "primaries" are alive, and epoch fencing must leave
# exactly one term winner with every zombie write counted and rejected.
KILL_GCS_PRIMARY = "kill_gcs_primary"    # SIGKILL primary; standby promotes
PARTITION_GCS_PAIR = "partition_gcs_pair"  # split-brain window (window_s)
# compiled-DAG channel plane (dag/channels.py send/recv + the
# dag/compiled.py exec loops): a value lost in flight (receiver's
# bounded read raises ChannelTimeoutError) vs a late writer (delay_s) —
# the collective fault kinds' semantics on the channel substrate.
DROP_CHANNEL = "drop_channel"            # written value lost in flight
STALL_CHANNEL = "stall_channel"          # channel op delayed by delay_s
# device-direct transfer plane (ray_tpu/fabric/transport.py): the same
# two failure modes the KV-transfer kinds model, on the ICI/device
# substrate — a device transfer that never lands vs one whose pages
# arrive bit-flipped (caught by the device-side checksum at import).
# Distinct kinds so a schedule can fault ONLY the device edges and the
# orchestrator's RPC-fallback path is what gets exercised.
DROP_DEVICE_TRANSFER = "drop_device_transfer"        # device xfer lost
CORRUPT_DEVICE_TRANSFER = "corrupt_device_transfer"  # pages flipped on device

KINDS = frozenset({
    KILL_WORKER, KILL_REPLICA, DROP_RPC, DELAY_RPC, STALL_HEARTBEAT,
    PREEMPT_NODE, CORRUPT_FRAME, PREEMPT_ENGINE,
    DROP_KV_TRANSFER, CORRUPT_KV_TRANSFER,
    KILL_RANK, STALL_COLLECTIVE, DROP_COLLECTIVE, PARTIAL_PARTITION,
    KILL_GCS, STALL_GCS, DROP_CHANNEL, STALL_CHANNEL,
    DROP_DEVICE_TRANSFER, CORRUPT_DEVICE_TRANSFER,
    KILL_GCS_PRIMARY, PARTITION_GCS_PAIR,
})

# kinds the in-process hook ignores (a runner executes them instead)
ORCHESTRATED = frozenset({
    PREEMPT_NODE, KILL_GCS, KILL_GCS_PRIMARY, PARTITION_GCS_PAIR,
})
# kinds ChaosRunner knows how to execute on an at_s timeline
RUNNER_KINDS = frozenset({
    PREEMPT_NODE, KILL_WORKER, KILL_REPLICA, KILL_GCS,
    KILL_GCS_PRIMARY, PARTITION_GCS_PAIR,
})


@dataclasses.dataclass
class FaultSpec:
    """One rule in a schedule.

    ``site`` / ``match`` select eligible hook calls (fnmatch patterns;
    ``match`` patterns apply to the hook's keyword attrs). Of eligible
    calls, the first ``start_after`` are skipped, then every
    ``every_n``-th is considered, fires with probability ``p`` (drawn
    from the spec's seeded RNG), at most ``max_fires`` times."""

    kind: str
    site: str = "*"
    match: dict = dataclasses.field(default_factory=dict)
    p: float = 1.0
    start_after: int = 0
    every_n: int = 1
    max_fires: int = -1          # -1 = unbounded
    delay_s: float = 0.05        # DELAY_RPC sleep
    at_s: float = 0.0            # orchestrated: offset from runner start
    target: Optional[str] = None  # orchestrated: node_id / "app/deployment"
    # KILL_GCS only: restart the control plane this many seconds after
    # the kill (0 = no scheduled restart; the test restarts it itself).
    # The window [at_s, at_s + restart_after_s] IS the blackout.
    # (KILL_GCS_PRIMARY deliberately rejects it: HA survival must come
    # from standby promotion, never from the dead primary coming back.)
    restart_after_s: float = 0.0
    # PARTITION_GCS_PAIR only: how long the split-brain window stays
    # open before the partition heals.
    window_s: float = 0.0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; one of {sorted(KINDS)}")
        if not (0.0 <= self.p <= 1.0):
            raise ValueError(f"p must be in [0, 1], got {self.p}")
        if self.every_n < 1:
            raise ValueError("every_n must be >= 1")
        if self.restart_after_s < 0.0:
            raise ValueError("restart_after_s must be >= 0")
        if self.restart_after_s > 0.0 and self.kind != KILL_GCS:
            raise ValueError(
                f"restart_after_s is only valid for {KILL_GCS!r}, "
                f"not {self.kind!r}"
            )
        if self.window_s < 0.0:
            raise ValueError("window_s must be >= 0")
        if self.window_s > 0.0 and self.kind != PARTITION_GCS_PAIR:
            raise ValueError(
                f"window_s is only valid for {PARTITION_GCS_PAIR!r}, "
                f"not {self.kind!r}"
            )
        if self.kind == PARTITION_GCS_PAIR and self.window_s <= 0.0:
            raise ValueError(
                f"{PARTITION_GCS_PAIR!r} requires window_s > 0 "
                "(the split-brain window must eventually heal)"
            )
        if self.at_s > 0.0 and self.kind not in RUNNER_KINDS:
            # at_s routes the spec to ChaosRunner, which only executes
            # RUNNER_KINDS — anything else would be a silent no-op that
            # fires nowhere (neither hooks nor runner)
            raise ValueError(
                f"at_s is only valid for {sorted(RUNNER_KINDS)}, "
                f"not {self.kind!r} (in-process kinds use "
                "start_after/every_n/p instead)"
            )


@dataclasses.dataclass
class Fault:
    """A fired fault — the post-mortem record (also mirrored into the
    ray_tpu.obs flight recorder as a ``chaos.<kind>`` event span)."""

    seq: int
    kind: str
    site: str
    spec_index: int
    attrs: dict
    t: float


class FaultSchedule:
    """Seeded, thread-safe fault decider. Same seed + same eligible-call
    sequence => same fault sequence; zero shared global RNG state."""

    def __init__(self, seed: int, faults: Sequence[FaultSpec]):
        self.seed = int(seed)
        self.specs = list(faults)
        for f in self.specs:
            if not isinstance(f, FaultSpec):
                raise TypeError(f"expected FaultSpec, got {type(f)}")
        # one RNG per spec: a spec added/removed between runs cannot
        # shift its siblings' decision streams
        self._rngs = [
            random.Random((self.seed << 16) ^ i) for i in range(len(self.specs))
        ]
        self._eligible = [0] * len(self.specs)
        self._fired = [0] * len(self.specs)
        self.log: list[Fault] = []
        self._seq = 0
        self._lock = threading.Lock()

    # -- decision ------------------------------------------------------------

    def fire(self, site: str, kinds: Optional[Sequence[str]] = None,
             **attrs) -> list[FaultSpec]:
        """Decide which specs fire for this hook call; records them in
        ``log``. Deterministic in (seed, call order). ``kinds`` is the
        set of fault kinds THIS hook site implements: a spec whose kind
        the site would ignore is not eligible here — otherwise a
        wildcard-site spec could burn its max_fires budget (and log a
        fault into the post-mortem) at a site where nothing happens."""
        hits: list[FaultSpec] = []
        with self._lock:
            for i, spec in enumerate(self.specs):
                if spec.kind in ORCHESTRATED or spec.at_s > 0.0:
                    # timeline-orchestrated specs belong to ChaosRunner;
                    # matching them at in-process hook sites too would
                    # fire the same fault twice through different planes
                    continue
                if kinds is not None and spec.kind not in kinds:
                    continue
                if not fnmatchcase(site, spec.site):
                    continue
                if not all(
                    fnmatchcase(str(attrs.get(k, "")), pat)
                    for k, pat in spec.match.items()
                ):
                    continue
                n = self._eligible[i]
                self._eligible[i] += 1
                if n < spec.start_after:
                    continue
                if (n - spec.start_after) % spec.every_n:
                    continue
                if spec.max_fires >= 0 and self._fired[i] >= spec.max_fires:
                    continue
                if spec.p < 1.0 and self._rngs[i].random() >= spec.p:
                    continue
                self._fired[i] += 1
                self.log.append(Fault(
                    seq=self._seq, kind=spec.kind, site=site, spec_index=i,
                    attrs=dict(attrs), t=time.time(),
                ))
                self._seq += 1
                hits.append(spec)
        return hits

    def pick(self, spec_index: int, choices: Sequence) -> object:
        """Deterministic choice for orchestrated faults (e.g. which node
        to preempt) from the spec's own RNG."""
        if not choices:
            raise ValueError("no choices to pick from")
        return self._rngs[spec_index].choice(sorted(choices, key=str))

    def orchestrated(self) -> list[tuple[int, FaultSpec]]:
        """(index, spec) pairs a ChaosRunner should execute, by at_s."""
        out = [
            (i, s) for i, s in enumerate(self.specs)
            if s.kind in ORCHESTRATED or s.at_s > 0.0
        ]
        out.sort(key=lambda t: t[1].at_s)
        return out

    def fired_kinds(self) -> list[str]:
        with self._lock:
            return [f.kind for f in self.log]

    def decisions(self) -> list[tuple[str, str, int]]:
        """Compact (kind, site, spec_index) sequence — the determinism
        contract surface: equal for equal seeds and call sequences."""
        with self._lock:
            return [(f.kind, f.site, f.spec_index) for f in self.log]

    # -- wire form (env propagation to subprocesses) --------------------------

    def to_wire(self) -> str:
        return json.dumps({
            "seed": self.seed,
            "faults": [dataclasses.asdict(f) for f in self.specs],
        })

    @classmethod
    def from_wire(cls, wire: str) -> "FaultSchedule":
        doc = json.loads(wire)
        return cls(doc["seed"], [FaultSpec(**f) for f in doc["faults"]])
