"""ray_tpu.chaos — deterministic fault injection for a runtime that must
survive preemptible fleets.

Usage::

    from ray_tpu import chaos

    sched = chaos.FaultSchedule(seed=7, faults=[
        chaos.FaultSpec(chaos.DROP_RPC, site="rpc.call",
                        match={"method": "push_task"}, p=0.25, max_fires=3),
        chaos.FaultSpec(chaos.PREEMPT_ENGINE, site="llm.engine.step",
                        start_after=5, max_fires=1),
    ])
    chaos.install(sched)             # propagate_env=True for subprocesses
    try:
        ...                          # run the workload; faults fire
        print(sched.decisions())     # the deterministic post-mortem
    finally:
        chaos.uninstall()

Hook sites are woven into cluster/rpc.py, cluster/client.py,
cluster/node_daemon.py, core/process_pool.py, serve/replica.py, and
llm/engine.py, each behind an ``ACTIVE is None`` fast path — disabled
chaos costs one attribute load per site. Orchestrated process kills
(PREEMPT_NODE etc.) run through ``chaos.runner.ChaosRunner``. Fired
faults are mirrored into the ``ray_tpu.obs`` flight recorder as
``chaos.<kind>`` event spans.
"""

from ray_tpu.chaos import harness
from ray_tpu.chaos.harness import (
    ENV_VAR,
    EnginePreempted,
    FaultInjected,
    RankKilled,
    ReplicaCrashed,
    corrupt_frame,
    fault_log,
    fire,
    install,
    install_from_env,
    uninstall,
)
from ray_tpu.chaos.schedule import (
    CORRUPT_FRAME,
    DELAY_RPC,
    DROP_CHANNEL,
    DROP_COLLECTIVE,
    DROP_RPC,
    KILL_GCS,
    KILL_GCS_PRIMARY,
    KILL_RANK,
    KILL_REPLICA,
    KILL_WORKER,
    KINDS,
    PARTIAL_PARTITION,
    PARTITION_GCS_PAIR,
    PREEMPT_ENGINE,
    PREEMPT_NODE,
    STALL_CHANNEL,
    STALL_COLLECTIVE,
    STALL_GCS,
    STALL_HEARTBEAT,
    Fault,
    FaultSchedule,
    FaultSpec,
)


def active():
    """The installed schedule, or None (read harness.ACTIVE for the
    fast-path guard — this module re-binds lazily)."""
    return harness.ACTIVE


def __getattr__(name):
    if name == "ACTIVE":  # convenience mirror of harness.ACTIVE
        return harness.ACTIVE
    if name == "ChaosRunner":
        from ray_tpu.chaos.runner import ChaosRunner

        return ChaosRunner
    raise AttributeError(f"module 'ray_tpu.chaos' has no attribute {name!r}")


__all__ = [
    "CORRUPT_FRAME", "DELAY_RPC", "DROP_CHANNEL", "DROP_COLLECTIVE",
    "DROP_RPC", "KILL_GCS", "KILL_GCS_PRIMARY", "KILL_RANK",
    "KILL_REPLICA", "KILL_WORKER", "KINDS", "PARTIAL_PARTITION",
    "PARTITION_GCS_PAIR",
    "PREEMPT_ENGINE", "PREEMPT_NODE", "STALL_CHANNEL", "STALL_COLLECTIVE",
    "STALL_GCS", "STALL_HEARTBEAT",
    "Fault", "FaultSchedule", "FaultSpec", "FaultInjected", "RankKilled",
    "ReplicaCrashed",
    "EnginePreempted", "ChaosRunner", "ENV_VAR", "active", "corrupt_frame",
    "fault_log", "fire", "harness", "install", "install_from_env", "uninstall",
]
