"""Functional NN building blocks (pure jax, pytree params).

The framework's model layer is deliberately functional: params are plain
pytrees built next to a parallel pytree of logical-axis annotations
(see ray_tpu.parallel.sharding). No module objects, no tracing magic —
everything stays jit/scan/shard_map-friendly.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    """RMSNorm in fp32 regardless of input dtype (numerics on the VPU are cheap)."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)).astype(x.dtype)


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 500000.0) -> tuple[jax.Array, jax.Array]:
    """Precompute RoPE cos/sin tables [max_seq, head_dim//2] in fp32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array) -> jax.Array:
    """Rotate pairs of features. x: [B, S, H, D]; positions: [B, S] or [S]."""
    c = cos[positions]  # [..., S, D/2]
    s = sin[positions]
    if c.ndim == 2:  # positions was [S]
        c = c[None, :, None, :]
        s = s[None, :, None, :]
    else:  # [B, S, D/2]
        c = c[:, :, None, :]
        s = s[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array) -> jax.Array:
    gate = jnp.einsum("bsd,df->bsf", x, w_gate.astype(x.dtype))
    up = jnp.einsum("bsd,df->bsf", x, w_up.astype(x.dtype))
    return jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up, w_down.astype(x.dtype))


def init_dense(key: jax.Array, shape: tuple[int, ...], dtype, scale: float | None = None) -> jax.Array:
    """Truncated-normal fan-in init."""
    fan_in = shape[0]
    std = scale if scale is not None else 1.0 / jnp.sqrt(fan_in)
    return (jax.random.truncated_normal(key, -3, 3, shape, jnp.float32) * std).astype(dtype)


def cross_entropy_loss(
    logits: jax.Array,  # [B, S, V] (any float dtype; upcast internally)
    targets: jax.Array,  # [B, S] int32
    mask: jax.Array | None = None,  # [B, S] 0/1
) -> tuple[jax.Array, jax.Array]:
    """Returns (mean_nll, total_weight). fp32 log-softmax for stability."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if mask is None:
        mask = jnp.ones_like(nll)
    mask = mask.astype(jnp.float32)
    total = jnp.maximum(mask.sum(), 1.0)
    return (nll * mask).sum() / total, total


# ---------------------------------------------------------------------------
# fused lm-head + cross entropy
# ---------------------------------------------------------------------------
#
# The naive path (forward() -> [T, V] logits -> cross_entropy_loss) is
# HBM-bound, not MXU-bound: XLA materializes the fp32 logits, the
# logsumexp intermediates, the take_along_axis gather, and the softmax
# in the backward — ~79 ms of the 221 ms flagship step at B=8/S=1024/
# V=32000 (benchmarks/profile_step2.py, round 5) against an ~8 ms MXU
# floor for the three head matmuls. This custom-VJP version:
#   * forward: ONE [T, V] fp32 materialization (the matmul output),
#     read twice (lse, gold-via-iota-compare); no gather;
#   * backward: recomputes logits (one extra matmul — cheaper than
#     storing [T, V]), forms d_logits = (softmax - onehot) * coef in
#     one fused pass in bf16, then the two grad matmuls;
#   * residuals are h, w, lse, gold — O(T) not O(T*V).
# The reference delegates this to torch CE inside vLLM/torch workers;
# the TPU design needs it fused for the same reason flash attention
# does (HBM bandwidth is the ceiling, SURVEY §5.7).


@jax.custom_vjp
def _fused_nll(h, w, targets):
    """Per-token negative log-likelihood of a linear head.

    h: [T, D] (bf16 typical), w: [D, V], targets: [T] int32 -> [T] f32.
    """
    nll, _ = _fused_nll_fwd(h, w, targets)
    return nll


def _logits_f32(h, w):
    return jax.lax.dot_general(
        h, w.astype(h.dtype), (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [T, V] fp32 accumulation off bf16 operands (full-rate MXU)


def _fused_nll_fwd(h, w, targets):
    logits = _logits_f32(h, w)
    m = jnp.max(logits, axis=-1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), axis=-1))
    # gold logit via iota-compare reduction: a [T, V] compare+select
    # feeding a row sum fuses into one pass; take_along_axis lowers to
    # a slow TPU gather (and a scatter in the backward)
    V = w.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, V), 1)
    gold = jnp.sum(
        jnp.where(iota == targets[:, None], logits, 0.0), axis=-1
    )
    return lse - gold, (h, w, targets, lse)


def _fused_nll_bwd(res, g):  # g: [T] f32 cotangent of nll
    h, w, targets, lse = res
    logits = _logits_f32(h, w)  # recompute: cheaper than storing [T, V]
    V = w.shape[1]
    iota = jax.lax.broadcasted_iota(jnp.int32, (1, V), 1)
    p = jnp.exp(logits - lse[:, None])
    onehot = (iota == targets[:, None]).astype(jnp.float32)
    dl = ((p - onehot) * g[:, None]).astype(h.dtype)  # [T, V] bf16
    dh = jax.lax.dot_general(
        dl, w.astype(h.dtype), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(h.dtype)
    dw = jax.lax.dot_general(
        h, dl, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(w.dtype)
    return dh, dw, None


_fused_nll.defvjp(_fused_nll_fwd, _fused_nll_bwd)


def fused_cross_entropy_loss(
    h: jax.Array,        # [B, S, D] final hidden states (pre lm-head)
    w: jax.Array,        # [D, V] lm-head weight
    targets: jax.Array,  # [B, S] int32
    mask: jax.Array | None = None,  # [B, S] 0/1
) -> tuple[jax.Array, jax.Array]:
    """(mean_nll, total_weight) without materializing fp32 softmax state."""
    B, S, D = h.shape
    nll = _fused_nll(h.reshape(B * S, D), w, targets.reshape(B * S))
    nll = nll.reshape(B, S)
    if mask is None:
        mask = jnp.ones_like(nll)
    mask = mask.astype(jnp.float32)
    total = jnp.maximum(mask.sum(), 1.0)
    return (nll * mask).sum() / total, total


Initializer = Callable[[jax.Array, tuple[int, ...]], jax.Array]
