"""Functional NN building blocks (pure jax, pytree params).

The framework's model layer is deliberately functional: params are plain
pytrees built next to a parallel pytree of logical-axis annotations
(see ray_tpu.parallel.sharding). No module objects, no tracing magic —
everything stays jit/scan/shard_map-friendly.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-5) -> jax.Array:
    """RMSNorm in fp32 regardless of input dtype (numerics on the VPU are cheap)."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jax.lax.rsqrt(var + eps)
    return (y * scale.astype(jnp.float32)).astype(x.dtype)


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 500000.0) -> tuple[jax.Array, jax.Array]:
    """Precompute RoPE cos/sin tables [max_seq, head_dim//2] in fp32."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_seq, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array, positions: jax.Array) -> jax.Array:
    """Rotate pairs of features. x: [B, S, H, D]; positions: [B, S] or [S]."""
    c = cos[positions]  # [..., S, D/2]
    s = sin[positions]
    if c.ndim == 2:  # positions was [S]
        c = c[None, :, None, :]
        s = s[None, :, None, :]
    else:  # [B, S, D/2]
        c = c[:, :, None, :]
        s = s[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)


def swiglu(x: jax.Array, w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array) -> jax.Array:
    gate = jnp.einsum("bsd,df->bsf", x, w_gate.astype(x.dtype))
    up = jnp.einsum("bsd,df->bsf", x, w_up.astype(x.dtype))
    return jnp.einsum("bsf,fd->bsd", jax.nn.silu(gate) * up, w_down.astype(x.dtype))


def init_dense(key: jax.Array, shape: tuple[int, ...], dtype, scale: float | None = None) -> jax.Array:
    """Truncated-normal fan-in init."""
    fan_in = shape[0]
    std = scale if scale is not None else 1.0 / jnp.sqrt(fan_in)
    return (jax.random.truncated_normal(key, -3, 3, shape, jnp.float32) * std).astype(dtype)


def cross_entropy_loss(
    logits: jax.Array,  # [B, S, V] (any float dtype; upcast internally)
    targets: jax.Array,  # [B, S] int32
    mask: jax.Array | None = None,  # [B, S] 0/1
) -> tuple[jax.Array, jax.Array]:
    """Returns (mean_nll, total_weight). fp32 log-softmax for stability."""
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    nll = logz - gold
    if mask is None:
        mask = jnp.ones_like(nll)
    mask = mask.astype(jnp.float32)
    total = jnp.maximum(mask.sum(), 1.0)
    return (nll * mask).sum() / total, total


Initializer = Callable[[jax.Array, tuple[int, ...]], jax.Array]
