"""Audited-exception infrastructure shared by every analysis pass.

An allowlist is how a lint stays honest at scale: a true positive the
code is *deliberately* keeping (a forever-park in a daemon main(), an
RPC send under a lock whose hold-invariant is documented) gets an entry
— but every entry must carry a written justification, and an entry whose
code disappeared FAILS the lint. A stale audited exception is a lie
waiting to mask the next violation introduced under the same key.
"""

from __future__ import annotations

from typing import Optional

MIN_JUSTIFICATION = 10  # characters; a reason must actually say something


class Allowlist(dict):
    """``{key_tuple: justification}`` with used-entry tracking.

    Subclasses dict so existing callers (and the check_timeouts tier-1
    test) keep ``.items()`` / ``in`` / indexing. Passes call
    ``permits(key)`` at each would-be violation; after the scan,
    ``problems()`` reports unjustified and stale entries as violations
    in their own right.
    """

    def __init__(self, entries: Optional[dict] = None, *, label: str = "allowlist"):
        super().__init__(entries or {})
        self.label = label
        self.used: set = set()

    def permits(self, key) -> bool:
        """True when ``key`` is audited; marks the entry as used."""
        if key in self:
            self.used.add(key)
            return True
        return False

    def unjustified(self) -> list:
        """Keys whose justification is missing or too short to mean
        anything."""
        return [
            k for k, reason in self.items()
            if not isinstance(reason, str) or len(reason.strip()) < MIN_JUSTIFICATION
        ]

    def stale(self) -> list:
        """Entries never consumed by the scan that just ran."""
        return sorted(set(self) - self.used, key=str)

    def problems(self) -> list[str]:
        """Post-scan self-audit: unjustified entries + stale entries,
        formatted like pass violations so they fail the same gate."""
        out = []
        for key in self.unjustified():
            out.append(
                f"{_key_head(key)}: {self.label} entry {_key_tail(key)} has "
                "no written justification — say WHY the invariant holds"
            )
        for key in self.stale():
            out.append(
                f"{_key_head(key)}: stale {self.label} entry {_key_tail(key)}"
                " — the call it audited no longer exists; remove it"
            )
        return out


def _key_head(key) -> str:
    return str(key[0]) if isinstance(key, tuple) and key else str(key)


def _key_tail(key) -> str:
    if isinstance(key, tuple) and len(key) > 1:
        return "/".join(str(p) for p in key[1:])
    return str(key)
