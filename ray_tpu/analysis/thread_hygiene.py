"""Thread-hygiene lint: every ``threading.Thread`` must be daemonized or
joined on a reachable shutdown path.

A non-daemon thread nobody joins keeps the process alive after main()
returns — the bench-helper hang — and a *daemon* thread nobody joins is
fine for the interpreter but still a leak if its loop pins resources.
The enforced rule is the cheap, checkable core: ``daemon=True`` at
construction, OR the thread object lands somewhere (``self._t = ...``,
``t = ...``, ``pool.append(t)``) that a ``.join()`` in the same file
reaches (direct ``name.join()``, or ``for t in pool: t.join()`` covering
the container it was appended into).

Exceptions (e.g. a thread whose join lives in another module) go in
``ALLOWLIST`` keyed by ``(file, function)`` with a written reason.
"""

from __future__ import annotations

import ast

from ray_tpu.analysis import lockmodel
from ray_tpu.analysis.allowlist import Allowlist
from ray_tpu.analysis.walker import DEFAULT_PACKAGES, has_kwarg, iter_files

ALLOWLIST = Allowlist(label="thread-hygiene allowlist")

# this pass also scans the bench helpers: driver threads leaked there
# hang the bench process exactly like a leaked runtime thread would.
# Single source of truth for the CLI, the umbrella runner, and the
# tier-1 gate.
SCAN_PACKAGES = tuple(DEFAULT_PACKAGES) + ("benchmarks",)


def _daemon_true(node) -> bool:
    for kw in node.keywords:
        if kw.arg == "daemon":
            return isinstance(kw.value, ast.Constant) and kw.value.value is True
    return False


def check_model(model: lockmodel.FileModel,
                allowlist: Allowlist | None = None) -> list[str]:
    al = ALLOWLIST if allowlist is None else allowlist
    # containers whose elements get joined, plus names appended into them
    covered_names: set[str] = set(model.joined_names)
    for container, member in model.appends:
        if container in model.join_covered_containers:
            covered_names.add(member)
    out = []
    for th in model.threads:
        if _daemon_true(th.node):
            continue
        if has_kwarg(th.node, "daemon"):
            # daemon=<expr>: defer to the expression's author
            continue
        target = th.target_name
        joined = (
            (target is not None and target in covered_names)
            or (th.stored_into is not None
                and th.stored_into in model.join_covered_containers)
        )
        if joined:
            continue
        key = (model.rel, th.func.split(".", 1)[0])
        if al.permits(key):
            continue
        out.append(
            f"{model.rel}:{th.line}: Thread created in {th.func} is neither "
            "daemon=True nor joined on any path in this file — a leaked "
            "non-daemon thread outlives main(); pass daemon=True or join "
            "it on the shutdown path"
        )
    return out


def collect_violations(packages=None, root=None,
                       allowlist: Allowlist | None = None) -> list[str]:
    if packages is None:
        packages = SCAN_PACKAGES
    al = ALLOWLIST if allowlist is None else allowlist
    al.used.clear()
    out: list[str] = []
    for sf in iter_files(packages, root):
        model = lockmodel.build_file_model(sf.tree, sf.rel)
        out.extend(check_model(model, al))
    out.extend(al.problems())
    return out
