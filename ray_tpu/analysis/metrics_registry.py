"""Live metrics-registry lint (the original ``check_metrics``, now under
the shared analysis umbrella).

Unlike the AST passes this one runs against the LIVE registry: it
imports every instrumented module, forces lazily-registered metrics to
register, then walks ``util/metrics``'s registry and fails on missing
descriptions, names outside the ``ray_tpu_``/``llm_`` conventions,
type conflicts (including histogram ``_sum``/``_count``/``_bucket``
exposition-series collisions), and telemetry-plane gauges with no
declared aggregation kind.

CLI shim: ``python scripts/check_metrics.py`` (exit 1 on problems).
"""

from __future__ import annotations

import re

# every module that registers metrics, plus the hook that forces lazy
# singletons to register (None = import alone registers / no hook)
INSTRUMENTED = [
    ("ray_tpu.obs.slo", "register_all"),
    ("ray_tpu.obs.telemetry", "register_metrics"),
    ("ray_tpu.profiler.trace", None),
    ("ray_tpu.llm.decode_loop", "chunk_histogram"),
    ("ray_tpu.llm.pipeline", "register_metrics"),
    ("ray_tpu.llm.spec.stats", "_spec_metrics"),
    ("ray_tpu.llm.admission", "register_metrics"),
    ("ray_tpu.llm.engine", "register_metrics"),
    ("ray_tpu.cluster.node_daemon", "register_metrics"),
    ("ray_tpu.cluster.gcs_service", "register_metrics"),
    ("ray_tpu.serve.controller", "register_metrics"),
    ("ray_tpu.train.elastic", "register_metrics"),
    ("ray_tpu.fabric.metrics", "register_metrics"),
    ("ray_tpu.llm.kvtier.metrics", "register_metrics"),
    ("ray_tpu.llm.kvfetch.metrics", "register_metrics"),
    ("ray_tpu.rl.post_train.metrics", "register_metrics"),
    ("ray_tpu.autoscale.metrics", "register_metrics"),
    ("ray_tpu.fleet.metrics", "register_metrics"),
    ("ray_tpu.obs.perfwatch.metrics", "register_metrics"),
    ("ray_tpu.cluster.lockstats", "register_metrics"),
]

_NAME_RE = re.compile(r"^(ray_tpu|llm)_[a-z0-9][a-z0-9_]*$")
_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def register_instrumented_metrics() -> list[str]:
    """Import instrumented modules + fire their registration hooks;
    returns import problems (a module that stops importing is itself a
    regression this gate should catch)."""
    import importlib

    problems = []
    for mod_name, hook in INSTRUMENTED:
        try:
            mod = importlib.import_module(mod_name)
            if hook is not None:
                getattr(mod, hook)()
        except Exception as e:  # noqa: BLE001
            problems.append(f"{mod_name}: import/registration failed: {e!r}")
    # profiler.trace registers via explicit constructors
    try:
        from ray_tpu.profiler import trace as ptrace

        ptrace.segment_histogram()
        ptrace.coverage_gauge()
        ptrace.step_ms_gauge()
    except Exception as e:  # noqa: BLE001
        problems.append(f"ray_tpu.profiler.trace hooks failed: {e!r}")
    return problems


def check_registry() -> list[str]:
    """Walk the live registry; returns a list of problem strings."""
    from ray_tpu.util.metrics import Histogram, registry_snapshot

    problems = []
    metrics = registry_snapshot()
    seen: dict[str, str] = {}
    hist_names = {m.name for m in metrics if isinstance(m, Histogram)}
    for m in metrics:
        if not m.description.strip():
            problems.append(f"{m.name}: missing description")
        if not _NAME_RE.match(m.name):
            problems.append(
                f"{m.name}: name outside the ray_tpu_/llm_ convention "
                "(lowercase, [a-z0-9_], subsystem-prefixed)"
            )
        prior = seen.get(m.name)
        if prior is not None and prior != m.TYPE:
            problems.append(
                f"{m.name}: registered as both {prior} and {m.TYPE}"
            )
        seen[m.name] = m.TYPE
        # a non-histogram named <hist>_sum/_count/_bucket collides with
        # the exposition series histogram <hist> generates
        for suffix in _HIST_SUFFIXES:
            if m.name.endswith(suffix) and m.name[: -len(suffix)] in hist_names:
                problems.append(
                    f"{m.name}: collides with histogram "
                    f"{m.name[:-len(suffix)]!r}'s {suffix} series"
                )
    return problems


def check_aggregations() -> list[str]:
    """Telemetry-plane lint: every gauge/counter under the aggregated
    name prefixes must resolve to a valid aggregation kind. Counters
    default to sum; gauges must be explicitly declared (sum vs max is a
    semantic choice the metric's owner makes — see obs/telemetry.py)."""
    from ray_tpu.obs import telemetry
    from ray_tpu.util.metrics import registry_snapshot

    problems = []
    for m in registry_snapshot():
        if m.TYPE == "histogram":
            continue  # bucket merge is the only sane histogram rollup
        if not m.name.startswith(telemetry.AGGREGATED_PREFIXES):
            continue
        kind = telemetry.aggregation_kind(m.name, m.TYPE)
        if kind is None:
            problems.append(
                f"{m.name}: telemetry-plane {m.TYPE} with no declared "
                "aggregation kind (declare sum/max via "
                "obs.telemetry.declare_aggregation or the cluster_* helpers)"
            )
        elif kind not in telemetry.VALID_AGGREGATIONS:
            problems.append(
                f"{m.name}: invalid aggregation kind {kind!r}"
            )
    return problems


def run_check() -> list[str]:
    return (register_instrumented_metrics() + check_registry()
            + check_aggregations())


def main() -> int:
    problems = run_check()
    if problems:
        print(f"check_metrics: {len(problems)} problem(s):")
        for p in problems:
            print(f"  - {p}")
        return 1
    from ray_tpu.util.metrics import registry_snapshot

    print(f"check_metrics: ok ({len(registry_snapshot())} metrics clean)")
    return 0
