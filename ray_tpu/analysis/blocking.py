"""Blocking-call-under-lock: a stalled peer must never stall every other
caller of the lock.

"Exploring the limits of Concurrency in ML Training on Google TPUs"
(PAPERS.md) makes the availability case: one thread parked under a lock
the hot path needs stalls a whole pod. The pass flags calls that can
block on something OUTSIDE the process-local lock discipline — an RPC
send, a socket receive, a sleep, a thread join, a GCS ``kv_wait``, a
chaos-hook ``fire`` (an injected DELAY would serialize behind the lock)
— executed while any known lock is held.

The one systematic exemption: ``cv.wait()`` / ``cv.wait_for()`` on a
Condition whose lock is the ONLY lock held — waiting releases that lock;
that is the entire point of conditions. Holding a *second* lock while
waiting is still flagged (the wait releases only its own lock).

Everything else goes through ``ALLOWLIST`` keyed by
``(file, function, call name)`` with a written hold-invariant.
"""

from __future__ import annotations

import ast

from ray_tpu.analysis import lockmodel
from ray_tpu.analysis.allowlist import Allowlist
from ray_tpu.analysis.walker import DEFAULT_PACKAGES, iter_files

# call names that can park the calling thread on an external event.
# ``call`` is the cluster RPC send (cluster/client.py, rpc.py); ``fire``
# is the chaos hook (an injected DELAY_RPC sleeps at the hook site).
BLOCKING_CALLS = frozenset({
    "sleep",
    "recv", "recv_into", "recvfrom", "recv_bytes", "readexactly", "accept",
    "connect", "sendall", "send_frame",
    "call", "kv_wait",
    "wait", "wait_for",
    "join",
    "fire",
})

ALLOWLIST = Allowlist({
    ("cluster/rpc.py", "call", "sendall"): (
        "_wlock IS the frame-serialization lock: writes to one socket "
        "must be serialized, so snapshot-then-send-outside cannot exist "
        "here. The native path bounds the write with a poll timeout "
        "derived from the client timeout; the pure-python sendall "
        "fallback rides the audited no-socket-timeout invariant "
        "(check_timeouts: a timeout-mode sendall can abandon a frame "
        "mid-write, bytes-sent indeterminate, and corrupt the stream)"
    ),
}, label="blocking-under-lock allowlist")


def _condition_roots(model: lockmodel.FileModel, owner: str) -> dict[str, str]:
    """{condition attr/global name: canonical root ident} for conditions
    owned by ``owner`` (waiting on one releases its root)."""
    out = {}
    for info in model.locks.values():
        if info.owner == owner and info.kind == "condition":
            root = model.lock_root(info.owner, info.name)
            if root is not None:
                out[info.name] = root
    return out


def check_model(model: lockmodel.FileModel,
                allowlist: Allowlist | None = None) -> list[str]:
    al = ALLOWLIST if allowlist is None else allowlist
    out = []
    for call in model.calls:
        if call.name not in BLOCKING_CALLS or not call.held:
            continue
        if _is_self_method(model, call):
            continue  # self.wait()/self.join() on own class: the
            # one-hop lock_order pass judges what the callee does
        if _is_exempt_condition_wait(model, call):
            continue
        if call.name == "join" and not _looks_like_thread_join(call.node):
            continue  # "-".join(parts) / os.path.join(...) are not parks
        key = (model.rel, call.func.split(".", 1)[0], call.name)
        if al.permits(key):
            continue
        held = ", ".join(sorted(call.held))
        recv = f"{call.receiver}.{call.name}" if call.receiver else call.name
        out.append(
            f"{model.rel}:{call.line}: blocking {recv}() while holding "
            f"{held} (in {call.func}) — a stalled peer stalls every "
            "caller of the lock; snapshot under the lock, block outside it"
        )
    return out


def _looks_like_thread_join(node: ast.Call) -> bool:
    """Thread/process joins are ``t.join()`` or ``t.join(timeout)`` /
    ``t.join(timeout=...)``; ``sep.join(iterable)`` and
    ``os.path.join(a, b, ...)`` take string/iterable positionals."""
    if len(node.args) > 1:
        return False
    if (isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Constant)):
        return False  # "sep".join(...)
    if node.args:
        arg = node.args[0]
        return (isinstance(arg, ast.Constant)
                and isinstance(arg.value, (int, float)))
    return True


def _is_self_method(model: lockmodel.FileModel, call) -> bool:
    return (call.receiver == "self"
            and call.name in model.class_methods.get(call.owner, ()))


def _is_exempt_condition_wait(model: lockmodel.FileModel, call) -> bool:
    if call.name not in ("wait", "wait_for") or call.receiver is None:
        return False
    cv_name = call.receiver.removeprefix("self.")
    root = _condition_roots(model, call.owner).get(cv_name)
    if root is None and call.owner != lockmodel.MODULE:
        root = _condition_roots(model, lockmodel.MODULE).get(call.receiver)
    return root is not None and call.held == frozenset({root})


def collect_violations(packages=DEFAULT_PACKAGES, root=None,
                       allowlist: Allowlist | None = None) -> list[str]:
    al = ALLOWLIST if allowlist is None else allowlist
    al.used.clear()
    out: list[str] = []
    for sf in iter_files(packages, root):
        model = lockmodel.build_file_model(sf.tree, sf.rel)
        out.extend(check_model(model, al))
    out.extend(al.problems())
    return out
