"""Shared AST walking scaffolding for the analysis passes.

One place owns "find the repo, iterate a package's Python files, parse
them, track the enclosing-function stack" so each pass is only its rule.
``check_timeouts`` and ``check_metrics`` predate this module and carried
private copies; they now ride it (scripts/ keeps thin CLI shims).
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Iterable, Iterator, Optional

# The heavily-threaded planes every concurrency pass scans by default.
# util/ is deliberately out of scope here: its primitives (metrics
# registry, queues) are the *implementations* the passes model, and the
# registry lint covers them through the live registry instead.
DEFAULT_PACKAGES = (
    "ray_tpu/cluster",
    "ray_tpu/serve",
    "ray_tpu/llm",
    "ray_tpu/collective",
    "ray_tpu/dag",
    "ray_tpu/core",
    "ray_tpu/obs",
    "ray_tpu/train",
    "ray_tpu/chaos",
    # the device-direct transfer plane: sender/receiver loops + topology
    # state ride the same peer-may-die, lock-guarded substrate
    "ray_tpu/fabric",
    # the native socket/shm plane rides the same peer-may-die substrate
    # the timeouts pass already scans — the lock passes cover it too
    "ray_tpu/native",
    # r19: the RL post-training actor/learner plane — trajectory queue,
    # feeder batch cache, and the async publish worker are all
    # lock-guarded structures shared across the two tiers' threads
    "ray_tpu/rl/post_train",
    # r20: the autoscale control loop — a controller thread ticking
    # against GCS telemetry while actuators mutate shared pool maps
    "ray_tpu/autoscale",
    # r21: the multi-tenant fleet plane — replica runner threads, the
    # QoS admission tables, and the canary weight plane share state
    # between the ingress and every replica's engine loop
    "ray_tpu/fleet",
    # r24: the kernel tier (ragged/paged/flash attention) — pure jax
    # today, but it feeds the engine's hot path; scanned so any future
    # host-side state (capture caches, interpreter shims) inherits the
    # discipline from day one
    "ray_tpu/ops",
)


def repo_root() -> str:
    """The repository root (two levels above this file's package)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@dataclasses.dataclass
class SourceFile:
    """One parsed module. ``rel`` is the repo-relative path with "/"
    separators and the leading ``ray_tpu/`` stripped — the key form the
    allowlists and violation strings use (stable across checkouts)."""

    rel: str
    path: str
    source: str
    tree: ast.Module


def rel_key(path: str, root: str) -> str:
    rel = os.path.relpath(path, root).replace(os.sep, "/")
    return rel.removeprefix("ray_tpu/")


def iter_files(packages: Iterable[str] = DEFAULT_PACKAGES,
               root: Optional[str] = None) -> Iterator[SourceFile]:
    """Yield every ``.py`` file under the given repo-relative package
    dirs, parsed, in deterministic (sorted) order."""
    base_root = root or repo_root()
    for pkg in packages:
        pkg_dir = os.path.join(base_root, pkg.replace("/", os.sep))
        for dirpath, dirs, files in os.walk(pkg_dir):
            dirs.sort()
            for f in sorted(files):
                if not f.endswith(".py"):
                    continue
                path = os.path.join(dirpath, f)
                with open(path, encoding="utf-8") as fh:
                    source = fh.read()
                yield SourceFile(
                    rel=rel_key(path, base_root),
                    path=path,
                    source=source,
                    tree=ast.parse(source),
                )


def call_name(node: ast.Call) -> Optional[str]:
    """The called name: ``attr`` for ``x.attr(...)``, ``id`` for
    ``name(...)``, None for anything fancier."""
    if isinstance(node.func, ast.Attribute):
        return node.func.attr
    if isinstance(node.func, ast.Name):
        return node.func.id
    return None


def call_receiver(node: ast.Call) -> Optional[str]:
    """For ``x.attr(...)``: ``x`` if the receiver is a bare name,
    ``self.y`` if it is a self attribute; else None."""
    if not isinstance(node.func, ast.Attribute):
        return None
    val = node.func.value
    if isinstance(val, ast.Name):
        return val.id
    if (isinstance(val, ast.Attribute) and isinstance(val.value, ast.Name)
            and val.value.id == "self"):
        return f"self.{val.attr}"
    return None


def has_kwarg(node: ast.Call, name: str) -> bool:
    return any(kw.arg == name for kw in node.keywords)


def self_attr(node: ast.AST) -> Optional[str]:
    """``x`` when ``node`` is exactly ``self.x``; else None."""
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


class FuncStackVisitor(ast.NodeVisitor):
    """NodeVisitor that maintains the enclosing-function-name stack —
    the scope scaffolding every pass needs. Subclasses read
    ``self.func_stack`` / ``self.scope()`` and may override
    ``enter_function``/``leave_function`` for per-scope state."""

    def __init__(self) -> None:
        self.func_stack: list[str] = []

    def scope(self) -> str:
        return self.func_stack[-1] if self.func_stack else "<module>"

    def enter_function(self, node) -> None:  # pragma: no cover - hook
        pass

    def leave_function(self, node) -> None:  # pragma: no cover - hook
        pass

    def _visit_func(self, node) -> None:
        self.func_stack.append(node.name)
        self.enter_function(node)
        self.generic_visit(node)
        self.leave_function(node)
        self.func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func
