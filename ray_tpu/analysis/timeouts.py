"""Static blocking-call timeout lint (the original ``check_timeouts``,
now on the shared analysis framework).

The control plane's availability story (heartbeat death verdicts, lease
retries, chaos-driven failover) only works if no thread can block
FOREVER on a peer that silently died: every blocking socket/RPC receive
in ``ray_tpu/cluster/``, ``ray_tpu/native/``, ``ray_tpu/collective/``
and ``ray_tpu/dag/`` must carry an explicit timeout. Fails on:

 * ``settimeout(None)`` — an explicit opt-in to unbounded blocking;
 * bare receive-family calls (``recv`` / ``recv_into`` / ``recvfrom`` /
   ``recv_bytes`` / ``readexactly`` / ``accept``) with no ``timeout``
   argument in a scope that never set a bounded socket timeout;
 * zero-argument ``.wait()`` / ``.get()`` / ``.result()`` — unbounded
   thread parks (Event/Condition/queue/Future);
 * ``wait_for``/``kv_wait`` without their timeout operand.

Audited exceptions live in ``ALLOWLIST`` (analysis/allowlist.py: every
entry carries a justification, stale entries are violations).

CLI shim: ``python scripts/check_timeouts.py`` (exit 1 on problems).
"""

from __future__ import annotations

import ast
import os

from ray_tpu.analysis.allowlist import Allowlist
from ray_tpu.analysis.walker import FuncStackVisitor, call_name, has_kwarg, repo_root

RECV_CALLS = {
    "recv", "recv_into", "recvfrom", "recv_bytes", "readexactly", "accept",
}
PARK_CALLS = {"wait", "get", "result"}
# park-calls whose timeout is a REQUIRED trailing positional (or kwarg):
# Condition.wait_for(pred[, timeout]) and the GCS kv_wait(key, ns,
# timeout) — the collective plane's rendezvous primitives. Calling them
# without the timeout operand is an unbounded park.
BOUNDED_PARK_MIN_ARGS = {"wait_for": 2, "kv_wait": 3}

# (path suffix, enclosing function name, call attr) -> reason
ALLOWLIST = Allowlist({
    ("cluster/rpc.py", "connect", "settimeout"): (
        "clears create_connection's lingering timeout: timeout-mode "
        "sendall can abandon a frame mid-write (bytes sent indeterminate) "
        "and corrupt the stream; sends must block, the read loop bounds "
        "itself with select() polls"
    ),
    ("cluster/rpc.py", "_on_conn", "readexactly"): (
        "asyncio server-side connection reader: a stalled client parks one "
        "coroutine (not a thread); connection close/cancellation unblocks it"
    ),
    ("cluster/gcs_service.py", "main", "wait"): (
        "daemon main(): intentional forever-park of the entry thread; "
        "SIGINT/SIGTERM are the designed wakeups"
    ),
    ("cluster/node_daemon.py", "main", "wait"): (
        "daemon main(): intentional forever-park; SIGTERM triggers the "
        "graceful-drain handler"
    ),
    ("cluster/worker_main.py", "main", "wait"): (
        "worker main(): intentional forever-park; the daemon kills the "
        "process when its lease ends"
    ),
})

SCAN_DIRS = (
    "ray_tpu/cluster", "ray_tpu/native", "ray_tpu/collective",
    # r13: the compiled-DAG channel plane — exec loops ride the same
    # peer-may-die substrate as the collectives, so its reads/parks must
    # be bounded too (ChannelTimeoutError instead of a hung loop)
    "ray_tpu/dag",
    # r15: the fabric transfer plane — endpoint receives must poll
    # bounded (a transfer plane never parks a consumer loop forever)
    "ray_tpu/fabric",
    # r17: the tiered prefix cache — object-store gets and index RPCs
    # sit on the prefill admission path, so every park must be bounded
    "ray_tpu/llm/kvtier",
    # r18: the cross-engine fetch plane + prefetch/spill workers — a
    # dead fetch source or a stalled endpoint must fail typed within
    # its bound, and the worker loops must park in bounded slices
    "ray_tpu/llm/kvfetch",
    # r19: the RL post-training planes — a starved trajectory queue or
    # a wedged publish must park in bounded slices (the learner gang's
    # fault detector must never be the thing that notices)
    "ray_tpu/rl/post_train",
    # r20: the autoscale controller — signal fetches and actuator calls
    # cross the RPC plane, so every wait must carry its bound
    "ray_tpu/autoscale",
    # r21: the fleet plane — request submission crosses replica runner
    # queues and the canary ladder polls SLO grades; both must park in
    # bounded slices
    "ray_tpu/fleet",
    # r22: the perfwatch sampler — its probe loop parks between ladder
    # runs and its stop() joins the thread; both must carry bounds (an
    # observability plane must never be the thing that hangs shutdown)
    "ray_tpu/obs/perfwatch",
    # r24: the kernel tier (pure jax/pallas — no parks today, but ops
    # code grows host callbacks and test harnesses; scanning from day
    # one keeps the floor in place) and the mixed-batch planner, which
    # sits directly on the engine's step path
    "ray_tpu/ops",
    "ray_tpu/llm/mixed.py",
)


class _Linter(FuncStackVisitor):
    def __init__(self, rel_path: str):
        super().__init__()
        self.rel = rel_path
        # scopes where a bounded settimeout() was seen (function names)
        self.bounded_scopes: set[str] = set()
        self.violations: list[str] = []
        self.used_allowlist: set[tuple] = set()

    def _allowed(self, call_name_: str) -> bool:
        for fn in self.func_stack or ["<module>"]:
            key = (self.rel, fn, call_name_)
            if ALLOWLIST.permits(key):
                self.used_allowlist.add(key)
                return True
        return False

    # -- the rules ------------------------------------------------------------

    def visit_Call(self, node: ast.Call):
        name = call_name(node)
        if name == "settimeout":
            args = node.args
            if args and isinstance(args[0], ast.Constant) and args[0].value is None:
                if not self._allowed("settimeout"):
                    self.violations.append(
                        f"{self.rel}:{node.lineno}: settimeout(None) — "
                        "unbounded socket block; set a poll timeout and "
                        "re-check a stop flag"
                    )
            elif args:
                for fn in self.func_stack:
                    self.bounded_scopes.add(fn)
        elif name == "select" and len(node.args) >= 4:
            # select.select(r, w, x, timeout): a readability poll with a
            # timeout bounds the recv that follows it in this scope
            for fn in self.func_stack:
                self.bounded_scopes.add(fn)
        elif name in RECV_CALLS and isinstance(node.func, ast.Attribute):
            covered = any(fn in self.bounded_scopes for fn in self.func_stack)
            if not covered and not has_kwarg(node, "timeout"):
                if not self._allowed(name):
                    self.violations.append(
                        f"{self.rel}:{node.lineno}: blocking {name}() with no "
                        "timeout in scope (no bounded settimeout on this "
                        "path, no timeout= argument)"
                    )
        elif (
            name in PARK_CALLS
            and isinstance(node.func, ast.Attribute)
            and not node.args
            and not node.keywords
        ):
            if not self._allowed(name):
                self.violations.append(
                    f"{self.rel}:{node.lineno}: zero-argument .{name}() — "
                    "unbounded park; pass a timeout and loop on a stop flag"
                )
        elif (
            name in BOUNDED_PARK_MIN_ARGS
            and isinstance(node.func, ast.Attribute)
            and len(node.args) < BOUNDED_PARK_MIN_ARGS[name]
            and not has_kwarg(node, "timeout")
        ):
            if not self._allowed(name):
                self.violations.append(
                    f"{self.rel}:{node.lineno}: .{name}() without its "
                    "timeout operand — unbounded park on a peer that may "
                    "never arrive"
                )
        self.generic_visit(node)


def lint_source(src: str, rel_path: str,
                used_allowlist: "set | None" = None) -> list[str]:
    """Lint one file's source; returns violation strings. Consumed
    ALLOWLIST keys are added to ``used_allowlist`` when given."""
    tree = ast.parse(src)
    # two passes: settimeout()/select() may appear after a nested
    # function's definition but cover calls made at runtime — collect
    # bounded scopes first, then judge
    first = _Linter(rel_path)
    first.visit(tree)
    second = _Linter(rel_path)
    second.bounded_scopes = first.bounded_scopes
    second.visit(tree)
    if used_allowlist is not None:
        used_allowlist.update(second.used_allowlist)
    return second.violations


def collect_violations(repo_root_: str | None = None) -> list[str]:
    root = repo_root_ or repo_root()
    out: list[str] = []
    ALLOWLIST.used.clear()
    used: set = set()
    for scan in SCAN_DIRS:
        base = os.path.join(root, scan)
        if os.path.isfile(base):
            # single-file entries (e.g. ray_tpu/llm/mixed.py) — os.walk
            # on a file path yields nothing and would silently scan zero
            # lines
            paths = [base]
        else:
            paths = [
                os.path.join(dirpath, f)
                for dirpath, _dirs, files in os.walk(base)
                for f in sorted(files)
                if f.endswith(".py")
            ]
        for path in paths:
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            rel = rel.removeprefix("ray_tpu/")
            with open(path, encoding="utf-8") as fh:
                out.extend(lint_source(fh.read(), rel, used))
    # the shared allowlist self-audit: unjustified entries + stale
    # entries (an audited exception that no longer matches any code is a
    # lie waiting to mask the next unbounded call under the same key)
    ALLOWLIST.used.update(used)
    out.extend(ALLOWLIST.problems())
    return out


def main() -> int:
    problems = collect_violations()
    if problems:
        print(f"check_timeouts: {len(problems)} problem(s)")
        for p in problems:
            print(f"  {p}")
        return 1
    print("check_timeouts: ok")
    return 0
