"""Perf regression gate over the capture ledger (``check_perf``).

Three verdicts when a fresh capture meets the ledger:

 * **fail** — a metric regressed past its tolerance band vs the most
   recent SAME-FINGERPRINT entry of the same bench family. The failure
   names the metric, both values, and the band (a perf gate that just
   says "regressed" is a perf gate people disable).
 * **record (fingerprint mismatch)** — no same-fingerprint baseline
   exists. The first TPU capture of a family never fights a CPU
   baseline; it records as the new baseline for its own hardware.
 * **record (missing baseline)** — the family has no ledger entry at
   all; the capture records.

Tier-1 / lint mode (``run_check``): validates the whole ledger — every
capture file enveloped, every envelope schema-valid, every capture's
band math self-consistent (a capture must PASS when gated against
itself; a NaN value or an inverted band surfaces here, not in the first
real comparison months later).

CLI shim: ``python scripts/check_perf.py`` (ledger check), or
``python scripts/check_perf.py --capture fresh.json`` to gate fresh
captures against the ledger (exit 1 on regression).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Optional

from ray_tpu.obs.perfwatch.ledger import (
    BETTER_HIGHER,
    CaptureLedger,
    MetricSpec,
    envelope_of,
    fingerprints_match,
    load_capture,
    validate_envelope,
)

PASS = "pass"
FAIL = "fail"
RECORD = "record"


@dataclasses.dataclass
class GateResult:
    status: str                 # PASS | FAIL | RECORD
    bench: str
    reason: str
    failures: list[str] = dataclasses.field(default_factory=list)
    baseline_path: Optional[str] = None

    @property
    def ok(self) -> bool:
        return self.status != FAIL


def compare_metric(name: str, fresh: MetricSpec,
                   base: MetricSpec) -> Optional[str]:
    """One band comparison; returns a failure string or None.

    The BASELINE's band applies (the checked-in capture owns its own
    noise model); direction comes from the baseline too — a fresh
    capture cannot relax a gate by flipping ``better``."""
    fv, bv = float(fresh.value), float(base.value)
    if base.better == BETTER_HIGHER:
        floor = bv * (1.0 - base.rel_tol) - base.abs_tol
        if fv < floor:
            return (
                f"{name}: {fv:g}{base.unit and ' ' + base.unit} regressed "
                f"below band floor {floor:g} (baseline {bv:g}, "
                f"rel_tol {base.rel_tol:g})"
            )
    else:
        ceil = bv * (1.0 + base.rel_tol) + base.abs_tol
        if fv > ceil:
            return (
                f"{name}: {fv:g}{base.unit and ' ' + base.unit} regressed "
                f"above band ceiling {ceil:g} (baseline {bv:g}, "
                f"rel_tol {base.rel_tol:g})"
            )
    return None


def evaluate_capture(fresh_doc: dict, baseline_doc: dict,
                     baseline_path: Optional[str] = None) -> GateResult:
    """Band math between two enveloped captures of the same family.
    Metrics only the baseline has are ignored (a bench may drop a
    number); metrics only the fresh capture has record silently (new
    numbers start their own history)."""
    fresh_env = envelope_of(fresh_doc) or {}
    base_env = envelope_of(baseline_doc) or {}
    bench = fresh_env.get("bench", "?")
    failures = []
    compared = 0
    base_metrics = base_env.get("metrics") or {}
    for name, spec in (fresh_env.get("metrics") or {}).items():
        base_spec = base_metrics.get(name)
        if base_spec is None:
            continue
        compared += 1
        problem = compare_metric(
            name, MetricSpec.from_dict(spec), MetricSpec.from_dict(base_spec))
        if problem:
            failures.append(f"{bench}: {problem}")
    if failures:
        return GateResult(FAIL, bench,
                          f"{len(failures)} metric(s) regressed past band",
                          failures, baseline_path)
    return GateResult(PASS, bench, f"{compared} metric(s) within band",
                      baseline_path=baseline_path)


def gate_capture(fresh_doc: dict, ledger: Optional[CaptureLedger] = None, *,
                 exclude_path: Optional[str] = None) -> GateResult:
    """Gate one fresh capture against the ledger: find the most recent
    same-bench same-fingerprint entry; compare, or record."""
    ledger = ledger or CaptureLedger()
    env = envelope_of(fresh_doc)
    if env is None:
        return GateResult(FAIL, "?", "capture has no perfwatch envelope",
                          ["capture has no perfwatch envelope"])
    bench = env.get("bench", "?")
    fp = env.get("fingerprint")
    entries = ledger.entries(bench)
    if exclude_path is not None:
        ex = os.path.abspath(exclude_path)
        entries = [(p, d) for p, d in entries if os.path.abspath(p) != ex]
    if not entries:
        return GateResult(RECORD, bench,
                          "no baseline for this bench family — recording")
    for path, doc in entries:
        if fingerprints_match(envelope_of(doc).get("fingerprint"), fp):
            return evaluate_capture(fresh_doc, doc, path)
    return GateResult(
        RECORD, bench,
        "fingerprint mismatch vs every ledger entry (new hardware "
        "supersedes, it does not compare) — recording",
    )


def run_check(root: Optional[str] = None) -> list[str]:
    """Ledger-integrity pass (tier-1 + lint_all): every capture file
    enveloped, schema-valid, and self-consistent under the band math."""
    ledger = CaptureLedger(root)
    problems = []
    for path in ledger.unenveloped():
        problems.append(
            f"{os.path.basename(path)}: capture without a perfwatch "
            "envelope (run python -m ray_tpu.obs.perfwatch.migrate)"
        )
    for path, doc in ledger.entries():
        name = os.path.basename(path)
        for p in validate_envelope(doc):
            problems.append(f"{name}: {p}")
        # self-gate: a capture must sit inside its own band. Catches
        # NaN/negative-band corruption where it happened, and proves the
        # compare path runs over every migrated entry.
        result = evaluate_capture(doc, doc, path)
        if not result.ok:
            problems.extend(f"{name}: self-gate {f}" for f in result.failures)
    return problems


def main(argv: Optional[list] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--capture", action="append", default=[],
                    help="fresh capture file(s) to gate against the ledger")
    ap.add_argument("--ledger", default=None,
                    help="ledger directory (default: benchmarks/)")
    args = ap.parse_args(argv)

    ledger = CaptureLedger(args.ledger)
    rc = 0
    if args.capture:
        for path in args.capture:
            try:
                doc = load_capture(path)
            except (OSError, json.JSONDecodeError) as e:
                print(f"check_perf: {path}: unreadable: {e}")
                rc = 1
                continue
            result = gate_capture(doc, ledger, exclude_path=path)
            print(f"check_perf: {path}: {result.status} — {result.reason}"
                  + (f" (baseline {result.baseline_path})"
                     if result.baseline_path else ""))
            for f in result.failures:
                print(f"  - {f}")
            if not result.ok:
                rc = 1
        return rc

    problems = run_check(args.ledger)
    if problems:
        print(f"check_perf: {len(problems)} problem(s):")
        for p in problems:
            print(f"  - {p}")
        return 1
    n = len(ledger.entries())
    print(f"check_perf: ok ({n} enveloped captures, bands self-consistent)")
    return 0
