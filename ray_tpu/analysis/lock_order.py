"""Lock-order deadlock detection over the global acquisition graph.

Two rules, one graph:

 * **cycles** — an edge L -> M exists when M is acquired while L is held:
   nested ``with`` in one method, or one hop through a self-method call
   (method holds L, calls ``self.m()``, m acquires M). A cycle means two
   threads can each hold one lock and want the other — the classic
   deadlock no test reliably reproduces and chaos only finds by luck.
 * **non-reentrant self-acquisition** — an edge L -> L where L is a plain
   ``Lock`` (or ``Condition`` wrapping one) is not a *potential* deadlock
   but a CERTAIN one on any path that executes it: ``with self._lock:``
   then a call into a method that re-takes ``_lock``. RLock/bare-
   Condition self-edges are reentrant and ignored.

Lock identity is per (file, owner, attribute): cross-file edges would
need points-to analysis the model deliberately doesn't claim. A
justified exception (e.g. a self-edge on a branch that provably cannot
execute under the outer hold) goes in ``ALLOWLIST`` keyed by
``(file, "L->M")``.
"""

from __future__ import annotations

from ray_tpu.analysis import lockmodel
from ray_tpu.analysis.allowlist import Allowlist
from ray_tpu.analysis.walker import DEFAULT_PACKAGES, iter_files

ALLOWLIST = Allowlist(label="lock-order allowlist")


def build_edges(model: lockmodel.FileModel) -> dict[tuple, list[str]]:
    """{(L, M): [evidence site, ...]} in canonical lock idents, scoped to
    this file. Includes self-edges (L == M)."""
    edges: dict[tuple, list[str]] = {}

    def add(src: str, dst: str, where: str) -> None:
        edges.setdefault((src, dst), []).append(where)

    # direct nesting: with self._a: ... with self._b:
    for acq in model.acquires:
        for held in acq.held_before:
            add(held, acq.lock,
                f"{model.rel}:{acq.line} ({acq.func})")
    # one hop through self-method calls: holder -> every lock the callee
    # acquires anywhere in its body
    # nested defs inside the callee run later on another stack — only
    # the method's own body counts as "the callee acquires"
    callee_locks: dict[tuple, set] = {}
    for acq in model.acquires:
        if "." in acq.func:
            continue
        callee_locks.setdefault((acq.owner, acq.func), set()).add(
            (acq.lock, acq.line)
        )
    for call in model.self_calls:
        if not call.held:
            continue
        for lock, line in sorted(callee_locks.get((call.cls, call.callee), ())):
            for held in call.held:
                add(held, lock,
                    f"{model.rel}:{call.line} ({call.func} -> "
                    f"self.{call.callee}, acquires at line {line})")
    return edges


def _reentrant(model: lockmodel.FileModel, ident: str) -> bool:
    info = model.lock_info(ident)
    if info is None:
        return False
    # a Condition wrapping a lock resolves to the wrapped lock before it
    # ever reaches an edge, so `kind` here is the root's own kind
    return info.kind in lockmodel.REENTRANT_KINDS


def _find_cycles(edges: dict[tuple, list[str]]) -> list[list[str]]:
    """Simple cycles of length >= 2 via DFS (the graphs here are tiny:
    a handful of locks per file)."""
    graph: dict[str, set] = {}
    for (src, dst), _ev in edges.items():
        if src != dst:
            graph.setdefault(src, set()).add(dst)
    cycles: list[list[str]] = []
    seen_keys: set = set()

    def dfs(start: str, node: str, path: list[str], visited: set) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start and len(path) >= 2:
                key = frozenset(path)
                if key not in seen_keys:
                    seen_keys.add(key)
                    cycles.append(path + [start])
            elif nxt not in visited and nxt > start:
                # only walk nodes ordered after start: each cycle is
                # found exactly once, rooted at its smallest node
                visited.add(nxt)
                dfs(start, nxt, path + [nxt], visited)
                visited.remove(nxt)

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return cycles


def check_model(model: lockmodel.FileModel,
                allowlist: Allowlist | None = None) -> list[str]:
    al = ALLOWLIST if allowlist is None else allowlist
    edges = build_edges(model)
    out = []
    for (src, dst), evidence in sorted(edges.items()):
        if src != dst:
            continue
        if _reentrant(model, src):
            continue
        if al.permits((model.rel, f"{src}->{dst}")):
            continue
        out.append(
            f"{model.rel}: non-reentrant self-acquisition of {src} — "
            f"guaranteed deadlock on this path: {'; '.join(evidence)}"
        )
    for cycle in _find_cycles(edges):
        arrow = " -> ".join(cycle)
        if al.permits((model.rel, arrow)):
            continue
        ev = []
        for a, b in zip(cycle, cycle[1:]):
            ev.append(f"{a}->{b} at {edges[(a, b)][0]}")
        out.append(
            f"{model.rel}: lock-order cycle {arrow} — two threads taking "
            f"these in opposite order deadlock: {'; '.join(ev)}"
        )
    return out


def collect_violations(packages=DEFAULT_PACKAGES, root=None,
                       allowlist: Allowlist | None = None) -> list[str]:
    al = ALLOWLIST if allowlist is None else allowlist
    al.used.clear()
    out: list[str] = []
    for sf in iter_files(packages, root):
        model = lockmodel.build_file_model(sf.tree, sf.rel)
        out.extend(check_model(model, al))
    out.extend(al.problems())
    return out
