"""Chaos-coverage lint: a declared fault kind nobody fires or tests is
untested robustness.

``ray_tpu/chaos/schedule.py`` is the fault vocabulary; this pass holds
it to account:

 * every kind in ``KINDS`` must have >= 1 FIRING SITE — an in-process
   ``fire(..., kinds=(..., KIND, ...))`` hook naming it, or (for the
   runner-orchestrated kinds) an executor branch in ``chaos/runner.py``
   referencing it;
 * every kind must be REFERENCED BY >= 1 TEST (constant name or wire
   string in ``tests/``) — a kind that fires but is never asserted on is
   coverage theater.

Everything is resolved from the AST (no imports), so a half-broken
schedule module still lints. Dead kinds being *removed* is fine — the
point is that declaration, firing, and testing move together.
"""

from __future__ import annotations

import ast
import os

from ray_tpu.analysis.walker import call_name, iter_files, repo_root

SCHEDULE_REL = "ray_tpu/chaos/schedule.py"
RUNNER_REL = "ray_tpu/chaos/runner.py"


def declared_kinds(root: str | None = None) -> dict[str, str]:
    """{CONSTANT_NAME: wire string} for every kind in schedule.KINDS,
    resolved statically from the module's AST."""
    base = root or repo_root()
    with open(os.path.join(base, SCHEDULE_REL), encoding="utf-8") as fh:
        tree = ast.parse(fh.read())
    consts: dict[str, str] = {}
    kinds_names: set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if not isinstance(tgt, ast.Name):
                continue
            if (isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                consts[tgt.id] = node.value.value
            elif tgt.id == "KINDS":
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name):
                        kinds_names.add(sub.id)
    return {name: consts[name] for name in sorted(kinds_names)
            if name in consts}


def firing_sites(root: str | None = None) -> dict[str, list[str]]:
    """{CONSTANT_NAME: ["file:line", ...]} — in-process ``fire`` hook
    sites whose ``kinds`` argument names the constant, plus runner
    executor references in chaos/runner.py."""
    base = root or repo_root()
    sites: dict[str, list[str]] = {}

    def add(name: str, where: str) -> None:
        sites.setdefault(name, []).append(where)

    for sf in iter_files(("ray_tpu",), base):
        is_runner = sf.rel == RUNNER_REL.removeprefix("ray_tpu/")
        if sf.rel.startswith("chaos/") and not is_runner:
            continue  # the schedule/harness defining a kind isn't firing it
        if is_runner:
            # the runner EXECUTES orchestrated kinds: any load of the
            # constant in an executor branch counts as its firing site
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                    add(node.id, f"{sf.rel}:{node.lineno}")
            continue
        fire_lines = [
            node.lineno for node in ast.walk(sf.tree)
            if isinstance(node, ast.Call) and call_name(node) == "fire"
        ]
        if not fire_lines:
            continue
        # a hook file passes kinds both inline (kinds=(_chaos.X,)) and
        # via a variable built from the constants earlier in the file —
        # any constant reference in a file that fires counts as its site
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Attribute):
                add(node.attr, f"{sf.rel}:{node.lineno}")
            elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
                add(node.id, f"{sf.rel}:{node.lineno}")
    return sites


def test_references(root: str | None = None) -> set[str]:
    """Raw token soup of tests/: constant names and wire strings are
    matched textually (tests reference kinds both ways)."""
    base = root or repo_root()
    blob: list[str] = []
    tests_dir = os.path.join(base, "tests")
    for dirpath, _dirs, files in os.walk(tests_dir):
        for f in sorted(files):
            if f.endswith(".py"):
                with open(os.path.join(dirpath, f), encoding="utf-8") as fh:
                    blob.append(fh.read())
    return _token_set("\n".join(blob))


def _token_set(text: str) -> set[str]:
    import re

    return set(re.findall(r"[A-Za-z_][A-Za-z0-9_]*", text))


def collect_violations(root: str | None = None) -> list[str]:
    kinds = declared_kinds(root)
    sites = firing_sites(root)
    tokens = test_references(root)
    out = []
    for name, wire in kinds.items():
        if not sites.get(name):
            out.append(
                f"{SCHEDULE_REL}: fault kind {name} ({wire!r}) has no "
                "firing site — no fire(..., kinds=...) hook names it and "
                "the runner does not execute it; a kind nothing can "
                "inject is dead vocabulary"
            )
        if name not in tokens and wire not in tokens:
            out.append(
                f"{SCHEDULE_REL}: fault kind {name} ({wire!r}) is not "
                "referenced by any test under tests/ — untested "
                "robustness is a claim, not a property"
            )
    return out
