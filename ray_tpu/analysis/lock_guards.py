"""Lock-guard inference: which lock protects which attribute, and who
touches it without that lock.

For every class (and module) with at least one lock, the pass infers a
guard relation from the evidence the code itself provides: an attribute
that is consistently touched inside ``with self._lock:`` bodies is
treated as guarded by ``_lock``, and the remaining accesses — the ones
outside any acquisition of that lock — are exactly the TSAN-shaped bugs
PR 7/8 hit (a snapshot read racing a mutator, a reconcile writing state
the sweep thread owns).

Inference rule (tuned against this codebase; see tests/test_analysis.py):
an attribute is **guarded by L** when, excluding ``__init__``-time
construction (happens-before publication of ``self``):

 * it is WRITTEN at least once while holding L (shared *mutable* state —
   read-only config attrs set in ``__init__`` never qualify), and
 * at least ``MIN_GUARDED`` accesses hold L, and
 * at least ``GUARD_FRACTION`` of all its accesses hold L (majority
   evidence — a 50/50 attribute has no inferred discipline to enforce).

Violations are the minority accesses. Audited exceptions go in
``ALLOWLIST`` keyed by ``(file, Class.attr, function)`` with a written
invariant; stale entries fail the pass (analysis/allowlist.py).
"""

from __future__ import annotations

from ray_tpu.analysis import lockmodel
from ray_tpu.analysis.allowlist import Allowlist
from ray_tpu.analysis.walker import DEFAULT_PACKAGES, iter_files

MIN_GUARDED = 4        # accesses under L before we believe the pattern
GUARD_FRACTION = 0.75  # share of accesses that must hold L

# (file, owner.attr, function) -> justification. The function key is the
# OUTERMOST enclosing def (nested helpers inherit their parent's audit).
ALLOWLIST = Allowlist({
    ("serve/router.py", "Router._replicas", "_refresh"): (
        "advisory staleness fast-path on the dispatch hot path: "
        "GIL-atomic reads; a stale value costs one redundant refresh RPC "
        "or 0.25s extra staleness, while locking here serializes the "
        "dispatch fan-out (burst shedding regressed measurably under it)"
    ),
    ("serve/router.py", "Router._inflight", "_pick"): (
        "power-of-two-choices is a heuristic: GIL-atomic int reads; a "
        "stale counter skews one pick toward the busier replica, never "
        "correctness — the accounting increments/decrements stay under "
        "_lock. A hot mutex on every dispatch buys nothing here"
    ),
    ("core/placement.py", "PlacementGroup._state", "__repr__"): (
        "diagnostic repr: _state is a str rebound atomically under the "
        "GIL, and a stale value in a log line is acceptable; taking "
        "_lock in __repr__ would self-deadlock any log statement issued "
        "inside a locked region"
    ),
    ("core/runtime.py", "<module>._runtime", "get_runtime"): (
        "the atexit lambda registered here runs at interpreter shutdown "
        "(single-threaded by then); taking _runtime_lock inside the "
        "atexit hook could deadlock if exit fires while another thread "
        "holds the lock"
    ),
}, label="lock-guard allowlist")


def infer_guards(model: lockmodel.FileModel,
                 ctor_funcs: set | None = None) -> dict[tuple, str]:
    """{(owner, attr): lock_ident} for every attribute whose access
    pattern clears the inference thresholds."""
    if ctor_funcs is None:
        ctor_funcs = constructor_only_funcs(model)
    by_attr: dict[tuple, list] = {}
    for acc in model.accesses:
        if (acc.owner, acc.func) in ctor_funcs:
            continue
        by_attr.setdefault((acc.owner, acc.attr), []).append(acc)
    guards: dict[tuple, str] = {}
    for key, accs in by_attr.items():
        owner = key[0]
        candidate_locks = {
            info.ident for info in model.locks.values()
            if info.owner == owner and info.kind != "semaphore"
        }
        # semaphores with count > 1 are not mutual exclusion; a
        # Condition resolves to its root before reaching `held`
        best = None
        for lock in sorted(candidate_locks):
            root = model.lock_root(*lock.split(".", 1))
            held = [a for a in accs if root in a.held]
            if not any(a.write for a in held):
                continue
            if len(held) < MIN_GUARDED:
                continue
            if len(held) / len(accs) < GUARD_FRACTION:
                continue
            if best is None or len(held) > best[1]:
                best = (root, len(held))
        if best is not None:
            guards[key] = best[0]
    return guards


CONSTRUCTORS = ("__init__", "__new__", "__post_init__")


def constructor_only_funcs(model: lockmodel.FileModel) -> set[tuple]:
    """(owner, func) pairs that only ever run during construction:
    the constructors themselves, plus private helpers whose EVERY
    self-call site is constructor-only (``_load_snapshot`` called from
    ``__init__``). Their accesses happen before ``self`` is published,
    so no lock discipline applies — and they must not count as
    unguarded evidence against an attribute either."""
    owners = set(model.class_methods)
    ctor: set[tuple] = {(o, c) for o in owners for c in CONSTRUCTORS}
    sites: dict[tuple, list] = {}
    for sc in model.self_calls:
        sites.setdefault((sc.cls, sc.callee), []).append(sc)
    for _ in range(6):
        grew = False
        for (cls, m), calls in sites.items():
            if (cls, m) in ctor:
                continue
            if not m.startswith("_") or m.startswith("__"):
                continue
            if (cls, m) in model.method_refs:
                continue
            if all((c.cls, c.func) in ctor and "." not in c.func
                   for c in calls):
                ctor.add((cls, m))
                grew = True
        if not grew:
            break
    return ctor


def check_model(model: lockmodel.FileModel,
                allowlist: Allowlist | None = None) -> list[str]:
    al = ALLOWLIST if allowlist is None else allowlist
    ctor_funcs = constructor_only_funcs(model)
    guards = infer_guards(model, ctor_funcs)
    out = []
    for acc in model.accesses:
        if (acc.owner, acc.func) in ctor_funcs:
            continue
        guard = guards.get((acc.owner, acc.attr))
        if guard is None or guard in acc.held:
            continue
        outer = acc.func.split(".", 1)[0]
        key = (model.rel, f"{acc.owner}.{acc.attr}", outer)
        if al.permits(key):
            continue
        kind = "write to" if acc.write else "read of"
        out.append(
            f"{model.rel}:{acc.line}: {kind} {acc.owner}.{acc.attr} "
            f"outside its inferred guard {guard} (in {acc.func})"
        )
    return out


def collect_violations(packages=DEFAULT_PACKAGES, root=None,
                       allowlist: Allowlist | None = None) -> list[str]:
    al = ALLOWLIST if allowlist is None else allowlist
    al.used.clear()
    out: list[str] = []
    for sf in iter_files(packages, root):
        model = lockmodel.build_file_model(sf.tree, sf.rel)
        out.extend(check_model(model, al))
    out.extend(al.problems())
    return out
