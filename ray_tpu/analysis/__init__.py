"""ray_tpu.analysis — concurrency-discipline static analysis.

The Python planes' answer to the C++ layers' TSAN + absl thread
annotations (SURVEY: GCS/raylet/core_worker lean on both): a shared AST
framework plus whole-package passes that make threading discipline a
tier-1 gate instead of a chaos-suite lottery.

Shared framework
----------------
 * ``walker``      — repo/package file iteration, function-stack visitor,
                     per-class attribute/lock models (the scaffolding
                     ``check_timeouts``/``check_metrics`` used to duplicate)
 * ``allowlist``   — audited-exception infrastructure: every entry carries
                     a mandatory written justification, and entries that no
                     longer match code fail the lint (stale-entry detection)
 * ``lockmodel``   — per-class lock inventory (Lock/RLock/Condition/
                     Semaphore, with Condition(self._lock) aliasing) and
                     per-method lock-held event streams

Passes (each has a ``scripts/check_*.py`` CLI and a tier-1 test)
----------------------------------------------------------------
 * ``lock_guards``  — infer which lock guards which attribute from
                      ``with self._lock:`` bodies; flag unguarded accesses
 * ``lock_order``   — global lock-acquisition graph; fail on cycles and
                      non-reentrant self-deadlocks
 * ``blocking``     — blocking calls (RPC sends, socket recvs, sleeps,
                      joins, kv_wait, chaos-hook fires) under a held lock
 * ``thread_hygiene``  — every Thread is daemon or joined on shutdown
 * ``chaos_coverage``  — every declared FaultKind has a firing site + test
 * ``timeouts``     — unbounded blocking receives/parks (moved from
                      scripts/check_timeouts.py onto this framework)
 * ``metrics_registry`` — live metrics-registry lint (moved from
                      scripts/check_metrics.py)

Run everything: ``python scripts/lint_all.py`` (``--json`` for machines).
"""

from ray_tpu.analysis.allowlist import Allowlist
from ray_tpu.analysis.walker import (
    DEFAULT_PACKAGES,
    FuncStackVisitor,
    SourceFile,
    call_name,
    iter_files,
    repo_root,
)

__all__ = [
    "Allowlist",
    "DEFAULT_PACKAGES",
    "FuncStackVisitor",
    "SourceFile",
    "call_name",
    "iter_files",
    "repo_root",
]
