"""Per-file lock inventory + lock-held event streams.

This is the shared semantic model under the three concurrency passes
(lock_guards / lock_order / blocking): for every function and method in
a file, WHICH locks are held at every attribute access, lock
acquisition, and call site.

Model scope (deliberate under-approximation — a lint must not lie):

 * locks are ``threading.Lock/RLock/Condition/Semaphore/BoundedSemaphore``
   bound to ``self._x`` attributes or module-level names, acquired via
   ``with``;
 * ``threading.Condition(self._lock)`` ALIASES the wrapped lock — holding
   the condition is holding ``_lock`` (both resolve to one canonical
   root), which is what makes ``with self._lock: self._cv.wait(t)``
   analyzable;
 * unknown context managers (obs spans, ``open``, locks reached through
   dicts/tuples) are treated as not-a-lock: they add nothing to the held
   set, so they can cause false NEGATIVES but never false positives;
 * a nested ``def`` (thread target, callback) runs LATER — its body is
   walked with an empty held set, not the definition site's.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Optional

from ray_tpu.analysis.walker import call_name

# factory name -> lock kind; reentrancy matters for self-deadlock edges
LOCK_FACTORIES = {
    "Lock": "lock",
    "RLock": "rlock",
    "Condition": "condition",
    "Semaphore": "semaphore",
    "BoundedSemaphore": "semaphore",
}
REENTRANT_KINDS = frozenset({"rlock", "condition"})
# A bare Condition() wraps an RLock, so re-entering is safe; a
# Condition(self._lock) resolves to the wrapped lock's kind instead.

# receiver methods that mutate the receiver object — a call
# ``self._x.append(v)`` is a WRITE to the state _x guards
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "add", "update", "extend", "insert", "remove",
    "discard", "pop", "popleft", "popitem", "clear", "setdefault", "sort",
    "reverse",
})

MODULE = "<module>"


@dataclasses.dataclass
class LockInfo:
    owner: str                  # class name or MODULE
    name: str                   # attribute / global name
    kind: str                   # lock | rlock | condition | semaphore
    line: int
    wraps: Optional[str] = None  # Condition(self._x) -> "_x" (same owner)

    @property
    def ident(self) -> str:
        return f"{self.owner}.{self.name}"


@dataclasses.dataclass
class Access:
    """One read/write of a guard-candidate attribute or module global."""

    owner: str                  # class name or MODULE
    attr: str
    line: int
    write: bool
    held: frozenset             # canonical lock idents held
    func: str                   # "method" / "method.<locals>.inner" / func name


@dataclasses.dataclass
class Acquire:
    lock: str                   # canonical lock ident
    line: int
    held_before: frozenset
    func: str
    owner: str                  # class the acquiring code lives in (or MODULE)


@dataclasses.dataclass
class CallEvent:
    name: str                   # called attr/function name
    receiver: Optional[str]     # "x" / "self.x" / None
    line: int
    held: frozenset
    func: str
    owner: str
    node: ast.Call


@dataclasses.dataclass
class SelfCall:
    cls: str
    callee: str                 # method name on self
    line: int
    held: frozenset
    func: str


@dataclasses.dataclass
class ThreadCreate:
    line: int
    func: str
    owner: str
    node: ast.Call
    target_name: Optional[str] = None   # "self.x" / "x" the Thread is bound to
    stored_into: Optional[str] = None   # container it was .append()ed into


class FileModel:
    """Everything the passes need to know about one source file."""

    def __init__(self, rel: str):
        self.rel = rel
        self.locks: dict[str, LockInfo] = {}        # ident -> info
        self.class_methods: dict[str, set[str]] = {}
        self.module_globals: set[str] = set()
        self.accesses: list[Access] = []
        self.acquires: list[Acquire] = []
        self.calls: list[CallEvent] = []
        self.self_calls: list[SelfCall] = []
        self.threads: list[ThreadCreate] = []
        self.joined_names: set[str] = set()          # names .join() is called on
        self.join_covered_containers: set[str] = set()
        self.appends: list[tuple[str, str]] = []     # (container, appended name)
        self.method_refs: set[tuple[str, str]] = set()  # self.m passed as value

    # -- lock identity --------------------------------------------------------

    def lock_root(self, owner: str, name: str) -> Optional[str]:
        """Canonical ident for a lock reference: Condition(wrapped) chains
        resolve to the wrapped lock (holding one IS holding the other)."""
        seen = set()
        cur = f"{owner}.{name}"
        while cur in self.locks and cur not in seen:
            seen.add(cur)
            wraps = self.locks[cur].wraps
            if wraps is None:
                return cur
            cur = f"{self.locks[cur].owner}.{wraps}"
        return cur if cur in self.locks else None

    def lock_info(self, ident: str) -> Optional[LockInfo]:
        return self.locks.get(ident)


def _factory_kind(call: ast.Call) -> Optional[str]:
    """'lock'/'rlock'/... when ``call`` is a threading-primitive
    constructor (``threading.Lock()`` or bare ``Lock()``)."""
    name = call_name(call)
    if name not in LOCK_FACTORIES:
        return None
    if isinstance(call.func, ast.Attribute):
        base = call.func.value
        if not (isinstance(base, ast.Name) and base.id == "threading"):
            return None
    return LOCK_FACTORIES[name]


def _self_attr_of(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def build_file_model(tree: ast.Module, rel: str) -> FileModel:
    model = FileModel(rel)
    _collect_module_level(model, tree)
    _collect_classes(model, tree)
    # walk module functions and class methods with lock-context tracking
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _ContextWalker(model, MODULE, node.name).walk(node)
    for cls in _iter_classes(tree):
        for item in cls.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _ContextWalker(model, cls.name, item.name).walk(item)
    _propagate_private_held(model)
    return model


def _propagate_private_held(model: FileModel) -> None:
    """Call-graph-lite held-context propagation: a PRIVATE method whose
    every visible self-call site holds lock L is analyzed as entered
    with L held (the ``_evict_over_capacity_locked`` convention, made
    checkable). Excluded: dunders (the runtime calls them with nothing
    held) and methods ever passed as values (thread targets/callbacks
    run with no context we can see). Transitive via a small fixpoint."""
    for _ in range(6):
        calls_by_callee: dict[tuple, list[SelfCall]] = {}
        for sc in model.self_calls:
            calls_by_callee.setdefault((sc.cls, sc.callee), []).append(sc)
        entry: dict[tuple, frozenset] = {}
        for (cls, m), sites in calls_by_callee.items():
            if not m.startswith("_") or m.startswith("__"):
                continue
            if (cls, m) in model.method_refs:
                continue
            inter = frozenset.intersection(*[s.held for s in sites])
            if inter:
                entry[(cls, m)] = inter
        changed = False
        for ev in model.accesses + model.calls + model.self_calls:
            owner = ev.cls if isinstance(ev, SelfCall) else ev.owner
            if "." in ev.func:
                continue  # nested defs run later, on another stack
            extra = entry.get((owner, ev.func))
            if extra and not extra <= ev.held:
                ev.held = ev.held | extra
                changed = True
        for acq in model.acquires:
            if "." in acq.func:
                continue
            extra = entry.get((acq.owner, acq.func))
            if extra and not extra <= acq.held_before:
                acq.held_before = acq.held_before | extra
                changed = True
        if not changed:
            break


def _iter_classes(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            yield node


def _collect_module_level(model: FileModel, tree: ast.Module) -> None:
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            tgt = node.targets[0]
            if isinstance(tgt, ast.Name):
                if isinstance(node.value, ast.Call):
                    kind = _factory_kind(node.value)
                    if kind is not None:
                        model.locks[f"{MODULE}.{tgt.id}"] = LockInfo(
                            MODULE, tgt.id, kind, node.lineno,
                            wraps=_wrapped_name(node.value, module_level=True),
                        )
                        continue
                model.module_globals.add(tgt.id)
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            model.module_globals.add(node.target.id)


def _wrapped_name(call: ast.Call, *, module_level: bool) -> Optional[str]:
    """``Condition(self._lock)`` / ``Condition(_lock)`` -> wrapped name."""
    if call_name(call) != "Condition" or not call.args:
        return None
    arg = call.args[0]
    if module_level and isinstance(arg, ast.Name):
        return arg.id
    return _self_attr_of(arg)


def _collect_classes(model: FileModel, tree: ast.Module) -> None:
    for cls in _iter_classes(tree):
        methods = {
            n.name for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        model.class_methods[cls.name] = methods
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                attr = _self_attr_of(tgt)
                if attr is None or not isinstance(node.value, ast.Call):
                    continue
                kind = _factory_kind(node.value)
                if kind is None:
                    continue
                model.locks[f"{cls.name}.{attr}"] = LockInfo(
                    cls.name, attr, kind, node.lineno,
                    wraps=_wrapped_name(node.value, module_level=False),
                )


class _ContextWalker:
    """Walks ONE function/method body tracking the held-lock stack.

    Nested defs/lambdas are walked as their own contexts (empty held set
    — their bodies run later, on some other stack)."""

    def __init__(self, model: FileModel, owner: str, func: str):
        self.model = model
        self.owner = owner          # class name or MODULE
        self.func = func            # possibly dotted for nested defs
        self.held: list[str] = []   # canonical lock idents (stack)
        self.locals: set[str] = set()

    # -- entry ---------------------------------------------------------------

    def walk(self, fn) -> None:
        self.locals = _local_names(fn)
        for stmt in fn.body:
            self._visit(stmt)

    # -- held-set helpers ----------------------------------------------------

    def _held(self) -> frozenset:
        return frozenset(self.held)

    def _resolve_lock(self, expr: ast.AST) -> Optional[str]:
        attr = _self_attr_of(expr)
        if attr is not None and self.owner != MODULE:
            return self.model.lock_root(self.owner, attr)
        if isinstance(expr, ast.Name) and expr.id not in self.locals:
            return self.model.lock_root(MODULE, expr.id)
        return None

    # -- dispatch ------------------------------------------------------------

    def _visit(self, node: ast.AST) -> None:
        method = getattr(self, f"_visit_{type(node).__name__}", None)
        if method is not None:
            method(node)
        else:
            for child in ast.iter_child_nodes(node):
                self._visit(child)

    def _visit_children(self, node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    # -- nested scopes run later ---------------------------------------------

    def _nested(self, node, name: str) -> None:
        sub = _ContextWalker(self.model, self.owner,
                             f"{self.func}.<locals>.{name}")
        sub.walk(node)

    def _visit_FunctionDef(self, node):
        self._nested(node, node.name)

    _visit_AsyncFunctionDef = _visit_FunctionDef

    def _visit_Lambda(self, node):
        sub = _ContextWalker(self.model, self.owner,
                             f"{self.func}.<locals>.<lambda>")
        sub.locals = _local_names(node)
        sub._visit(node.body)

    # -- with: the acquisition form ------------------------------------------

    def _visit_With(self, node):
        pushed = 0
        for item in node.items:
            self._visit(item.context_expr)
            lock = self._resolve_lock(item.context_expr)
            if lock is not None:
                self.model.acquires.append(Acquire(
                    lock=lock, line=item.context_expr.lineno,
                    held_before=self._held(), func=self.func,
                    owner=self.owner,
                ))
                self.held.append(lock)
                pushed += 1
            if item.optional_vars is not None:
                self._visit(item.optional_vars)
        for stmt in node.body:
            self._visit(stmt)
        for _ in range(pushed):
            self.held.pop()

    _visit_AsyncWith = _visit_With

    # -- accesses ------------------------------------------------------------

    def _record_access(self, owner: str, attr: str, line: int, write: bool):
        if f"{owner}.{attr}" in self.model.locks:
            return
        if owner != MODULE and attr in self.model.class_methods.get(owner, ()):
            # `self._m` referenced as a VALUE (thread target, callback):
            # the method can then run with no lock context we can see, so
            # held-context propagation must not assume its call sites
            self.model.method_refs.add((owner, attr))
            return
        self.model.accesses.append(Access(
            owner=owner, attr=attr, line=line, write=write,
            held=self._held(), func=self.func,
        ))

    def _visit_Attribute(self, node):
        attr = _self_attr_of(node)
        if attr is not None and self.owner != MODULE:
            write = isinstance(node.ctx, (ast.Store, ast.Del))
            self._record_access(self.owner, attr, node.lineno, write)
            return
        # self._obj.field = v / self._map[k] = v: mutation of the object
        # _obj/_map holds — a write to the guarded state
        inner = _self_attr_of(node.value)
        if (inner is not None and self.owner != MODULE
                and isinstance(node.ctx, (ast.Store, ast.Del))):
            self._record_access(self.owner, inner, node.lineno, write=True)
            return
        self._visit_children(node)

    def _visit_Subscript(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            attr = _self_attr_of(node.value)
            if attr is not None and self.owner != MODULE:
                self._record_access(self.owner, attr, node.lineno, write=True)
                self._visit(node.slice)
                return
            if (isinstance(node.value, ast.Name)
                    and node.value.id in self.model.module_globals
                    and node.value.id not in self.locals):
                self._record_access(MODULE, node.value.id, node.lineno,
                                    write=True)
                self._visit(node.slice)
                return
        self._visit_children(node)

    def _visit_Name(self, node):
        if (node.id in self.model.module_globals
                and node.id not in self.locals):
            self._record_access(
                MODULE, node.id, node.lineno,
                write=isinstance(node.ctx, (ast.Store, ast.Del)),
            )

    # -- calls ---------------------------------------------------------------

    def _visit_Call(self, node):
        name = call_name(node)
        receiver = None
        fn = node.func
        if isinstance(fn, ast.Attribute):
            recv_attr = _self_attr_of(fn.value)
            if recv_attr is not None:
                receiver = f"self.{recv_attr}"
            elif isinstance(fn.value, ast.Name):
                receiver = fn.value.id
            callee_self = _self_attr_of(fn)
            if callee_self is not None and self.owner != MODULE:
                if callee_self in self.model.class_methods.get(self.owner, ()):
                    self.model.self_calls.append(SelfCall(
                        cls=self.owner, callee=callee_self, line=node.lineno,
                        held=self._held(), func=self.func,
                    ))
                else:
                    # self._cb(...) — a read of the attr holding the callable
                    self._record_access(self.owner, callee_self,
                                        node.lineno, write=False)
            elif recv_attr is not None:
                # self._x.append(v): mutator calls write the guarded state
                self._record_access(self.owner, recv_attr, node.lineno,
                                    write=name in MUTATOR_METHODS)
            elif (isinstance(fn.value, ast.Name)
                  and fn.value.id in self.model.module_globals
                  and fn.value.id not in self.locals):
                # _REG.pop(k): mutator calls write the guarded global
                self._record_access(MODULE, fn.value.id, node.lineno,
                                    write=name in MUTATOR_METHODS)
            else:
                self._visit(fn.value)
        elif isinstance(fn, ast.Name):
            self._visit_Name(fn)
        else:
            self._visit(fn)

        if name is not None:
            self.model.calls.append(CallEvent(
                name=name, receiver=receiver, line=node.lineno,
                held=self._held(), func=self.func, owner=self.owner,
                node=node,
            ))
        self._record_thread_ops(name, receiver, node)
        for arg in node.args:
            self._visit(arg)
        for kw in node.keywords:
            self._visit(kw.value)
        if (name == "append" and receiver is not None and len(node.args) == 1
                and isinstance(node.args[0], ast.Call) and self.model.threads
                and self.model.threads[-1].node is node.args[0]):
            self.model.threads[-1].stored_into = receiver

    # -- thread hygiene raw facts --------------------------------------------

    def _record_thread_ops(self, name, receiver, node: ast.Call) -> None:
        if name == "Thread":
            ok_receiver = receiver in (None, "threading")
            if ok_receiver:
                self.model.threads.append(ThreadCreate(
                    line=node.lineno, func=self.func, owner=self.owner,
                    node=node,
                ))
        elif name == "join" and receiver is not None:
            self.model.joined_names.add(receiver)
        elif name == "append" and receiver is not None and len(node.args) == 1:
            arg = node.args[0]
            if isinstance(arg, ast.Name):
                self.model.appends.append((receiver, arg.id))

    def _visit_For(self, node):
        # join-coverage: ``for t in self._threads: t.join()`` marks the
        # container as joined, covering every thread appended into it
        if isinstance(node.target, ast.Name):
            container = self._container_of(node.iter)
            if container is not None:
                tv = node.target.id
                for sub in ast.walk(node):
                    if (isinstance(sub, ast.Call) and call_name(sub) == "join"
                            and isinstance(sub.func, ast.Attribute)
                            and isinstance(sub.func.value, ast.Name)
                            and sub.func.value.id == tv):
                        self.model.join_covered_containers.add(container)
                        break
        self._visit_children(node)

    def _container_of(self, it: ast.AST) -> Optional[str]:
        attr = _self_attr_of(it)
        if attr is not None:
            return f"self.{attr}"
        if isinstance(it, ast.Name):
            return it.id
        if isinstance(it, ast.Call):  # list(ts) / sorted(self._threads)
            for a in it.args:
                aa = _self_attr_of(a)
                if aa is not None:
                    return f"self.{aa}"
                if isinstance(a, ast.Name):
                    return a.id
        return None

    # -- assignment forms feed both accesses and thread targets --------------

    def _visit_Assign(self, node):
        self._visit(node.value)
        for tgt in node.targets:
            self._visit(tgt)
        self._maybe_bind_thread(node.value, node.targets)

    def _visit_AugAssign(self, node):
        # x += v reads AND writes x
        self._visit(node.value)
        tgt = node.target
        attr = _self_attr_of(tgt)
        if attr is not None and self.owner != MODULE:
            self._record_access(self.owner, attr, tgt.lineno, write=True)
            self._record_access(self.owner, attr, tgt.lineno, write=False)
        else:
            self._visit(tgt)

    def _maybe_bind_thread(self, value: ast.AST, targets: list) -> None:
        """``t = threading.Thread(...)`` / ``self._t = Thread(...)`` —
        remember what name the thread landed in (join-coverage)."""
        if not (isinstance(value, ast.Call) and self.model.threads):
            return
        last = self.model.threads[-1]
        if last.node is not value or len(targets) != 1:
            return
        tgt = targets[0]
        attr = _self_attr_of(tgt)
        if attr is not None:
            last.target_name = f"self.{attr}"
        elif isinstance(tgt, ast.Name):
            last.target_name = tgt.id


def _local_names(fn) -> set[str]:
    """Names bound locally in ``fn`` (params + assignments), so global
    reads aren't confused with locals shadowing them. Names under a
    ``global`` declaration stay global."""
    names: set[str] = set()
    args = fn.args
    for a in (list(args.posonlyargs) + list(args.args)
              + list(args.kwonlyargs)):
        names.add(a.arg)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    declared_global: set[str] = set()

    def scan(node: ast.AST) -> None:
        # manual recursion so nested def/lambda subtrees are PRUNED —
        # ast.walk would keep descending and a name assigned only inside
        # a nested scope would wrongly shadow the module global in the
        # outer body (suppressing lock_guards events for it)
        if isinstance(node, ast.Global):
            declared_global.update(node.names)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested scopes collect their own locals
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
        for child in ast.iter_child_nodes(node):
            scan(child)

    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        scan(stmt)
    return names - declared_global
