"""PoolAutoscaler (r20): the SLO closed loop.

One daemon loop per cluster: fetch the GCS signal rollup (ONE
``autoscale_signals`` RPC — per-model SLO grades + ``autoscaler_hints``,
pool rollups, queue depth, the measured prefill-span distribution, and
the pending lease demand the seed autoscaler fed on), map it to
per-pool ``PoolSignals``, run the pure decision ladder, and drive the
actuator. The r11 hint mapping is applied verbatim: TTFT prices the
prefill pool, TPOT the decode pool, queue-wait overall capacity
(attributed to decode, where admission lives).

Failure posture: any fetch failure — connection refused, STALL_GCS
chaos, a blacked-out GCS — degrades every pool to HOLD for the tick
(``gcs_dark``), and the policy resets its streaks so recovery must
re-earn consecutive evidence before acting. A telemetry blackout can
never trigger a scale action.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, Optional

from ray_tpu.autoscale import metrics as as_metrics
from ray_tpu.autoscale.config import AutoscaleConfig, POOL_DECODE, POOL_PREFILL
from ray_tpu.autoscale.policy import (
    ACTION_COLD_START,
    ACTION_SCALE_DOWN,
    ACTION_SCALE_TO_ZERO,
    ACTION_SCALE_UP,
    GRADE_NO_DATA,
    Decision,
    PoolPolicy,
    PoolSignals,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.autoscale.controller")

_GRADE_ORDER = {"no_data": 0, "green": 1, "yellow": 2, "red": 3}

_UP_ACTIONS = (ACTION_SCALE_UP, ACTION_COLD_START)
_DOWN_ACTIONS = (ACTION_SCALE_DOWN, ACTION_SCALE_TO_ZERO)


def _worst(*grades: str) -> str:
    out = GRADE_NO_DATA
    for g in grades:
        if _GRADE_ORDER.get(g, 0) > _GRADE_ORDER.get(out, 0):
            out = g
    return out


def signals_from_payload(
    payload: dict, pools: tuple = (POOL_PREFILL, POOL_DECODE)
) -> Dict[str, PoolSignals]:
    """Map one ``autoscale_signals`` GCS payload to per-pool signals,
    merging across model tags (worst grade wins, any tag's hint
    breaches)."""
    slo = (payload.get("slo") or {}).get("model_tags") or {}
    rollup = payload.get("pools") or {}
    util = payload.get("utilization") or {}
    span = payload.get("prefill_span") or {}
    pending = int(payload.get("pending_demand") or 0)
    queue_depth = float(util.get("queue_depth") or 0.0)
    arrival = float(span.get("arrival_rate_per_s") or 0.0)

    breach = {POOL_PREFILL: False, POOL_DECODE: False}
    grade = {POOL_PREFILL: GRADE_NO_DATA, POOL_DECODE: GRADE_NO_DATA}
    for entry in slo.values():
        hints = entry.get("autoscaler_hints") or {}
        if hints.get("scale_prefill"):
            breach[POOL_PREFILL] = True
        if hints.get("scale_decode") or hints.get("shed_or_add_capacity"):
            breach[POOL_DECODE] = True
        grade[POOL_PREFILL] = _worst(
            grade[POOL_PREFILL], (entry.get("ttft") or {}).get("grade", GRADE_NO_DATA)
        )
        grade[POOL_DECODE] = _worst(
            grade[POOL_DECODE],
            (entry.get("tpot") or {}).get("grade", GRADE_NO_DATA),
            (entry.get("queue_wait") or {}).get("grade", GRADE_NO_DATA),
        )

    out: Dict[str, PoolSignals] = {}
    for pool in pools:
        pr = rollup.get(pool) or {}
        out[pool] = PoolSignals(
            grade=grade.get(pool, GRADE_NO_DATA),
            breach=breach.get(pool, False),
            queue_depth=queue_depth,
            arrival_rate_per_s=arrival,
            span_mean_s=(
                span.get("mean_s") if pool == POOL_PREFILL else None
            ),
            running=int(pr.get("replicas_running") or 0),
            target=(
                int(pr["replicas_target"])
                if pr.get("replicas_target") is not None else None
            ),
            pending_demand=pending,
        )
    return out


def _hold_cause(reason: str) -> str:
    if "gcs-dark" in reason:
        return "gcs_dark"
    if "cooldown" in reason:
        return "cooldown"
    if "streak" in reason or "idle" in reason:
        return "hysteresis"
    return "steady"


class PoolAutoscaler:
    """The closed-loop controller.

    ``gcs``: anything with ``.call(method, payload, timeout=...)`` (an
    RpcClient / ReconnectingRpcClient — the STALL_GCS chaos hook on
    ``gcs.call`` covers every fetch); alternatively pass
    ``fetch_signals`` directly (benches running against an in-process
    TelemetryStore). ``actuator``: a ``PoolActuator``; its
    ``pool_state()`` is authoritative for running/target counts when it
    tracks the pools itself."""

    def __init__(
        self,
        config: AutoscaleConfig,
        actuator: Any,
        gcs: Any = None,
        fetch_signals: Optional[Callable[[], dict]] = None,
        thresholds: Optional[dict] = None,
        rpc_timeout_s: float = 5.0,
        log_len: int = 256,
    ):
        if gcs is None and fetch_signals is None:
            raise ValueError("PoolAutoscaler needs a gcs client or fetch_signals")
        self.config = config
        self.actuator = actuator
        self._gcs = gcs
        self._fetch = fetch_signals
        self._thresholds = dict(thresholds or {})
        self._rpc_timeout_s = rpc_timeout_s
        self.policy = PoolPolicy(config)
        self._lock = threading.Lock()
        self._log: deque = deque(maxlen=log_len)
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_ticks = 0
        self.num_dark_ticks = 0
        self.num_scale_actions = 0
        self.gcs_dark = False

    # -- signal plane ---------------------------------------------------------

    def fetch_signals(self) -> dict:
        if self._fetch is not None:
            return self._fetch()
        return self._gcs.call(
            "autoscale_signals",
            {"thresholds": self._thresholds} if self._thresholds else {},
            timeout=self._rpc_timeout_s,
        )

    def _signals_dark(self, payload: dict) -> bool:
        """Fresh-enough check: reporters exist but ALL are staler than
        the window -> the fleet is partitioned from the GCS; grades built
        from that snapshot are history, not state."""
        staleness = payload.get("staleness") or {}
        if not staleness:
            return False
        vals = [v for v in staleness.values() if v is not None]
        return bool(vals) and min(vals) > self.config.max_signal_age_s

    # -- one tick -------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> Dict[str, Decision]:
        now = time.monotonic() if now is None else now
        payload: dict = {}
        dark = False
        try:
            payload = self.fetch_signals()
            dark = self._signals_dark(payload)
        except Exception as e:  # noqa: BLE001 — any fetch failure = dark
            dark = True
            logger.warning("signal fetch failed (holding): %s", e)
        self.gcs_dark = dark
        self.num_ticks += 1
        if dark:
            self.num_dark_ticks += 1

        pools = tuple(self.config.pools)
        sigs = signals_from_payload(payload, pools) if not dark else {
            p: PoolSignals() for p in pools
        }
        # the actuator's own view of running/target wins when present
        # (an in-process pool has no GCS rollup)
        try:
            state = self.actuator.pool_state() or {}
        except Exception:  # noqa: BLE001
            state = {}
        for pool, st in state.items():
            if pool in sigs:
                sigs[pool].running = int(st.get("replicas_running", 0))
                sigs[pool].target = int(st.get("replicas_target", 0))

        decisions: Dict[str, Decision] = {}
        for pool in pools:
            d = self.policy.decide(pool, sigs[pool], now, gcs_dark=dark)
            decisions[pool] = d
            self._record(d, sigs[pool], now, dark)
            if d.is_scale_action:
                self.num_scale_actions += 1
                try:
                    self.actuator.apply(d)
                except Exception:
                    logger.exception(
                        "actuator failed applying %s on %s", d.action, pool
                    )
        return decisions

    def _record(self, d: Decision, sig: PoolSignals, now: float,
                dark: bool) -> None:
        try:
            as_metrics.decisions_counter().inc(
                tags={"pool": d.pool, "action": d.action}
            )
            if d.action in _UP_ACTIONS:
                as_metrics.scale_ups_counter().inc(tags={"pool": d.pool})
            elif d.action in _DOWN_ACTIONS:
                as_metrics.scale_downs_counter().inc(tags={"pool": d.pool})
            else:
                as_metrics.holds_counter().inc(
                    tags={"cause": _hold_cause(d.reason)}
                )
            if d.target is not None:
                as_metrics.pool_target_gauge().set(
                    d.target, tags={"pool": d.pool}
                )
            as_metrics.gcs_dark_gauge().set(1.0 if dark else 0.0)
        except Exception:  # noqa: BLE001 — observability must not break the loop
            pass
        with self._lock:
            self._log.append({
                "t": now,
                "pool": d.pool,
                "action": d.action,
                "target": d.target,
                "reason": d.reason,
                "gcs_dark": dark,
                "grade": sig.grade,
            })
        if d.is_scale_action:
            logger.info("%s: %s -> %s (%s)", d.pool, d.action, d.target,
                        d.reason)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> "PoolAutoscaler":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="ray_tpu-pool-autoscaler", daemon=True
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception:
                logger.exception("autoscaler tick failed")

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- introspection --------------------------------------------------------

    def decision_log(self) -> list:
        with self._lock:
            return list(self._log)

    def status(self) -> dict:
        try:
            pools = self.actuator.pool_state()
        except Exception:  # noqa: BLE001
            pools = {}
        recent = self.decision_log()[-len(self.config.pools):]
        return {
            "pools": pools,
            "gcs_dark": self.gcs_dark,
            "num_ticks": self.num_ticks,
            "num_dark_ticks": self.num_dark_ticks,
            "num_scale_actions": self.num_scale_actions,
            "recent_decisions": recent,
        }
