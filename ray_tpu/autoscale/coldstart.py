"""Fabric cold start (r20): zero -> serving with streamed weights.

A pool scaled to zero holds no checkpoint lease and no warm process;
waking it must not touch a checkpoint path. The recipe: build a fresh
engine (its init weights are throwaway), register a fabric endpoint,
stream the publisher's retained latest bundle to it
(``WeightPublisher.publish_latest``), and apply it bitwise via
``WeightSubscriber.apply_to_engine`` — the same versioned device-bundle
plane the learner already publishes on. The report carries a bitwise
identity verdict so the serving acceptance gate ("first served tokens
come from bitwise-identical streamed weights") is checkable, and the
wall time lands in ``autoscale_cold_start_seconds``.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Optional

from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.autoscale.coldstart")


@dataclass
class ColdStartReport:
    pool: str
    endpoint_id: str
    seconds: float
    weight_version: Optional[int]
    bitwise_identical: bool


def params_bitwise_equal(a: Any, b: Any) -> bool:
    """Leaf-by-leaf bytes equality of two params pytrees — the identity
    check is on the EXACT device bytes, not an allclose."""
    import jax
    import numpy as np

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    if len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        xa, ya = np.asarray(x), np.asarray(y)
        if xa.shape != ya.shape or xa.dtype != ya.dtype:
            return False
        if xa.tobytes() != ya.tobytes():
            return False
    return True


def cold_start_engine(
    engine_factory: Callable[[], Any],
    publisher: Any,
    endpoint_id: str,
    *,
    pool: str = "decode",
    reference_params: Any = None,
    timeout_s: float = 30.0,
) -> tuple:
    """Bring one replica from nothing to serving-with-current-weights.

    ``publisher`` is a live ``WeightPublisher`` that has published at
    least once (its retained bundle is what streams). Returns
    ``(engine, ColdStartReport)``; the engine is ready to serve and
    ``engine.weight_version`` matches the fleet. When
    ``reference_params`` is given, the report's ``bitwise_identical``
    verdict compares the applied tree against it byte-for-byte."""
    from ray_tpu.train.weight_sync import WeightSubscriber

    t0 = time.monotonic()
    engine = engine_factory()
    target = publisher.register_rollout(
        endpoint_id, device=engine.kv_cache_device()
    )
    sub = WeightSubscriber(publisher.transport, endpoint_id)
    version = publisher.publish_latest(target, timeout_s=timeout_s)
    applied = sub.apply_to_engine(engine, timeout_s=timeout_s)
    seconds = time.monotonic() - t0
    if applied is None:
        raise RuntimeError(
            f"cold start {endpoint_id!r}: published v{version} bundle "
            "never arrived at the new endpoint"
        )
    identical = (
        params_bitwise_equal(reference_params, engine.params)
        if reference_params is not None else True
    )
    report = ColdStartReport(
        pool=pool, endpoint_id=endpoint_id, seconds=round(seconds, 6),
        weight_version=applied, bitwise_identical=identical,
    )
    try:
        from ray_tpu.autoscale.metrics import cold_start_histogram

        cold_start_histogram().observe(seconds, tags={"pool": pool})
    except Exception:  # noqa: BLE001 — observability must not fail the start
        pass
    logger.info(
        "cold start %s/%s: %.3fs to v%s (bitwise=%s)",
        pool, endpoint_id, seconds, applied, identical,
    )
    return engine, report
