"""Actuators (r20): how autoscale decisions become replica changes.

Two implementations of one small surface:

* ``ServePoolActuator`` — drives the serve controller's pool-level
  target (``ServeController.set_pool_target``); scale-down rides the
  reconcile loop's graceful drain (prepare_shutdown before kill).
* ``EnginePoolActuator`` — in-process replica pools for benches and
  chaos tests: replicas are any objects with ``drain()``/``close()``,
  scale-down drains the victim and RE-TARGETS its unfinished work onto
  the survivors (zero lost requests, even when chaos kills the victim
  mid-drain), and 0 -> N goes through a caller-supplied cold-start
  factory (fabric weight streaming via ``autoscale.coldstart``).

Both keep the invariants the policy assumes: decreases never hard-kill
serving replicas, and a cold start is just a scale-up whose factory
streams weights.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.autoscale.policy import (
    ACTION_COLD_START,
    ACTION_SCALE_DOWN,
    ACTION_SCALE_TO_ZERO,
    Decision,
)
from ray_tpu.chaos import harness as _chaos
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.autoscale.actuators")


class PoolActuator:
    """Minimal actuator surface the controller drives."""

    def apply(self, decision: Decision) -> None:
        raise NotImplementedError

    def pool_state(self) -> Dict[str, dict]:
        """{pool: {"replicas_running": int, "replicas_target": int}}"""
        raise NotImplementedError


class ServePoolActuator(PoolActuator):
    """Drive serve-controller pools by role tag. Accepts either a local
    ``ServeController`` instance or its actor handle (the r10 singleton
    actor: methods called via ``.remote`` + ``ray_tpu.get``)."""

    def __init__(self, controller: Any, call_timeout_s: float = 10.0):
        self._controller = controller
        self._timeout = call_timeout_s

    def _call(self, method: str, *args):
        fn = getattr(self._controller, method)
        if hasattr(fn, "remote"):
            import ray_tpu

            return ray_tpu.get(fn.remote(*args), timeout=self._timeout)
        return fn(*args)

    def apply(self, decision: Decision) -> None:
        if not decision.is_scale_action or decision.target is None:
            return
        out = self._call("set_pool_target", decision.pool, decision.target)
        logger.info(
            "serve pool %s -> %d (%s): %s",
            decision.pool, decision.target, decision.action,
            out.get("deployments"),
        )

    def pool_state(self) -> Dict[str, dict]:
        return self._call("pool_state", None)


class FleetPoolActuator(PoolActuator):
    """Drive a FleetManager's per-model replica pools (r21): pools are
    base model ids, targets converge via ``FleetManager.set_pool_target``
    (spawned replicas stream the fleet's current weight version from the
    weight plane; scale-down retires only replicas that drain idle — the
    same never-hard-kill invariant as the other actuators)."""

    def __init__(self, manager: Any, drain_timeout_s: float = 5.0):
        self._manager = manager
        self._drain_timeout_s = drain_timeout_s

    def apply(self, decision: Decision) -> None:
        if not decision.is_scale_action or decision.target is None:
            return
        target = max(1, int(decision.target))  # a fleet model never parks at 0
        got = self._manager.set_pool_target(
            decision.pool, target, drain_timeout_s=self._drain_timeout_s
        )
        logger.info(
            "fleet pool %s -> %d (%s): now %d replica(s)",
            decision.pool, target, decision.action, got,
        )

    def pool_state(self) -> Dict[str, dict]:
        return self._manager.pool_state()


class EnginePoolActuator(PoolActuator):
    """In-process pools of replica workers.

    ``spawn(pool)`` builds a warm replica; ``cold_start(pool)`` (used
    only for the 0 -> N transition when provided) builds one with
    fabric-streamed weights. Replicas may expose ``drain(timeout_s) ->
    list`` (unfinished work to re-target) and ``close()``; both are
    optional. Thread-safe: the controller loop and bench load threads
    may look at pool state concurrently."""

    def __init__(
        self,
        spawn: Callable[[str], Any],
        cold_start: Optional[Callable[[str], Any]] = None,
        requeue: Optional[Callable[[str, list], None]] = None,
        drain_timeout_s: float = 10.0,
    ):
        self._spawn = spawn
        self._cold_start = cold_start
        self._requeue = requeue
        self._drain_timeout_s = drain_timeout_s
        self._lock = threading.Lock()
        self._pools: Dict[str, List[Any]] = {}
        self._targets: Dict[str, int] = {}
        self.num_drained = 0
        self.num_drain_killed = 0
        self.num_retargeted = 0

    def replicas(self, pool: str) -> List[Any]:
        with self._lock:
            return list(self._pools.get(pool, ()))

    def pool_state(self) -> Dict[str, dict]:
        with self._lock:
            return {
                p: {
                    "replicas_running": len(reps),
                    "replicas_target": self._targets.get(p, len(reps)),
                }
                for p, reps in self._pools.items()
            }

    def apply(self, decision: Decision) -> None:
        if not decision.is_scale_action or decision.target is None:
            return
        pool, want = decision.pool, max(0, decision.target)
        with self._lock:
            have = len(self._pools.get(pool, ()))
            self._targets[pool] = want
        if want > have:
            use_cold = (
                decision.action == ACTION_COLD_START
                and self._cold_start is not None
            )
            for _ in range(want - have):
                rep = (self._cold_start if use_cold else self._spawn)(pool)
                with self._lock:
                    self._pools.setdefault(pool, []).append(rep)
        elif want < have and decision.action in (
            ACTION_SCALE_DOWN, ACTION_SCALE_TO_ZERO,
        ):
            for _ in range(have - want):
                self._retire_one(pool)

    def _retire_one(self, pool: str) -> None:
        with self._lock:
            reps = self._pools.get(pool, [])
            if not reps:
                return
            victim = reps.pop()
        # chaos site: a replica can die mid-drain (in-process KILL_REPLICA
        # analog of a node preemption hitting the drain victim) — its
        # unfinished work must still be re-targeted, never lost
        killed = any(
            f.kind == _chaos.KILL_REPLICA
            for f in _chaos.fire(
                "autoscale.drain", kinds=(_chaos.KILL_REPLICA,), pool=pool
            )
        )
        leftovers: list = []
        if killed:
            self.num_drain_killed += 1
            pending = getattr(victim, "pending", None)
            if pending is not None:
                leftovers = list(pending())
        else:
            drain = getattr(victim, "drain", None)
            if drain is not None:
                leftovers = list(drain(self._drain_timeout_s) or ())
            self.num_drained += 1
        close = getattr(victim, "close", None)
        if close is not None:
            try:
                close()
            except Exception:  # noqa: BLE001 — victim may already be dead
                pass
        if leftovers:
            self.num_retargeted += len(leftovers)
            if self._requeue is not None:
                self._requeue(pool, leftovers)
            else:
                with self._lock:
                    survivors = self._pools.get(pool, ())
                    target = survivors[0] if survivors else None
                if target is not None:
                    for item in leftovers:
                        target.submit(item)
                else:
                    logger.warning(
                        "pool %s drained to zero with %d unfinished items "
                        "and no requeue hook", pool, len(leftovers),
                    )

    def close(self) -> None:
        with self._lock:
            pools, self._pools = self._pools, {}
        for reps in pools.values():
            for rep in reps:
                close = getattr(rep, "close", None)
                if close is not None:
                    try:
                        close()
                    except Exception:  # noqa: BLE001
                        pass
