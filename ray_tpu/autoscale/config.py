"""Configuration for the SLO closed-loop pool autoscaler (r20).

One config object covers the whole loop: per-pool replica bounds, the
hysteresis windows that keep a yellow blip from flapping the pool, the
cooldowns that space consecutive actions, and the prefill-sizing target
utilization. Everything is plain data — the policy consuming it is a
pure function of (signals, config, clock), so every window is unit-
testable without a cluster.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict

# Canonical pool names: match the serve controller's role tags (r10) and
# the r11 autoscaler_hints mapping (TTFT -> prefill, TPOT -> decode).
POOL_PREFILL = "prefill"
POOL_DECODE = "decode"


@dataclass
class PoolLimits:
    """Replica bounds for one pool.

    ``min_replicas=0`` opts the pool into scale-to-zero; a pool with a
    floor >= 1 is never drained below it regardless of idleness.
    """

    min_replicas: int = 0
    max_replicas: int = 4

    def __post_init__(self):
        if self.min_replicas < 0:
            raise ValueError("min_replicas must be >= 0")
        if self.max_replicas < max(1, self.min_replicas):
            raise ValueError("max_replicas must be >= max(1, min_replicas)")


@dataclass
class AutoscaleConfig:
    """Tuning for the PoolAutoscaler decision ladder.

    Hysteresis: a pool scales up only after ``breach_ticks`` CONSECUTIVE
    breached (yellow/red) observations and scales down only after
    ``green_ticks`` consecutive green ones — a single yellow blip, or a
    green blip in a red run, resets the opposing streak and holds.
    Cooldowns additionally space actions in wall time so the controller
    can never outrun the pool's own reaction to the last action.
    """

    pools: Dict[str, PoolLimits] = field(
        default_factory=lambda: {
            POOL_PREFILL: PoolLimits(),
            POOL_DECODE: PoolLimits(),
        }
    )
    # consecutive breached ticks before a scale-up fires
    breach_ticks: int = 2
    # consecutive green ticks before a scale-down fires
    green_ticks: int = 5
    scale_up_cooldown_s: float = 5.0
    scale_down_cooldown_s: float = 20.0
    # a zero-min pool with NO traffic and NO SLO data for this long is
    # drained to zero (cold-started back via fabric weight streaming)
    idle_to_zero_s: float = 30.0
    # prefill-pool sizing target: keep sum(arrival_rate x prefill_span)
    # per replica at this fraction of capacity (M/M/c style headroom)
    prefill_target_utilization: float = 0.6
    # replicas added/removed per decision (beyond the sized floor)
    max_step: int = 1
    # controller loop period
    interval_s: float = 1.0
    # signals older than this (GCS-side staleness) are treated as dark
    max_signal_age_s: float = 30.0

    def __post_init__(self):
        if self.breach_ticks < 1 or self.green_ticks < 1:
            raise ValueError("breach_ticks and green_ticks must be >= 1")
        if not 0.0 < self.prefill_target_utilization <= 1.0:
            raise ValueError("prefill_target_utilization must be in (0, 1]")
        if self.max_step < 1:
            raise ValueError("max_step must be >= 1")

    def limits(self, pool: str) -> PoolLimits:
        return self.pools.get(pool) or PoolLimits()
