"""Pure decision ladder for the SLO closed-loop pool autoscaler (r20).

The policy is deterministic state-machine code: given one tick's
``PoolSignals`` (derived from the GCS telemetry rollup — r11 grades +
``autoscaler_hints``, queue depth, prefill-span distribution, pending
lease demand) and an explicit clock, it emits exactly one ``Decision``
per pool. No I/O, no threads, no wall clock — every hysteresis window,
cooldown, sizing rule and scale-to-zero eligibility check is unit-
testable with a hand-rolled ``now``.

Ladder order (first match wins):

1. GCS dark -> HOLD, and RESET both streaks: a telemetry blackout is
   not evidence of anything, and recovery must re-earn consecutive
   ticks before any action (no flap on recovery).
2. Pool at zero + traffic -> COLD_START (fabric weight streaming, no
   checkpoint path).
3. Breach streak >= breach_ticks (+ up-cooldown) -> SCALE_UP, with the
   prefill pool additionally floored at the span-distribution sizing.
   Breaches accumulate only while the pool has offered load — cumulative
   histograms keep a grade hot long after traffic stops, and capacity is
   never added for zero demand. The prefill sizing rule also acts as a
   FEEDFORWARD term: when the measured span distribution says the pool
   is under-provisioned for the offered load (sized > target for
   breach_ticks consecutive ticks), it scales to the sized count
   without waiting for the cumulative p95 to degrade (whose detection
   lag grows with history).
4. Zero-min pool idle past idle_to_zero_s (+ down-cooldown) ->
   SCALE_TO_ZERO (always via graceful drain).
5. Green streak >= green_ticks (+ down-cooldown) -> SCALE_DOWN, never
   below max(min_replicas, sized floor, 1-while-traffic).
6. Otherwise HOLD (with the reason telling which window is pending).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, Optional

from ray_tpu.autoscale.config import AutoscaleConfig, POOL_PREFILL

# grade strings mirror ray_tpu.obs.telemetry (kept literal so this
# module stays importable without the telemetry plane)
GRADE_GREEN = "green"
GRADE_YELLOW = "yellow"
GRADE_RED = "red"
GRADE_NO_DATA = "no_data"

ACTION_HOLD = "hold"
ACTION_SCALE_UP = "scale_up"
ACTION_SCALE_DOWN = "scale_down"
ACTION_SCALE_TO_ZERO = "scale_to_zero"
ACTION_COLD_START = "cold_start"

ACTIONS = (
    ACTION_HOLD,
    ACTION_SCALE_UP,
    ACTION_SCALE_DOWN,
    ACTION_SCALE_TO_ZERO,
    ACTION_COLD_START,
)


@dataclass
class PoolSignals:
    """One tick's observed state for one pool."""

    grade: str = GRADE_NO_DATA
    # the r11 autoscaler_hints flag already mapped to this pool
    # (TTFT -> prefill, TPOT / queue_wait -> decode)
    breach: bool = False
    queue_depth: float = 0.0
    arrival_rate_per_s: float = 0.0
    # measured mean prefill span (s) from the merged distribution; only
    # the prefill pool carries it
    span_mean_s: Optional[float] = None
    running: int = 0
    target: Optional[int] = None
    # parked lease specs from the seed demand feed (ONE brain: pending
    # placement-group/lease demand is an input here, not a second loop)
    pending_demand: int = 0

    @property
    def has_traffic(self) -> bool:
        return (
            self.arrival_rate_per_s > 0.0
            or self.queue_depth > 0.0
            or self.pending_demand > 0
        )


@dataclass
class Decision:
    """One pool's action for one tick. ``target`` is the new desired
    replica count for any non-HOLD action."""

    pool: str
    action: str = ACTION_HOLD
    target: Optional[int] = None
    reason: str = ""

    @property
    def is_scale_action(self) -> bool:
        return self.action != ACTION_HOLD


@dataclass
class _PoolState:
    breach_streak: int = 0
    green_streak: int = 0
    sized_streak: int = 0
    idle_since: Optional[float] = None
    last_scale_up: float = float("-inf")
    last_scale_down: float = float("-inf")


def size_prefill_pool(
    arrival_rate_per_s: float,
    span_mean_s: Optional[float],
    target_utilization: float,
    max_replicas: Optional[int] = None,
) -> Optional[int]:
    """Replicas needed so offered prefill load (arrival rate x mean
    prefill span = mean busy servers, Little's law) sits at
    ``target_utilization`` per replica. None when the distribution has
    no data yet."""
    if span_mean_s is None or span_mean_s <= 0 or arrival_rate_per_s <= 0:
        return None
    offered = arrival_rate_per_s * span_mean_s
    n = max(1, math.ceil(offered / target_utilization))
    if max_replicas is not None:
        n = min(n, max_replicas)
    return n


def span_mean_from_histogram(hist: Optional[dict]) -> Optional[float]:
    """Mean from a merged-histogram dict ({"sum", "count", ...}) as the
    telemetry plane ships them; None below one observation."""
    if not hist:
        return None
    count = int(hist.get("count") or 0)
    if count <= 0:
        return None
    return float(hist.get("sum", 0.0)) / count


class PoolPolicy:
    """Per-pool hysteresis state + the decision ladder.

    Single-threaded by design: one controller loop owns it. All time is
    the caller's ``now`` (monotonic seconds)."""

    def __init__(self, config: AutoscaleConfig):
        self.config = config
        self._state: Dict[str, _PoolState] = {}

    def state(self, pool: str) -> _PoolState:
        st = self._state.get(pool)
        if st is None:
            st = self._state[pool] = _PoolState()
        return st

    def decide(
        self,
        pool: str,
        sig: PoolSignals,
        now: float,
        *,
        gcs_dark: bool = False,
    ) -> Decision:
        cfg = self.config
        lim = cfg.limits(pool)
        st = self.state(pool)

        # 1. dark control plane: a blackout is never evidence. HOLD and
        # reset streaks so recovery must re-earn consecutive ticks.
        if gcs_dark:
            st.breach_streak = 0
            st.green_streak = 0
            st.sized_streak = 0
            st.idle_since = None
            return Decision(pool, ACTION_HOLD, reason="gcs-dark: holding")

        target = sig.target if sig.target is not None else sig.running

        # streaks: breach and green are mutually exclusive; no_data
        # resets the breach streak (no breach evidence) and freezes the
        # green streak (no green evidence either). A breach counts only
        # while the pool has offered load: grades come from CUMULATIVE
        # histograms, so a bad stretch keeps the grade hot long after
        # traffic stops — capacity is never added for zero demand.
        if sig.breach and sig.has_traffic:
            st.breach_streak += 1
            st.green_streak = 0
        elif sig.grade == GRADE_GREEN:
            st.green_streak += 1
            st.breach_streak = 0
        else:
            st.breach_streak = 0

        # idle clock for scale-to-zero: runs while the pool sees no
        # traffic (windowed arrival rate, queue depth, pending demand).
        # Grades are computed from CUMULATIVE histograms, so "grade is
        # green" only says traffic once flowed — it never goes back to
        # no_data and must not keep an idle pool warm.
        if sig.has_traffic:
            st.idle_since = None
        elif st.idle_since is None:
            st.idle_since = now

        sized = None
        if pool == POOL_PREFILL:
            sized = size_prefill_pool(
                sig.arrival_rate_per_s, sig.span_mean_s,
                cfg.prefill_target_utilization, lim.max_replicas,
            )
        if sized is not None and sized > target and sig.has_traffic:
            st.sized_streak += 1
        else:
            st.sized_streak = 0

        # 2. cold start: pool parked at zero, work has arrived
        if target <= 0 and sig.has_traffic:
            want = max(1, lim.min_replicas, sized or 0)
            st.idle_since = None
            st.last_scale_up = now
            st.breach_streak = 0
            return Decision(
                pool, ACTION_COLD_START, target=want,
                reason=f"cold-start: traffic at zero replicas -> {want}",
            )

        up_ready = now - st.last_scale_up >= cfg.scale_up_cooldown_s
        down_ready = now - st.last_scale_down >= cfg.scale_down_cooldown_s

        # 3. scale up on a sustained breach
        if st.breach_streak >= cfg.breach_ticks and target < lim.max_replicas:
            if not up_ready:
                return Decision(
                    pool, ACTION_HOLD,
                    reason="breach sustained but scale-up cooldown active",
                )
            want = min(lim.max_replicas, max(target + cfg.max_step, sized or 0))
            if want > target:
                st.last_scale_up = now
                st.breach_streak = 0
                return Decision(
                    pool, ACTION_SCALE_UP, target=want,
                    reason=f"{sig.grade} breach x{cfg.breach_ticks}: "
                           f"{target} -> {want}",
                )

        # 3b. feedforward prefill sizing: the measured span distribution
        # says the pool is under-provisioned for the offered load —
        # scale to the sized count without waiting for the SLO to
        # degrade (cumulative-p95 breach detection lags by design; the
        # sizing rule is the feedforward term, breach hysteresis the
        # feedback term).
        if (
            st.sized_streak >= cfg.breach_ticks
            and 0 < target < lim.max_replicas
            and up_ready
        ):
            want = min(lim.max_replicas, sized)
            if want > target:
                st.last_scale_up = now
                st.sized_streak = 0
                st.breach_streak = 0
                return Decision(
                    pool, ACTION_SCALE_UP, target=want,
                    reason=f"span-sized {sized} > target {target} "
                           f"x{cfg.breach_ticks}: feedforward",
                )

        # 4. scale to zero: opted-in pool idle past the window
        if (
            lim.min_replicas == 0
            and target > 0
            and st.idle_since is not None
            and now - st.idle_since >= cfg.idle_to_zero_s
        ):
            if not down_ready:
                return Decision(
                    pool, ACTION_HOLD,
                    reason="idle-to-zero ready but scale-down cooldown active",
                )
            st.last_scale_down = now
            st.green_streak = 0
            st.idle_since = None
            return Decision(
                pool, ACTION_SCALE_TO_ZERO, target=0,
                reason=f"idle {cfg.idle_to_zero_s:g}s: drain {target} -> 0",
            )

        # 5. scale down after a sustained green run — via graceful drain,
        # never below the sized floor or (while serving) one replica
        floor = max(lim.min_replicas, sized or 0, 1 if sig.has_traffic else 0)
        floor = max(floor, 1) if target > 0 else floor
        if st.green_streak >= cfg.green_ticks and target > floor:
            if not down_ready:
                return Decision(
                    pool, ACTION_HOLD,
                    reason="green sustained but scale-down cooldown active",
                )
            want = max(floor, target - cfg.max_step)
            st.last_scale_down = now
            st.green_streak = 0
            return Decision(
                pool, ACTION_SCALE_DOWN, target=want,
                reason=f"green x{cfg.green_ticks}: drain {target} -> {want}",
            )

        # 6. hold, and say which window is pending
        if sig.breach:
            why = f"breach streak {st.breach_streak}/{cfg.breach_ticks}"
        elif sig.grade == GRADE_GREEN:
            why = f"green streak {st.green_streak}/{cfg.green_ticks}"
        elif st.idle_since is not None:
            why = f"idle {now - st.idle_since:.1f}/{cfg.idle_to_zero_s:g}s"
        else:
            why = "no data"
        return Decision(pool, ACTION_HOLD, reason=why)
