"""ray_tpu.autoscale — SLO closed-loop pool autoscaler (r20).

Closes ROADMAP item 2's loop: the r11 telemetry plane already grades
every model tag's TTFT/TPOT/queue-wait and emits ``autoscaler_hints``;
the serve controller (r10) exposes role-tagged pools with graceful
drain; the fabric weight plane (r15) can stream current weights to a
brand-new replica. This package is the controller in the middle:

* ``PoolPolicy`` / ``PoolAutoscaler`` — pure decision ladder + the loop
  driving it (prefill and decode scale independently; hysteresis +
  cooldowns; HOLD on a dark GCS; scale-down always via drain).
* ``size_prefill_pool`` — replica count from the measured prefill-span
  distribution (Little's law at a target utilization).
* ``cold_start_engine`` — zero -> serving via fabric weight streaming,
  bitwise-identical to the publisher, no checkpoint path.
* ``demand`` — the ONE bin-pack planning core shared with the seed
  node autoscalers (``ray_tpu.autoscaler``), whose pending-demand feed
  is one input signal here.
"""

from ray_tpu.autoscale.actuators import (
    EnginePoolActuator,
    FleetPoolActuator,
    PoolActuator,
    ServePoolActuator,
)
from ray_tpu.autoscale.coldstart import (
    ColdStartReport,
    cold_start_engine,
    params_bitwise_equal,
)
from ray_tpu.autoscale.config import (
    POOL_DECODE,
    POOL_PREFILL,
    AutoscaleConfig,
    PoolLimits,
)
from ray_tpu.autoscale.controller import PoolAutoscaler, signals_from_payload
from ray_tpu.autoscale.policy import (
    ACTION_COLD_START,
    ACTION_HOLD,
    ACTION_SCALE_DOWN,
    ACTION_SCALE_TO_ZERO,
    ACTION_SCALE_UP,
    Decision,
    PoolPolicy,
    PoolSignals,
    size_prefill_pool,
    span_mean_from_histogram,
)

__all__ = [
    "ACTION_COLD_START",
    "ACTION_HOLD",
    "ACTION_SCALE_DOWN",
    "ACTION_SCALE_TO_ZERO",
    "ACTION_SCALE_UP",
    "AutoscaleConfig",
    "ColdStartReport",
    "Decision",
    "EnginePoolActuator",
    "FleetPoolActuator",
    "POOL_DECODE",
    "POOL_PREFILL",
    "PoolActuator",
    "PoolAutoscaler",
    "PoolLimits",
    "PoolPolicy",
    "PoolSignals",
    "ServePoolActuator",
    "cold_start_engine",
    "params_bitwise_equal",
    "signals_from_payload",
    "size_prefill_pool",
    "span_mean_from_histogram",
]
