"""Autoscaler observability (r20): the ``ray_tpu_autoscale_`` series.

Every metric declares its aggregation kind via the cluster_* helpers so
the telemetry plane can roll controller replicas up without guessing;
``register_metrics`` is the scripts/check_metrics.py hook that forces
registration + declaration at lint time.
"""

from __future__ import annotations

from ray_tpu.obs.telemetry import (
    AGG_MAX,
    cluster_counter,
    cluster_gauge,
    cluster_histogram,
)
from ray_tpu.util.metrics import Counter, Gauge, Histogram

# cold starts are dominated by engine bring-up + one fabric weight
# stream: sub-second for tiny models, tens of seconds at size
_COLD_START_BOUNDARIES = [0.1, 0.25, 0.5, 1, 2.5, 5, 10, 30, 60, 120, 300]


def decisions_counter() -> Counter:
    return cluster_counter(
        "autoscale_decisions_total",
        description="autoscaler decisions by pool and action "
        "(hold / scale_up / scale_down / scale_to_zero / cold_start)",
        tag_keys=("pool", "action"),
    )


def scale_ups_counter() -> Counter:
    return cluster_counter(
        "autoscale_scale_ups_total",
        description="scale-up actions applied (cold starts included), "
        "by pool",
        tag_keys=("pool",),
    )


def scale_downs_counter() -> Counter:
    return cluster_counter(
        "autoscale_scale_downs_total",
        description="scale-down actions applied (always via graceful "
        "drain; scale-to-zero included), by pool",
        tag_keys=("pool",),
    )


def holds_counter() -> Counter:
    return cluster_counter(
        "autoscale_holds_total",
        description="ticks the controller explicitly held, by cause "
        "(gcs_dark / hysteresis / cooldown / steady)",
        tag_keys=("cause",),
    )


def cold_start_histogram() -> Histogram:
    return cluster_histogram(
        "autoscale_cold_start_seconds",
        description="seconds from cold-start decision to a replica "
        "serving with fabric-streamed weights (no checkpoint path)",
        boundaries=_COLD_START_BOUNDARIES,
        tag_keys=("pool",),
    )


def pool_target_gauge() -> Gauge:
    return cluster_gauge(
        "autoscale_pool_target",
        description="the controller's current desired replica count "
        "per pool",
        tag_keys=("pool",),
    )


def gcs_dark_gauge() -> Gauge:
    return cluster_gauge(
        "autoscale_gcs_dark",
        description="1 while the controller cannot fetch fresh signals "
        "from the GCS (decisions degrade to HOLD), else 0",
        agg=AGG_MAX,
    )


def register_metrics() -> None:
    """scripts/check_metrics.py hook: force autoscaler metrics to
    register and their aggregation kinds to be declared."""
    decisions_counter()
    scale_ups_counter()
    scale_downs_counter()
    holds_counter()
    cold_start_histogram()
    pool_target_gauge()
    gcs_dark_gauge()
