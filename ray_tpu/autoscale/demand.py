"""Shared demand-driven launch planning (the ONE autoscaling brain's
bin-pack core, r20).

Both seed reconcilers — the in-process ``StandardAutoscaler`` (scheduler
queue + pending PGs) and the cluster-plane ``ClusterAutoscaler``
(heartbeat lease-spec feed) — previously carried near-identical
first-fit-decreasing loops. They now delegate here, and the r20
``PoolAutoscaler`` consumes the same pending-demand count as one input
signal, so demand planning has exactly one implementation.

Pure functions over plain data: no provider, no clock, no logging.
"""

from __future__ import annotations

from typing import Callable, Iterable, List, Tuple


def fits(req: dict, cap: dict) -> bool:
    """True when every requested resource is available in ``cap``."""
    return all(cap.get(k, 0.0) >= v for k, v in req.items())


def plan_launches(
    demand: List[dict],
    node_types: dict,
    count: Callable[[str], int],
    seed_capacity: Iterable[dict] = (),
) -> Tuple[List[str], List[dict]]:
    """First-fit-decreasing bin pack of unmet demand onto new nodes.

    ``node_types`` maps name -> config with ``.resources`` and
    ``.max_workers``; ``count(name)`` is how many of that type already
    exist (launched or launching); ``seed_capacity`` is leftover room on
    nodes already bought but not yet absorbed (the ClusterAutoscaler's
    in-flight launches), consumed before anything new is planned.

    Returns ``(planned_type_names, unplaced_requests)`` — the caller
    launches the former and logs the latter.
    """
    planned: list[dict] = [dict(cap) for cap in seed_capacity]
    planned_types: list[str] = []
    unplaced: list[dict] = []
    for req in sorted(demand, key=lambda d: -sum(d.values())):
        placed = False
        for cap in planned:
            if fits(req, cap):
                for k, v in req.items():
                    cap[k] = cap.get(k, 0.0) - v
                placed = True
                break
        if placed:
            continue
        for tname, tcfg in node_types.items():
            if (
                fits(req, tcfg.resources)
                and count(tname) + planned_types.count(tname) < tcfg.max_workers
            ):
                cap = dict(tcfg.resources)
                for k, v in req.items():
                    cap[k] = cap.get(k, 0.0) - v
                planned.append(cap)
                planned_types.append(tname)
                placed = True
                break
        if not placed:
            unplaced.append(req)
    return planned_types, unplaced
