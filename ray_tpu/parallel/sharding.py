"""Logical-axis sharding rules: model code names axes, rules map them to mesh axes.

Models annotate every parameter/activation dimension with a *logical* name
("embed", "heads", "batch", ...). A `ShardingRules` table maps logical
names to mesh axes (or None = replicated). This decouples model code from
the parallelism layout — change the rules, not the model, to go from pure
DP to FSDP+TP+SP. (The reference delegates this entirely to torch FSDP /
vLLM internals; here it is a first-class framework concept, in the style
of GSPMD logical axis annotations.)
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

# Default layout: batch split over (dp, fsdp); params sharded ZeRO-3-style
# over fsdp on their "embed"-ish dim and Megatron-style over tp on their
# "heads"/"mlp" dim; sequence split over sp for context parallelism;
# experts over ep.
DEFAULT_RULES: dict[str, object] = {
    "batch": ("dp", "fsdp"),
    "seq": "sp",
    "embed": "fsdp",
    "mlp": "tp",
    "heads": "tp",
    "kv_heads": "tp",
    "head_dim": None,
    "vocab": "tp",
    "layers": None,
    "stage": "pp",
    "expert": "ep",
    "norm": None,
}


class ShardingRules(dict):
    """Mapping logical axis name -> mesh axis (str), tuple of mesh axes, or None."""

    def spec(self, logical_axes: tuple[str | None, ...]) -> PartitionSpec:
        parts = []
        for ax in logical_axes:
            if ax is None:
                parts.append(None)
            else:
                if ax not in self:
                    raise KeyError(f"no sharding rule for logical axis {ax!r}")
                parts.append(self[ax])
        return P(*parts)

    def sharding(self, mesh: Mesh, logical_axes: tuple[str | None, ...]) -> NamedSharding:
        return NamedSharding(mesh, self.spec(logical_axes))


def default_rules(**overrides) -> ShardingRules:
    rules = ShardingRules(DEFAULT_RULES)
    rules.update(overrides)
    return rules


def tree_specs(rules: ShardingRules, logical_tree) -> object:
    """Map a pytree of logical-axis tuples to a pytree of PartitionSpecs."""
    return jax.tree.map(
        lambda axes: rules.spec(axes),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple) and all(isinstance(a, (str, type(None))) for a in x),
    )


def tree_shardings(mesh: Mesh, rules: ShardingRules, logical_tree) -> object:
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        tree_specs(rules, logical_tree),
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def constrain(x, mesh: Mesh, rules: ShardingRules, logical_axes: tuple[str | None, ...]):
    """with_sharding_constraint by logical axes (no-op outside jit)."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, rules.spec(logical_axes)))


def shard_map_compat(f, *, mesh: Mesh, in_specs, out_specs,
                     axis_names=None, check_vma: bool = True):
    """`jax.shard_map` across jax versions.

    New jax exposes `jax.shard_map(f, mesh=, in_specs=, out_specs=,
    axis_names=, check_vma=)`; 0.4.x has `jax.experimental.shard_map`
    with `check_rep=` (the old name for check_vma) and `auto=` (the
    COMPLEMENT of axis_names: axes left to the compiler). Manual mesh
    axes and replication checking mean the same thing in both.
    """
    if hasattr(jax, "shard_map"):
        kwargs = {"check_vma": check_vma}
        if axis_names is not None:
            kwargs["axis_names"] = frozenset(axis_names)
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    # 0.4.x fallback: always FULLY manual. The partial-manual form
    # (auto = complement of axis_names) lowers to a PartitionId HLO the
    # 0.4.x SPMD partitioner rejects; with full manual, axes the specs
    # don't mention are simply replicated through the body — numerically
    # identical, at worst redundant compute on those axes.
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
