"""Pipeline parallelism: GPipe-style collective pipelining over the mesh
`pp` axis, inside ONE jitted SPMD program.

Reference analog: Ray's pipeline parallelism is delegated — vLLM drives
PP through compiled graphs (python/ray/dag/compiled_dag_node.py:795)
with NCCL channels between stage actors, configured by
pipeline_parallel_degree (llm/.../vllm/vllm_models.py:121). TPU-native
redesign: stages are shards of the `pp` mesh axis; inter-stage transfer
is `lax.ppermute` over ICI (the channel), and the microbatch schedule is
a `lax.scan` — the whole pipeline compiles to one XLA program, no
per-hop driver round-trips, and autodiff differentiates straight
through the schedule (GPipe: backward replays stages in reverse).

Schedule: classic GPipe fill-drain with rotating buffers. With S stages
and M = S microbatches the scan runs 2S - 1 ticks; at tick t, stage s
computes microbatch t - s (mod S, garbage outside the window — the
bubble). Microbatch inputs live SHARDED over pp (stage s starts holding
microbatch s) and rotate -1 each tick so stage 0 always finds the next
microbatch locally; retired outputs rotate -1 likewise so microbatch j
ends resident on stage j. Everything cross-stage is a ppermute — no
all-reduce anywhere in the forward OR backward path (the transpose of a
ppermute is the inverse ppermute), which keeps bf16 activations off
XLA-CPU's fragile all-reduce promotion pass and keeps TPU traffic to
neighbor hops on the ICI ring.
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from ray_tpu.parallel.sharding import shard_map_compat
from jax.sharding import PartitionSpec as P


def pipeline_apply(
    mesh,
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    h: jax.Array,
    n_micro: Optional[int] = None,
    axis: str = "pp",
) -> jax.Array:
    """Apply a stack of layers pipelined over the mesh `pp` axis.

    stage_fn(stage_params, x) applies ONE stage's layers (leading dim of
    stage_params = layers_per_stage) to activations x [mb, S, D].
    stacked_params: pytree with leading dim n_stages (sharded over pp).
    h: [B, S, D] full-batch activations entering the stack.

    Returns activations after all stages, [B, S, D] — numerically equal
    to applying the stages sequentially (GPipe semantics).
    """
    pp = mesh.shape[axis]
    if pp == 1:  # degenerate: no pipeline, just run the single stage
        return stage_fn(jax.tree.map(lambda x: x[0], stacked_params), h)
    M = int(n_micro) if n_micro else pp
    if M != pp:
        raise NotImplementedError(
            f"rotating-buffer schedule needs n_micro == pp (got {M} != {pp})"
        )
    B = h.shape[0]
    if B % M != 0:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    hm = h.reshape((M, B // M) + h.shape[1:])
    fwd = [(i, (i + 1) % pp) for i in range(pp)]  # to the next stage
    bwd = [(i, (i - 1) % pp) for i in range(pp)]  # buffer rotation
    last = pp - 1

    def body(hm_local, stage_params):
        # manual over `pp` only: hm_local [1, mb, S, D] is THIS stage's
        # resident microbatch; stage_params this stage's layer slice
        stage_params = jax.tree.map(lambda x: x[0], stage_params)
        stage = jax.lax.axis_index(axis)
        inputs = hm_local[0]
        state = jnp.zeros_like(inputs)
        out_buf = jnp.zeros_like(inputs)

        def tick(carry, t):
            inputs, state, out_buf = carry
            # retired microbatches drift -1 so microbatch j lands on stage j
            out_buf = jax.lax.ppermute(out_buf, axis, bwd)
            x = jnp.where(stage == 0, inputs, state)
            y = stage_fn(stage_params, x)
            out_idx = t - last
            writing = (stage == last) & (out_idx >= 0) & (out_idx < M)
            out_buf = jnp.where(writing, y, out_buf)
            state = jax.lax.ppermute(y, axis, fwd)
            inputs = jax.lax.ppermute(inputs, axis, bwd)
            return (inputs, state, out_buf), None

        (inputs, state, out_buf), _ = jax.lax.scan(
            tick, (inputs, state, out_buf), jnp.arange(M + pp - 1)
        )
        return out_buf[None]  # [1, mb, S, D], sharded back over pp

    out = shard_map_compat(
        body,
        mesh=mesh,
        in_specs=(P(axis), P(axis)),
        out_specs=P(axis),
        axis_names=frozenset({axis}),
        check_vma=False,
    )(hm, stacked_params)
    return out.reshape(h.shape)


def stack_stages(layer_params: Any, n_stages: int) -> Any:
    """[L, ...] stacked layer params -> [n_stages, L/n_stages, ...]."""

    def split(x):
        L = x.shape[0]
        if L % n_stages != 0:
            raise ValueError(f"{L} layers not divisible by {n_stages} stages")
        return x.reshape((n_stages, L // n_stages) + x.shape[1:])

    return jax.tree.map(split, layer_params)
