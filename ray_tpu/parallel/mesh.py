"""Device-mesh construction: the TPU-native substrate for every parallelism axis.

Where the reference wires parallelism through per-worker process groups
(reference: python/ray/train/torch/config.py:115 `dist.init_process_group`
and python/ray/util/collective NCCL groups), a TPU framework expresses all
of DP/FSDP/PP/TP/SP/EP as axes of a single `jax.sharding.Mesh` over the
slice's chips; XLA then lowers the program's shardings to ICI collectives.
This module owns the mesh axis convention used everywhere else:

    ("dp", "pp", "fsdp", "ep", "sp", "tp")

Axis order encodes ICI locality: `tp` is innermost (highest-bandwidth
neighbors, most latency-sensitive collectives), `dp` outermost (pure
gradient allreduce, can ride DCN between slices).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

MESH_AXES = ("dp", "pp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Degrees of each parallelism axis. Product must equal device count
    (use -1 for one axis to infer it)."""

    dp: int = 1
    pp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def sizes(self) -> tuple[int, ...]:
        return (self.dp, self.pp, self.fsdp, self.ep, self.sp, self.tp)

    def resolve(self, n_devices: int) -> "MeshSpec":
        """Fill in a single -1 axis so the product matches n_devices."""
        sizes = list(self.sizes())
        if sizes.count(-1) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if -1 in sizes:
            known = math.prod(s for s in sizes if s != -1)
            if n_devices % known != 0:
                raise ValueError(
                    f"cannot infer axis: {n_devices} devices not divisible by {known}"
                )
            sizes[sizes.index(-1)] = n_devices // known
        if math.prod(sizes) != n_devices:
            raise ValueError(
                f"mesh spec {sizes} (= {math.prod(sizes)}) != device count {n_devices}"
            )
        return MeshSpec(*sizes)

    @classmethod
    def data_parallel(cls, n: int = -1) -> "MeshSpec":
        return cls(dp=n)

    @classmethod
    def fsdp_only(cls, n: int = -1) -> "MeshSpec":
        return cls(fsdp=n)


def make_mesh(
    spec: MeshSpec | None = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the framework-standard 6-axis mesh.

    `mesh_utils.create_device_mesh` lays physical chips out so that the
    innermost axes land on ICI-adjacent neighbors (torus-aware on TPU).
    """
    if devices is None:
        devices = jax.devices()
    spec = (spec or MeshSpec()).resolve(len(devices))
    if len(devices) == 1:
        dev_array = np.asarray(devices).reshape(spec.sizes())
    else:
        dev_array = mesh_utils.create_device_mesh(
            spec.sizes(), devices=list(devices), allow_split_physical_axes=True
        )
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh(device: Optional[jax.Device] = None) -> Mesh:
    devs = [device] if device is not None else jax.devices()[:1]
    return Mesh(np.asarray(devs).reshape((1,) * len(MESH_AXES)), MESH_AXES)


def mesh_shape(mesh: Mesh) -> MeshSpec:
    return MeshSpec(**{a: mesh.shape[a] for a in MESH_AXES})
