"""Ambient parallelism context: the active (mesh, rules) pair.

Model code that needs mesh-aware ops (ring attention over the `sp` axis,
expert all-to-all over `ep`) reads the ambient context instead of
threading a Mesh through every function signature. Trainers enter it
around their jitted step; tests enter it explicitly.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

from jax.sharding import Mesh

from ray_tpu.parallel.sharding import ShardingRules, default_rules


class _Ctx(threading.local):
    def __init__(self):
        self.stack: list[tuple[Mesh, ShardingRules]] = []


_ctx = _Ctx()


@contextlib.contextmanager
def parallel_context(mesh: Mesh, rules: Optional[ShardingRules] = None):
    _ctx.stack.append((mesh, rules if rules is not None else default_rules()))
    try:
        yield
    finally:
        _ctx.stack.pop()


def current_mesh() -> Optional[Mesh]:
    return _ctx.stack[-1][0] if _ctx.stack else None


def current_rules() -> Optional[ShardingRules]:
    return _ctx.stack[-1][1] if _ctx.stack else None
