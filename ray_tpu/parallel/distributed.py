"""Multi-host SPMD bootstrap: jax.distributed over the cluster plane.

Reference analog: Ray Train's per-worker process-group bootstrap — the
backend hook sets MASTER_ADDR/PORT from worker 0 and calls
torch.distributed.init_process_group inside every worker
(/python/ray/train/torch/config.py:115,153-173); on TPU pods the
coordinator is elected via the `TPU-{pod}-head` resource
(/python/ray/_private/accelerators/tpu.py:330-393). TPU-native
redesign: the "process group" is `jax.distributed.initialize` — after
it, every process sees the GLOBAL device fleet and XLA collectives run
over ICI/DCN with no NCCL analog to wrap. The CPU fallback backend
(tests, laptops) is the same call with gloo cross-process collectives
and a virtual per-process device fleet.
"""

from __future__ import annotations

import os
import socket
from dataclasses import dataclass
from typing import Optional

from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.parallel.distributed")


@dataclass
class JaxDistributedConfig:
    """Backend config for a distributed gang (the TorchConfig analog).

    enabled: run jax.distributed.initialize in every worker before the
        user loop; jax.devices() then spans the whole gang.
    platform: pin a platform first ("cpu" for the test backend; None
        keeps the ambient TPU platform).
    local_device_count: for platform="cpu", fake this many devices per
        process (XLA_FLAGS --xla_force_host_platform_device_count).
    coordinator_port: fixed port for worker 0's coordinator (default:
        picked free at bootstrap time).
    """

    enabled: bool = True
    platform: Optional[str] = None
    local_device_count: Optional[int] = None
    coordinator_port: Optional[int] = None


def reserve_coordinator_address(
    host: Optional[str] = None, port: Optional[int] = None
) -> str:
    """Pick `host:port` for the jax.distributed coordinator (run on the
    rank-0 worker; the port is free at reservation time)."""
    if host is None:
        host = os.environ.get("RAY_TPU_NODE_IP", "127.0.0.1")
    if port is None:
        s = socket.socket()
        s.bind((host, 0))
        port = s.getsockname()[1]
        s.close()
    return f"{host}:{port}"


def initialize_gang_member(
    coordinator_address: str,
    num_processes: int,
    process_id: int,
    config: Optional[JaxDistributedConfig] = None,
) -> None:
    """Run the jax.distributed bootstrap in this process (gang member).

    Must run before the first backend touch (jax.devices/jit). After it,
    `jax.devices()` is the global fleet and jitted collectives cross
    process boundaries (ICI on TPU slices, gloo on the CPU test backend).
    """
    config = config or JaxDistributedConfig()
    if config.platform == "cpu" and config.local_device_count:
        import re

        # replace (not just append): the controller's env may already pin a
        # different virtual device count and children inherit it
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+",
            "",
            os.environ.get("XLA_FLAGS", ""),
        ).strip()
        want = f"--xla_force_host_platform_device_count={config.local_device_count}"
        os.environ["XLA_FLAGS"] = f"{flags} {want}".strip()

    import jax

    if config.platform:
        jax.config.update("jax_platforms", config.platform)
        if config.platform == "cpu":
            # cross-process collectives on the CPU backend ride gloo
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "gang member %d/%d up: %d global / %d local devices",
        process_id, num_processes,
        len(jax.devices()), len(jax.local_devices()),
    )
