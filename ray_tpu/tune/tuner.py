"""Tuner + trial controller (reference: python/ray/tune/tuner.py:43 and
execution/tune_controller.py:68).

The controller is an event loop over trial actors on the task runtime:
class trainables are driven step-by-step (one in-flight `train()` ref
per trial), function trainables stream results through a report queue
(the same session mechanism JaxTrainer workers use). Schedulers see
every result and can stop trials (ASHA/median) or request
checkpoint-clone exploits (PBT)."""

from __future__ import annotations

import inspect
import itertools
import queue
import threading
import time
import traceback
from typing import Any, Callable, Optional

from ray_tpu.core import api
from ray_tpu.train import session as session_mod
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.result import Result
from ray_tpu.tune import schedulers as sched_mod
from ray_tpu.tune.search import BasicVariantGenerator, Searcher
from ray_tpu.tune.schedulers import CONTINUE, STOP, FIFOScheduler, TrialScheduler
from ray_tpu.tune.trainable import Trainable
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.tune")


class TuneConfig:
    def __init__(
        self,
        *,
        metric: Optional[str] = None,
        mode: str = "min",
        num_samples: int = 1,
        max_concurrent_trials: Optional[int] = None,
        search_alg: Optional[Searcher] = None,
        scheduler: Optional[TrialScheduler] = None,
        seed: Optional[int] = None,
    ):
        self.metric = metric
        self.mode = mode
        self.num_samples = num_samples
        self.max_concurrent_trials = max_concurrent_trials
        self.search_alg = search_alg
        self.scheduler = scheduler or FIFOScheduler()
        self.seed = seed


class Trial:
    _ids = itertools.count()

    def __init__(self, config: dict):
        self.trial_id = f"trial_{next(Trial._ids):05d}"
        self.config = config
        self.status = "PENDING"
        self.history: list[dict] = []
        self.last_result: dict = {}
        self.checkpoint: Optional[Checkpoint] = None
        self.error: Optional[BaseException] = None

    def record(self, metrics: dict):
        self.history.append(metrics)
        self.last_result = metrics

    def to_result(self) -> Result:
        return Result(
            metrics=dict(self.last_result),
            checkpoint=self.checkpoint,
            path=None,
            error=self.error,
            metrics_history=self.history,
        )


class ResultGrid:
    def __init__(self, trials: list[Trial], metric: Optional[str], mode: str):
        self._trials = trials
        self._metric = metric
        self._mode = mode

    def __len__(self):
        return len(self._trials)

    def __getitem__(self, i) -> Result:
        return self._trials[i].to_result()

    @property
    def errors(self) -> list[BaseException]:
        return [t.error for t in self._trials if t.error is not None]

    @property
    def num_errors(self) -> int:
        return len(self.errors)

    def get_best_result(
        self, metric: Optional[str] = None, mode: Optional[str] = None
    ) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set in TuneConfig or pass here)")
        scored = [t for t in self._trials if metric in t.last_result]
        if not scored:
            raise ValueError(f"no trial reported metric {metric!r}")
        best = (min if mode == "min" else max)(
            scored, key=lambda t: t.last_result[metric]
        )
        return best.to_result()

    def get_dataframe(self):
        import pandas as pd

        return pd.DataFrame([{"trial_id": t.trial_id, **t.last_result} for t in self._trials])


@api.remote
class _ClassTrialRunner:
    def __init__(self, cls, config):
        self._cls = cls
        self._t = cls(config)

    def train(self) -> dict:
        return self._t.train()

    def save(self):
        return (self._t.save_checkpoint(), self._t.iteration)

    def restore(self, state, new_config: Optional[dict] = None):
        ckpt, iteration = state
        if new_config is not None and not self._t.reset_config(new_config):
            self._t.cleanup()
            self._t = self._cls(new_config)
        self._t.load_checkpoint(ckpt)
        self._t.iteration = iteration
        return True

    def cleanup(self):
        self._t.cleanup()
        return True


@api.remote
class _FnTrialRunner:
    """Function trainable: runs fn(config) under a train session so
    tune.report streams results to the controller's queue."""

    def __init__(self, report_queue, stop_event):
        self._ctx = session_mod.TrainContext(
            world_rank=0,
            world_size=1,
            trial_dir="",
            report_queue=report_queue,
            stop_event=stop_event,
        )

    def run(self, fn, config) -> str:
        session_mod._set_session(self._ctx)
        try:
            fn(config)
            return "done"
        except StopIteration:
            return "stopped"
        finally:
            session_mod._clear_session()


class _RunningTrial:
    def __init__(self, trial: Trial, kind: str, actor, *, run_ref=None, q=None, stop=None):
        self.trial = trial
        self.kind = kind  # "class" | "fn"
        self.actor = actor
        self.step_ref = None  # class: in-flight train() ref
        self.run_ref = run_ref  # fn: final-status ref
        self.queue = q
        self.stop_event = stop
        self.stopping = False


class Tuner:
    def __init__(
        self,
        trainable,
        *,
        param_space: Optional[dict] = None,
        tune_config: Optional[TuneConfig] = None,
        run_config=None,
        stop: Optional[dict] = None,
    ):
        self._trainable = trainable
        self._space = param_space or {}
        self._cfg = tune_config or TuneConfig()
        self._run_config = run_config
        self._stop = stop or {}

    # -- trial lifecycle ----------------------------------------------------

    def _launch(self, trial: Trial) -> _RunningTrial:
        trial.status = "RUNNING"
        resources = dict(getattr(self._trainable, "__ray_tpu_resources__", None) or {})
        opts = {
            "num_cpus": resources.pop("CPU", 0),
            "num_tpus": resources.pop("TPU", 0),
        }
        if resources:
            opts["resources"] = resources  # custom resources pass through
        if isinstance(self._trainable, type) and issubclass(self._trainable, Trainable):
            actor = _ClassTrialRunner.options(**opts).remote(self._trainable, trial.config)
            rt = _RunningTrial(trial, "class", actor)
            rt.step_ref = actor.train.remote()
            return rt
        q: queue.Queue = queue.Queue()
        stop = threading.Event()
        actor = _FnTrialRunner.options(**opts).remote(q, stop)
        run_ref = actor.run.remote(self._trainable, trial.config)
        return _RunningTrial(trial, "fn", actor, run_ref=run_ref, q=q, stop=stop)

    def _finish(self, rt: _RunningTrial, status: str, error=None):
        rt.trial.status = status
        rt.trial.error = error
        try:
            api.kill(rt.actor)
        except Exception:
            pass

    def _should_stop_by_criteria(self, metrics: dict) -> bool:
        for k, v in self._stop.items():
            if k in metrics and metrics[k] >= v:
                return True
        return False

    def _handle_result(self, rt: _RunningTrial, metrics: dict, scheduler) -> str:
        rt.trial.record(metrics)
        decision = scheduler.on_result(rt.trial, metrics)
        if self._should_stop_by_criteria(metrics):
            decision = STOP
        # PBT exploit: clone weights+config from a better trial
        exploits = getattr(scheduler, "pending_exploits", None)
        if exploits and rt.trial.trial_id in exploits:
            src_id = exploits.pop(rt.trial.trial_id)
            self._exploit(rt, src_id)
        return decision

    def _exploit(self, rt: _RunningTrial, src_id: str):
        src = self._running.get(src_id)
        if src is None or src.kind != "class" or rt.kind != "class":
            logger.warning(
                "PBT exploit dropped for %s (src=%s): exploits need class "
                "trainables with the source trial still running",
                rt.trial.trial_id, src_id,
            )
            return
        scheduler = self._cfg.scheduler
        new_config = scheduler.perturb(src.trial.config)
        try:
            state = api.get(src.actor.save.remote())
            api.get(rt.actor.restore.remote(state, new_config))
            rt.trial.config = new_config
            if hasattr(scheduler, "on_exploit"):
                # the score jump from the checkpoint clone must not be
                # attributed to the new config (PB2's GP dataset)
                scheduler.on_exploit(rt.trial.trial_id)
            logger.info(
                "PBT exploit: %s cloned %s with config %s",
                rt.trial.trial_id, src_id, new_config,
            )
        except Exception as e:  # noqa: BLE001 - exploit is best-effort
            logger.warning("PBT exploit failed: %s", e)

    # -- main loop ----------------------------------------------------------

    def fit(self) -> ResultGrid:
        cfg = self._cfg
        searcher = cfg.search_alg or BasicVariantGenerator(
            self._space, num_samples=cfg.num_samples, seed=cfg.seed
        )
        scheduler = cfg.scheduler
        if hasattr(scheduler, "metric") and scheduler.metric is None:
            scheduler.metric = cfg.metric or "loss"
        if hasattr(scheduler, "mode") and scheduler.mode is None:
            scheduler.mode = cfg.mode or "min"
        max_conc = cfg.max_concurrent_trials or 8

        trials: list[Trial] = []
        self._running: dict[str, _RunningTrial] = {}
        exhausted = False

        while True:
            # launch up to the concurrency cap
            while not exhausted and len(self._running) < max_conc:
                sid = f"t{len(trials)}"
                config = searcher.suggest(sid)
                if config is None:
                    exhausted = True
                    break
                if config == "__pending__":
                    break
                trial = Trial(config)
                trials.append(trial)
                rt = self._launch(trial)
                # the id the searcher knows this trial by (ConcurrencyLimiter
                # tracks liveness per suggest id)
                rt.search_id = sid
                self._running[trial.trial_id] = rt

            if not self._running:
                if exhausted:
                    break
                time.sleep(0.01)
                continue

            progressed = False
            for tid, rt in list(self._running.items()):
                if rt.kind == "class":
                    progressed |= self._poll_class_trial(tid, rt, scheduler, searcher)
                else:
                    progressed |= self._poll_fn_trial(tid, rt, scheduler, searcher)
            if not progressed:
                time.sleep(0.005)

        return ResultGrid(trials, cfg.metric, cfg.mode)

    def _poll_class_trial(self, tid, rt, scheduler, searcher) -> bool:
        ready, _ = api.wait([rt.step_ref], num_returns=1, timeout=0)
        if not ready:
            return False
        try:
            metrics = api.get(rt.step_ref)
        except Exception as e:  # noqa: BLE001 - trial failure
            self._finish(rt, "ERROR", e)
            scheduler.on_complete(rt.trial)
            searcher.on_trial_complete(getattr(rt, "search_id", tid), None)
            del self._running[tid]
            return True
        decision = self._handle_result(rt, metrics, scheduler)
        if decision == STOP:
            self._finish(rt, "TERMINATED")
            scheduler.on_complete(rt.trial)
            searcher.on_trial_complete(getattr(rt, "search_id", tid), metrics)
            del self._running[tid]
        else:
            rt.step_ref = rt.actor.train.remote()
        return True

    def _drain_reports(self, rt: _RunningTrial, scheduler) -> bool:
        progressed = False
        try:
            while True:
                rep = rt.queue.get_nowait()
                progressed = True
                metrics = rep["metrics"]
                if rep.get("checkpoint") is not None:
                    rt.trial.checkpoint = rep["checkpoint"]
                metrics.setdefault("training_iteration", len(rt.trial.history) + 1)
                decision = self._handle_result(rt, metrics, scheduler)
                if decision == STOP and not rt.stopping:
                    rt.stopping = True
                    rt.stop_event.set()
        except queue.Empty:
            pass
        return progressed

    def _poll_fn_trial(self, tid, rt, scheduler, searcher) -> bool:
        progressed = self._drain_reports(rt, scheduler)
        ready, _ = api.wait([rt.run_ref], num_returns=1, timeout=0)
        if ready:
            # re-drain: reports enqueued between the drain above and the
            # run finishing would otherwise be lost with the trial
            self._drain_reports(rt, scheduler)
            try:
                api.get(rt.run_ref)
                self._finish(rt, "TERMINATED")
            except Exception as e:  # noqa: BLE001
                self._finish(rt, "ERROR", e)
            scheduler.on_complete(rt.trial)
            searcher.on_trial_complete(getattr(rt, "search_id", tid), rt.trial.last_result or None)
            del self._running[tid]
            progressed = True
        return progressed


def run(
    trainable,
    *,
    config: Optional[dict] = None,
    num_samples: int = 1,
    metric: Optional[str] = None,
    mode: str = "min",
    scheduler: Optional[TrialScheduler] = None,
    search_alg: Optional[Searcher] = None,
    stop: Optional[dict] = None,
    max_concurrent_trials: Optional[int] = None,
) -> ResultGrid:
    """Functional entry point (reference: tune.run)."""
    return Tuner(
        trainable,
        param_space=config,
        tune_config=TuneConfig(
            metric=metric,
            mode=mode,
            num_samples=num_samples,
            scheduler=scheduler,
            search_alg=search_alg,
            max_concurrent_trials=max_concurrent_trials,
        ),
        stop=stop,
    ).fit()
