"""ray_tpu.tune: hyperparameter search (reference: python/ray/tune/).

Tuner drives trials (class or function trainables) as actors on the
runtime; searchers expand param spaces; schedulers early-stop (ASHA,
median) or evolve (PBT) trials from streaming results.
"""

from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.session import report, get_checkpoint, get_context
from ray_tpu.tune.schedulers import (
    PB2,
    AsyncHyperBandScheduler,
    FIFOScheduler,
    MedianStoppingRule,
    PopulationBasedTraining,
    TrialScheduler,
)
from ray_tpu.tune.search import (
    AskTellSearcher,
    BasicVariantGenerator,
    ConcurrencyLimiter,
    Repeater,
    Searcher,
    choice,
    grid_search,
    loguniform,
    quniform,
    randint,
    randn,
    sample_from,
    uniform,
)
from ray_tpu.tune.trainable import Trainable, with_parameters, with_resources
from ray_tpu.tune.tuner import ResultGrid, TuneConfig, Tuner, run

ASHAScheduler = AsyncHyperBandScheduler

__all__ = [
    "ASHAScheduler",
    "AskTellSearcher",
    "AsyncHyperBandScheduler",
    "BasicVariantGenerator",
    "Checkpoint",
    "ConcurrencyLimiter",
    "FIFOScheduler",
    "MedianStoppingRule",
    "PB2",
    "PopulationBasedTraining",
    "Repeater",
    "ResultGrid",
    "Searcher",
    "Trainable",
    "TrialScheduler",
    "TuneConfig",
    "Tuner",
    "choice",
    "get_checkpoint",
    "get_context",
    "grid_search",
    "loguniform",
    "quniform",
    "randint",
    "randn",
    "report",
    "run",
    "sample_from",
    "uniform",
    "with_parameters",
    "with_resources",
]
