"""Trial schedulers (reference: python/ray/tune/schedulers/): early
stopping and population-based training decisions driven by streaming
trial results."""

from __future__ import annotations

import math
import random
from typing import Optional

import numpy as np

CONTINUE = "CONTINUE"
STOP = "STOP"


class TrialScheduler:
    def on_result(self, trial, result: dict) -> str:
        return CONTINUE

    def on_complete(self, trial) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    pass


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA (reference: tune/schedulers/async_hyperband.py): promote only
    trials in the top 1/reduction_factor at each rung; stop the rest."""

    def __init__(
        self,
        metric: "str | None" = None,
        mode: "str | None" = None,
        max_t: int = 100,
        grace_period: int = 1,
        reduction_factor: float = 4,
        time_attr: str = "training_iteration",
    ):
        self.metric, self.mode = metric, mode
        self.max_t, self.grace = max_t, grace_period
        self.rf = reduction_factor
        self.time_attr = time_attr
        # rung value -> list of recorded metric values (one per trial:
        # a trial is judged once per rung, at its first crossing)
        self.rungs: dict[int, list[float]] = {}
        self._recorded: set[tuple[str, int]] = set()
        r = grace_period
        self._rung_levels = []
        while r < max_t:
            self._rung_levels.append(int(r))
            r *= reduction_factor

    def _better(self, v: float, cutoff: float) -> bool:
        return v <= cutoff if self.mode != "max" else v >= cutoff

    def on_result(self, trial, result: dict) -> str:
        t = result.get(self.time_attr, 0)
        v = result.get(self.metric)
        if v is None or (isinstance(v, float) and math.isnan(v)):
            return CONTINUE
        if t >= self.max_t:
            return STOP
        for rung in reversed(self._rung_levels):
            if t >= rung:
                if (trial.trial_id, rung) in self._recorded:
                    return CONTINUE  # already judged at this rung
                self._recorded.add((trial.trial_id, rung))
                recorded = self.rungs.setdefault(rung, [])
                recorded.append(float(v))
                if len(recorded) < self.rf:
                    return CONTINUE  # not enough data to cut yet
                q = (
                    np.percentile(recorded, 100 / self.rf)
                    if self.mode != "max"  # same predicate as _better
                    else np.percentile(recorded, 100 * (1 - 1 / self.rf))
                )
                return CONTINUE if self._better(float(v), float(q)) else STOP
        return CONTINUE


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose running-average is worse than the median of other
    trials' averages at the same step (reference: median_stopping_rule.py)."""

    def __init__(
        self,
        metric: "str | None" = None,
        mode: "str | None" = None,
        grace_period: int = 1,
        min_samples_required: int = 3,
        time_attr: str = "training_iteration",
    ):
        self.metric, self.mode = metric, mode
        self.grace = grace_period
        self.min_samples = min_samples_required
        self.time_attr = time_attr
        self._avgs: dict[str, list[float]] = {}

    def on_result(self, trial, result: dict) -> str:
        v = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if v is None:
            return CONTINUE
        hist = self._avgs.setdefault(trial.trial_id, [])
        hist.append(float(v))
        if t < self.grace or len(self._avgs) < self.min_samples:
            return CONTINUE
        my_avg = float(np.mean(hist))
        others = [float(np.mean(h)) for tid, h in self._avgs.items() if tid != trial.trial_id]
        if len(others) < self.min_samples - 1:
            return CONTINUE
        med = float(np.median(others))
        ok = my_avg <= med if self.mode != "max" else my_avg >= med
        return CONTINUE if ok else STOP


class PopulationBasedTraining(TrialScheduler):
    """PBT (reference: tune/schedulers/pbt.py): at each perturbation
    interval, bottom-quantile trials clone the checkpoint of a
    top-quantile trial and perturb its hyperparameters. The controller
    performs the actual exploit (checkpoint copy) — the scheduler returns
    the decision via `pending_exploits`."""

    def __init__(
        self,
        metric: "str | None" = None,
        mode: "str | None" = None,
        perturbation_interval: int = 5,
        hyperparam_mutations: Optional[dict] = None,
        quantile_fraction: float = 0.25,
        resample_probability: float = 0.25,
        seed: Optional[int] = None,
        time_attr: str = "training_iteration",
    ):
        self.metric, self.mode = metric, mode
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.time_attr = time_attr
        self.rng = random.Random(seed)
        self._scores: dict[str, float] = {}
        self._last_perturb: dict[str, int] = {}
        # trial_id -> source trial_id to clone from (consumed by controller)
        self.pending_exploits: dict[str, str] = {}

    def on_result(self, trial, result: dict) -> str:
        v = result.get(self.metric)
        t = result.get(self.time_attr, 0)
        if v is None:
            return CONTINUE
        self._scores[trial.trial_id] = float(v)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t - last < self.interval or len(self._scores) < 2:
            return CONTINUE
        self._last_perturb[trial.trial_id] = t
        ids = list(self._scores)
        ranked = sorted(ids, key=self._scores.__getitem__, reverse=(self.mode == "max"))
        k = max(1, int(len(ranked) * self.quantile))
        top, bottom = ranked[:k], ranked[-k:]
        if trial.trial_id in bottom and trial.trial_id not in top:
            self.pending_exploits[trial.trial_id] = self.rng.choice(top)
        return CONTINUE

    def perturb(self, config: dict) -> dict:
        out = dict(config)
        for key, spec in self.mutations.items():
            if self.rng.random() < self.resample_p:
                out[key] = spec() if callable(spec) else self.rng.choice(list(spec))
            else:
                cur = out.get(key)
                if isinstance(cur, (int, float)):
                    factor = self.rng.choice([0.8, 1.2])
                    out[key] = type(cur)(cur * factor)
        return out


class PB2(PopulationBasedTraining):
    """Population Based Bandits (reference: tune/schedulers/pb2.py,
    Parker-Holder et al. 2020): PBT's exploit step, but explore selects
    new hyperparameters with a GP-UCB bandit over the observed
    (config -> reward improvement) surface instead of random
    perturbation — far more sample-efficient at small population sizes.

    The reference delegates the GP to GPy; here it is a plain-numpy RBF
    GP (the population history is tiny — tens of points — so exact
    inference is trivial).
    """

    def __init__(
        self,
        metric: "str | None" = None,
        mode: "str | None" = None,
        perturbation_interval: int = 5,
        hyperparam_bounds: Optional[dict] = None,  # key -> (low, high)
        quantile_fraction: float = 0.25,
        seed: Optional[int] = None,
        time_attr: str = "training_iteration",
        ucb_kappa: float = 2.0,
        n_candidates: int = 256,
    ):
        super().__init__(
            metric=metric, mode=mode,
            perturbation_interval=perturbation_interval,
            hyperparam_mutations={}, quantile_fraction=quantile_fraction,
            seed=seed, time_attr=time_attr,
        )
        if not hyperparam_bounds:
            raise ValueError("PB2 needs hyperparam_bounds {key: (low, high)}")
        self.bounds = dict(hyperparam_bounds)
        self.kappa = ucb_kappa
        self.n_candidates = n_candidates
        self._last_score: dict[str, float] = {}
        # (normalized config vector, reward improvement) observations
        self._obs: list = []

    def on_exploit(self, trial_id: str) -> None:
        """Controller hook after a checkpoint clone: the next result's
        score jump comes from the copied weights, not the new config —
        recording it would poison the GP with a huge spurious reward."""
        self._last_score.pop(trial_id, None)

    def on_result(self, trial, result: dict) -> str:
        v = result.get(self.metric)
        if v is not None:
            tid = trial.trial_id
            prev = self._last_score.get(tid)
            if prev is not None:
                dr = float(v) - prev
                if self.mode == "min":
                    dr = -dr
                self._obs.append((self._vec(trial.config), dr))
                if len(self._obs) > 512:
                    self._obs = self._obs[-512:]
            self._last_score[tid] = float(v)
        return super().on_result(trial, result)

    # -- GP machinery ---------------------------------------------------------

    def _vec(self, config: dict):
        import numpy as np

        out = []
        for k, (lo, hi) in sorted(self.bounds.items()):
            x = float(config.get(k, lo))
            out.append((x - lo) / max(hi - lo, 1e-12))
        return np.asarray(out)

    def _gp_posterior(self, X, y, Xq, length=0.2, noise=1e-3):
        import numpy as np

        def k(a, b):
            d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / length**2)

        K = k(X, X) + noise * np.eye(len(X))
        Kq = k(Xq, X)
        sol = np.linalg.solve(K, y)
        mu = Kq @ sol
        v = np.linalg.solve(K, Kq.T)
        var = np.clip(1.0 - (Kq * v.T).sum(-1), 1e-9, None)
        return mu, np.sqrt(var)

    def perturb(self, config: dict) -> dict:
        """GP-UCB explore inside the bounded box (the controller calls
        this when a bottom-quantile trial exploits a top one)."""
        import numpy as np

        out = dict(config)
        keys = sorted(self.bounds)
        rng = np.random.default_rng(self.rng.randrange(2**32))
        cand = rng.uniform(size=(self.n_candidates, len(keys)))
        if len(self._obs) >= 2:
            X = np.stack([o[0] for o in self._obs])
            y = np.asarray([o[1] for o in self._obs])
            sd = y.std() or 1.0
            mu, sigma = self._gp_posterior(X, (y - y.mean()) / sd, cand)
            best = cand[int(np.argmax(mu + self.kappa * sigma))]
        else:  # cold start: uniform resample
            best = cand[0]
        for i, key in enumerate(keys):
            lo, hi = self.bounds[key]
            val = lo + float(best[i]) * (hi - lo)
            cur = config.get(key)
            out[key] = type(cur)(val) if isinstance(cur, int) else val
        return out
