"""Trainable: the step API driven by the Tune controller (reference:
python/ray/tune/trainable/trainable.py:289 train())."""

from __future__ import annotations

from typing import Any, Optional


class Trainable:
    """Class trainable: subclass with setup/step/save_checkpoint/
    load_checkpoint. The controller calls train() repeatedly; PBT uses
    save/restore/reset_config for exploit steps."""

    def __init__(self, config: Optional[dict] = None):
        self.config = dict(config or {})
        self.iteration = 0
        self.setup(self.config)

    # -- override points ----------------------------------------------------

    def setup(self, config: dict) -> None:
        pass

    def step(self) -> dict:
        raise NotImplementedError

    def save_checkpoint(self) -> Any:
        return None

    def load_checkpoint(self, state: Any) -> None:
        pass

    def reset_config(self, new_config: dict) -> bool:
        """Return True if the trainable can adopt new hyperparameters
        in-place (avoids teardown/setup on PBT explore)."""
        return False

    def cleanup(self) -> None:
        pass

    # -- controller-facing --------------------------------------------------

    def train(self) -> dict:
        metrics = self.step() or {}
        self.iteration += 1
        metrics.setdefault("training_iteration", self.iteration)
        return metrics


def with_parameters(fn, **params):
    """Bind large/system objects to a function trainable without putting
    them in the param space (reference: tune/trainable/util.py)."""
    import functools

    @functools.wraps(fn)
    def wrapped(config):
        return fn(config, **params)

    wrapped.__ray_tpu_base_fn__ = fn
    return wrapped


def with_resources(trainable, resources: dict):
    """Attach per-trial resource requests."""
    trainable.__ray_tpu_resources__ = dict(resources)
    return trainable
