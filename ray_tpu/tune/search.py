"""Search spaces + search algorithms (reference: python/ray/tune/search/).

`grid_search`/`choice`/`uniform`/... build a param_space dict; the
BasicVariantGenerator expands grid axes exhaustively and samples the
distributions `num_samples` times — the reference's default searcher
(tune/search/basic_variant.py). Custom searchers implement Searcher
(suggest/on_trial_complete) and can be rate-limited by
ConcurrencyLimiter.
"""

from __future__ import annotations

import itertools
import random
from typing import Any, Callable, Optional

import numpy as np


class Domain:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class GridSearch:
    """Marker: expand every value as its own trial (cross-product with
    other grid axes)."""

    def __init__(self, values):
        self.values = list(values)


class Choice(Domain):
    def __init__(self, values):
        self.values = list(values)

    def sample(self, rng):
        return rng.choice(self.values)


class Uniform(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.uniform(self.low, self.high)


class QUniform(Domain):
    def __init__(self, low, high, q):
        self.low, self.high, self.q = low, high, q

    def sample(self, rng):
        v = rng.uniform(self.low, self.high)
        return round(v / self.q) * self.q


class LogUniform(Domain):
    def __init__(self, low, high, base=10):
        if low <= 0:
            raise ValueError("loguniform requires low > 0")
        self.low, self.high, self.base = low, high, base

    def sample(self, rng):
        import math

        lo, hi = math.log(self.low, self.base), math.log(self.high, self.base)
        return self.base ** rng.uniform(lo, hi)


class RandInt(Domain):
    def __init__(self, low, high):
        self.low, self.high = low, high

    def sample(self, rng):
        return rng.randrange(self.low, self.high)


class Randn(Domain):
    def __init__(self, mean=0.0, sd=1.0):
        self.mean, self.sd = mean, sd

    def sample(self, rng):
        return rng.gauss(self.mean, self.sd)


class SampleFrom(Domain):
    def __init__(self, fn: Callable[[dict], Any]):
        self.fn = fn

    def sample(self, rng):  # resolved against the spec later
        raise NotImplementedError


# public constructors (tune.grid_search etc.)
def grid_search(values) -> GridSearch:
    return GridSearch(values)


def choice(values) -> Choice:
    return Choice(values)


def uniform(low, high) -> Uniform:
    return Uniform(low, high)


def quniform(low, high, q) -> QUniform:
    return QUniform(low, high, q)


def loguniform(low, high, base=10) -> LogUniform:
    return LogUniform(low, high, base)


def randint(low, high) -> RandInt:
    return RandInt(low, high)


def randn(mean=0.0, sd=1.0) -> Randn:
    return Randn(mean, sd)


def sample_from(fn) -> SampleFrom:
    return SampleFrom(fn)


def _resolve(space: dict, rng: random.Random, grid_assignment: dict) -> dict:
    """One concrete config from a param space + fixed grid choices."""
    out = {}
    deferred = []
    for k, v in space.items():
        if isinstance(v, GridSearch):
            out[k] = grid_assignment[k]
        elif isinstance(v, SampleFrom):
            deferred.append((k, v))
        elif isinstance(v, Domain):
            out[k] = v.sample(rng)
        elif isinstance(v, dict):
            out[k] = _resolve(v, rng, grid_assignment.get(k, {}))
        else:
            out[k] = v
    for k, v in deferred:
        out[k] = v.fn(out)
    return out


def _grid_axes(space: dict, prefix=()) -> list[tuple[tuple, list]]:
    axes = []
    for k, v in space.items():
        if isinstance(v, GridSearch):
            axes.append(((*prefix, k), v.values))
        elif isinstance(v, dict):
            axes.extend(_grid_axes(v, (*prefix, k)))
    return axes


def _nest(flat: dict[tuple, Any]) -> dict:
    out: dict = {}
    for path, v in flat.items():
        d = out
        for p in path[:-1]:
            d = d.setdefault(p, {})
        d[path[-1]] = v
    return out


class Searcher:
    """ABC for pluggable search algorithms (reference: search/searcher.py)."""

    def suggest(self, trial_id: str) -> Optional[dict]:
        raise NotImplementedError

    def on_trial_complete(self, trial_id: str, result: Optional[dict]) -> None:
        pass


class BasicVariantGenerator(Searcher):
    """Grid cross-product × num_samples random draws."""

    def __init__(self, param_space: dict, num_samples: int = 1, seed: Optional[int] = None):
        self.space = param_space
        self.rng = random.Random(seed)
        axes = _grid_axes(param_space)
        if axes:
            keys = [a[0] for a in axes]
            combos = list(itertools.product(*[a[1] for a in axes]))
        else:
            keys, combos = [], [()]
        self._pending = [
            dict(zip(keys, combo)) for _ in range(num_samples) for combo in combos
        ]
        self.total = len(self._pending)

    def suggest(self, trial_id: str) -> Optional[dict]:
        if not self._pending:
            return None
        flat = self._pending.pop(0)
        return _resolve(self.space, self.rng, _nest(flat))


class ConcurrencyLimiter(Searcher):
    def __init__(self, searcher: Searcher, max_concurrent: int):
        self.searcher = searcher
        self.max_concurrent = max_concurrent
        self._live: set[str] = set()

    def suggest(self, trial_id):
        if len(self._live) >= self.max_concurrent:
            return "__pending__"
        cfg = self.searcher.suggest(trial_id)
        if cfg is not None and cfg != "__pending__":
            self._live.add(trial_id)
        return cfg

    def on_trial_complete(self, trial_id, result):
        self._live.discard(trial_id)
        self.searcher.on_trial_complete(trial_id, result)


class Repeater(Searcher):
    """Run every suggested config `repeat` times and report the MEAN
    metric to the wrapped searcher (reference: tune/search/repeater.py —
    variance reduction for noisy objectives; external searchers must see
    one aggregated result per config, not per seed)."""

    def __init__(self, searcher: Searcher, repeat: int,
                 metric: Optional[str] = None):
        if repeat < 1:
            raise ValueError("repeat must be >= 1")
        self.searcher = searcher
        self.repeat = repeat
        self.metric = metric
        self._groups: dict[str, dict] = {}   # group id -> state
        self._trial_group: dict[str, str] = {}
        self._queue: list[tuple[str, dict]] = []  # (group, config) replicas

    def suggest(self, trial_id: str) -> Optional[dict]:
        if self._queue:
            group, cfg = self._queue.pop(0)
            self._trial_group[trial_id] = group
            return dict(cfg)
        cfg = self.searcher.suggest(trial_id)
        if cfg is None or cfg == "__pending__":
            return cfg
        group = trial_id
        self._groups[group] = {"config": cfg, "results": [], "want": self.repeat}
        self._trial_group[trial_id] = group
        for _ in range(self.repeat - 1):
            self._queue.append((group, cfg))
        return dict(cfg)

    def on_trial_complete(self, trial_id: str, result: Optional[dict]) -> None:
        group = self._trial_group.pop(trial_id, None)
        if group is None or group not in self._groups:
            return
        st = self._groups[group]
        st["results"].append(result)
        if len(st["results"]) < st["want"]:
            return
        del self._groups[group]
        valid = [r for r in st["results"] if r]
        if not valid:
            self.searcher.on_trial_complete(group, None)
            return
        keys = self.metric and [self.metric] or [
            k for k in valid[0]
            if isinstance(valid[0][k], (int, float)) and not isinstance(valid[0][k], bool)
        ]
        agg = dict(valid[-1])
        for k in keys:
            vals = [r[k] for r in valid if isinstance(r.get(k), (int, float))]
            if vals:
                agg[k] = sum(vals) / len(vals)
        agg["num_repeats"] = len(valid)
        self.searcher.on_trial_complete(group, agg)


class AskTellSearcher(Searcher):
    """Adapter for external ask/tell optimizers (optuna, nevergrad,
    scikit-optimize all speak it). Reference analog: the per-library
    Searcher integrations under tune/search/{optuna,hyperopt,...} — one
    seam instead of N wrappers:

        ext = SomeLibStudy(...)
        Tuner(..., search_alg=AskTellSearcher(
            ask=ext.ask_dict, tell=ext.tell, metric="loss"))

    `ask()` returns the next config dict (or None when exhausted);
    `tell(config, value)` reports the RAW final metric for that config —
    optimization direction is the external optimizer's own configuration
    (e.g. optuna's study direction), never transformed here.
    """

    def __init__(self, ask: Callable[[], Optional[dict]],
                 tell: Callable[[dict, Optional[float]], None],
                 metric: str):
        self.ask = ask
        self.tell = tell
        self.metric = metric
        self._live: dict[str, dict] = {}

    def suggest(self, trial_id: str) -> Optional[dict]:
        cfg = self.ask()
        if cfg is None:
            return None
        self._live[trial_id] = dict(cfg)
        return dict(cfg)

    def on_trial_complete(self, trial_id: str, result: Optional[dict]) -> None:
        cfg = self._live.pop(trial_id, None)
        if cfg is None:
            return
        value = None if not result else result.get(self.metric)
        self.tell(cfg, None if value is None else float(value))
