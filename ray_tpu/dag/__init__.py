"""ray_tpu.dag: compiled static graphs of actor method calls.

Reference analog: python/ray/dag/ (CompiledDAG, compiled_dag_node.py:795)
+ python/ray/experimental/channel/. A DAG of actor-method calls is
compiled once into per-actor execution loops wired with reusable
channels, bypassing per-call task submission — the reference's
µs-latency substrate for vLLM pipeline parallelism. TPU-first delta:
device tensors should move via jitted collectives inside SPMD programs
(parallel/ + collective/), so these channels carry HOST objects
(control data, activations staged host-side, DCN hops); in one process
they are queue-backed, mirroring the reference's mutable-plasma
single-slot semantics.
"""

from ray_tpu.dag.channels import Channel, ChannelClosedError
from ray_tpu.dag.compiled import CompiledDAG, CompiledDAGRef
from ray_tpu.dag.nodes import (
    ClassMethodNode,
    CollectiveOutputNode,
    DAGNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)

__all__ = [
    "Channel",
    "ChannelClosedError",
    "ClassMethodNode",
    "CollectiveOutputNode",
    "CompiledDAG",
    "CompiledDAGRef",
    "DAGNode",
    "InputAttributeNode",
    "InputNode",
    "MultiOutputNode",
]
