"""TCP channel: the cross-NODE substrate for compiled DAGs.

Reference analog: the cross-actor channels compiled graphs use when
actors span nodes (python/ray/experimental/channel/
shared_memory_channel.py:151 routing through the object store;
torch_tensor_nccl_channel.py:44 for NCCL transports). Here a channel
whose writer and readers sit on different hosts is a direct
writer->reader TCP stream over DCN — pipelined length-prefixed frames
on persistent connections, no task submission, no object store, no
per-hop RPC:

  * rendezvous: the WRITER binds an ephemeral port on first write and
    publishes "host:port" under the channel name in the GCS KV
    (ns "dagchan"); readers long-poll the key and connect once;
  * frames: (seq, pickled payload), pushed in order per reader; each
    reader acks seq on the same socket right after receipt;
  * backpressure: before writing seq N the writer waits until every
    reader acked N - maxsize — at most `maxsize` values buffered,
    identical semantics to the shm channel;
  * close: in-stream CLOSE sentinel for connected readers plus a GCS
    close marker for processes that never connected AND for closes
    issued from non-writer processes (teardown, poison propagation);
    the writer's accept thread polls the marker.

The object is picklable (name + metadata travel in the compiled plan;
sockets/threads are rebuilt lazily in whichever process touches it).
"""

from __future__ import annotations

import pickle
import queue as _queue
import socket
import struct
import threading
import time
from typing import Any, Optional

from ray_tpu.dag.channels import ChannelClosedError
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.dag.socket_channel")

_NS = "dagchan"
_CLOSE_SEQ = -1
_HDR = struct.Struct("<qI")  # seq, payload length
_ACK = struct.Struct("<q")


def _recv_exact(sock: socket.socket, n: int, deadline: Optional[float],
                closed_check=None) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        if deadline is not None and time.monotonic() >= deadline:
            raise _queue.Empty()
        sock.settimeout(0.2 if closed_check or deadline is not None else None)
        try:
            chunk = sock.recv(n - len(buf))
        except socket.timeout:
            if closed_check is not None and closed_check():
                raise ChannelClosedError("channel closed")
            continue
        if not chunk:
            raise ChannelClosedError("channel writer hung up")
        buf.extend(chunk)
    return bytes(buf)


class _WriterServer:
    """Accept loop + per-reader sender threads, owned by the writer."""

    def __init__(self, chan: "SocketChannel"):
        self.chan = chan
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self.buffer: dict[int, bytes] = {}  # seq -> payload (bounded)
        self.next_seq = 0
        self.acked = [-1] * chan.num_readers
        self.closed = False
        self.sock = socket.create_server(("0.0.0.0", 0))
        self.port = self.sock.getsockname()[1]
        self._threads: list[threading.Thread] = []
        t = threading.Thread(target=self._accept_loop, daemon=True,
                             name=f"dagchan-accept-{chan.name[:8]}")
        t.start()
        self._threads.append(t)
        t = threading.Thread(target=self._close_poll_loop, daemon=True,
                             name=f"dagchan-poll-{chan.name[:8]}")
        t.start()
        self._threads.append(t)

    def _accept_loop(self):
        # bounded accept: each park re-checks the closed flag so a closed
        # channel reaps this thread instead of leaving it parked forever
        self.sock.settimeout(1.0)
        while True:
            try:
                conn, _ = self.sock.accept()
            except socket.timeout:
                with self.lock:
                    if self.closed:
                        return
                continue
            except OSError:
                return  # listener closed
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_reader, args=(conn,),
                                 daemon=True,
                                 name=f"dagchan-reader-{self.chan.name[:8]}")
            t.start()
            self._threads.append(t)

    def _close_poll_loop(self):
        """A non-writer process can only close via the GCS marker; surface
        it here so blocked writers/readers unblock. kv_wait long-polls
        server-side (~0.2 RPC/s per channel), not a tight get loop."""
        while True:
            with self.lock:
                if self.closed:
                    return
            try:
                self.chan._client().kv_wait(
                    self.chan._kv_close_key(), ns=_NS, timeout=5.0
                )
                self.mark_closed()
                return
            except TimeoutError:
                continue
            except Exception:  # noqa: BLE001 — GCS gone: nothing to learn
                return

    def _serve_reader(self, conn: socket.socket):
        try:
            raw = _recv_exact(conn, _ACK.size, None)
            (reader_idx,) = _ACK.unpack(raw)

            def ack_loop():
                while True:
                    try:
                        raw = _recv_exact(conn, _ACK.size, None)
                    except (ChannelClosedError, OSError):
                        return
                    (seq,) = _ACK.unpack(raw)
                    with self.cond:
                        self.acked[reader_idx] = max(
                            self.acked[reader_idx], seq
                        )
                        self.cond.notify_all()

            at = threading.Thread(target=ack_loop, daemon=True)
            at.start()
            with self.cond:
                # resume point races ack_loop's writes — read under cond
                sent = self.acked[reader_idx]
            while True:
                with self.cond:
                    while (sent + 1) not in self.buffer and not self.closed:
                        self.cond.wait(0.2)
                    if (sent + 1) in self.buffer:
                        seq = sent + 1
                        payload = self.buffer[seq]
                    elif self.closed:
                        seq, payload = _CLOSE_SEQ, b""
                conn.sendall(_HDR.pack(seq, len(payload)) + payload)
                if seq == _CLOSE_SEQ:
                    return
                sent = seq
        except (OSError, ChannelClosedError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def write(self, payload: bytes, timeout: Optional[float]):
        deadline = None if timeout is None else time.monotonic() + timeout
        with self.cond:
            seq = self.next_seq
            old = seq - self.chan.maxsize
            while any(a < old for a in self.acked) and not self.closed:
                if deadline is not None and time.monotonic() >= deadline:
                    raise TimeoutError(
                        f"channel {self.chan.name} write backpressure: "
                        f"readers acked {self.acked}, need {old}"
                    )
                self.cond.wait(0.2)
            if self.closed:
                raise ChannelClosedError("channel closed")
            if old in self.buffer:
                del self.buffer[old]
            self.buffer[seq] = payload
            self.next_seq = seq + 1
            self.cond.notify_all()

    def mark_closed(self):
        with self.cond:
            self.closed = True
            self.cond.notify_all()

    def shutdown(self):
        self.mark_closed()
        try:
            self.sock.close()
        except OSError:
            pass


class SocketChannel:
    """Single-writer, N-reader, bounded, named, cross-HOST."""

    def __init__(self, num_readers: int = 1, maxsize: int = 2,
                 name: Optional[str] = None):
        import uuid

        if num_readers < 1:
            raise ValueError("channel needs at least one reader")
        self.name = name or uuid.uuid4().hex
        self.num_readers = num_readers
        self.maxsize = max(1, maxsize)
        self._server: Optional[_WriterServer] = None
        # one process can hold SEVERAL reader indices of the same channel
        # (e.g. the driver reads a node both as a collective input and as
        # a DAG output) — each gets its own connection + stream buffer
        self._rsocks: dict[int, socket.socket] = {}
        self._rbufs: dict[int, bytearray] = {}

    def __reduce__(self):
        return (_rebuild, (self.name, self.num_readers, self.maxsize))

    # -- GCS rendezvous -------------------------------------------------------

    @staticmethod
    def _client():
        from ray_tpu.cluster.client import _ambient_client

        return _ambient_client()

    def _kv_key(self) -> bytes:
        return f"addr/{self.name}".encode()

    def _kv_close_key(self) -> bytes:
        return f"closed/{self.name}".encode()

    def _kv_closed(self) -> bool:
        return self._client().kv_get(self._kv_close_key(), ns=_NS) is not None

    # -- writer side ----------------------------------------------------------

    def _ensure_server(self) -> _WriterServer:
        if self._server is None:
            self._server = _WriterServer(self)
            client = self._client()
            # advertise the address this process's daemon registered with —
            # loopback on a single-host cluster, the routable NIC otherwise
            host = client.local_daemon_addr[0]
            client.kv_put(
                self._kv_key(), f"{host}:{self._server.port}".encode(), ns=_NS
            )
        return self._server

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        from ray_tpu.dag.channels import chaos_channel_op

        if chaos_channel_op("send", transport="socket"):
            return  # DROP_CHANNEL: lost in flight (never framed)
        self._ensure_server().write(
            pickle.dumps(value, protocol=5), timeout
        )

    # -- reader side ----------------------------------------------------------

    def _connect(self, reader_idx: int, timeout: Optional[float]):
        if reader_idx in self._rsocks:
            return
        client = self._client()
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            addr = client.kv_get(self._kv_key(), ns=_NS)
            if addr is not None:
                break
            if self._kv_closed():
                raise ChannelClosedError("channel closed before first write")
            if deadline is not None and time.monotonic() >= deadline:
                raise _queue.Empty()
            try:
                addr = client.kv_wait(self._kv_key(), ns=_NS, timeout=2.0)
                break
            except TimeoutError:
                continue
        host, port = bytes(addr).decode().rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=30)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.sendall(_ACK.pack(reader_idx))
        self._rsocks[reader_idx] = sock
        self._rbufs[reader_idx] = bytearray()

    def _fill_to(self, reader_idx: int, n: int,
                 deadline: Optional[float]) -> None:
        """Grow the per-reader buffer to >= n bytes WITHOUT consuming —
        a timeout mid-frame must leave the stream intact so the next
        read() resumes at the same frame boundary."""
        sock = self._rsocks[reader_idx]
        buf = self._rbufs[reader_idx]
        while len(buf) < n:
            if deadline is not None and time.monotonic() >= deadline:
                raise _queue.Empty()
            sock.settimeout(0.2 if deadline is not None else None)
            try:
                chunk = sock.recv(65536)
            except socket.timeout:
                continue
            if not chunk:
                raise ChannelClosedError("channel writer hung up")
            buf.extend(chunk)

    def read(self, reader_idx: int = 0, timeout: Optional[float] = None) -> Any:
        from ray_tpu.dag.channels import chaos_channel_op

        chaos_channel_op("recv", transport="socket")
        deadline = None if timeout is None else time.monotonic() + timeout
        self._connect(reader_idx, timeout)
        buf = self._rbufs[reader_idx]
        self._fill_to(reader_idx, _HDR.size, deadline)
        seq, ln = _HDR.unpack(bytes(buf[:_HDR.size]))
        if seq == _CLOSE_SEQ:
            raise ChannelClosedError("channel closed")
        self._fill_to(reader_idx, _HDR.size + ln, deadline)
        payload = bytes(buf[_HDR.size:_HDR.size + ln])
        del buf[:_HDR.size + ln]  # consume header+payload atomically
        self._rsocks[reader_idx].sendall(_ACK.pack(seq))
        return pickle.loads(payload)

    # -- lifecycle ------------------------------------------------------------

    def close(self) -> None:
        try:
            self._client().kv_put(self._kv_close_key(), b"1", ns=_NS)
        except Exception:  # noqa: BLE001 — GCS gone at teardown
            pass
        if self._server is not None:
            # full shutdown, not just the flag: the writer lives in an
            # ACTOR process where unlink() is never called — leaving the
            # listener open would leak an fd + accept thread per channel
            # per compile/teardown cycle. Sender threads still drain the
            # buffered frames and the CLOSE sentinel before exiting.
            self._server.shutdown()

    def unlink(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server = None
        for sock in self._rsocks.values():
            try:
                sock.close()
            except OSError:
                pass
        self._rsocks.clear()
        self._rbufs.clear()
        try:
            c = self._client()
            c.kv_del(self._kv_key(), ns=_NS)
            c.kv_del(self._kv_close_key(), ns=_NS)
        except Exception:  # noqa: BLE001
            pass


def _rebuild(name, num_readers, maxsize):
    return SocketChannel(num_readers=num_readers, maxsize=maxsize, name=name)
