"""Channels: reusable single-slot pipes between compiled-DAG actors.

Reference analog: python/ray/experimental/channel/shared_memory_channel.py
(Channel over mutable plasma objects — single writer, registered readers,
slot reused every iteration) and intra_process_channel.py. The C++
substrate there is MutableObjectManager spin-wait buffers
(src/ray/core_worker/experimental_mutable_object_manager.h:49); in one
host process a bounded queue per reader gives the same semantics
(backpressure at capacity, ordered delivery, N-reader fan-out) without
shared-memory ceremony.

Robustness (r13): reads are BOUNDED by default — ``read(timeout=None)``
parks at most ``default_timeout`` seconds and raises the typed
``ChannelTimeoutError`` instead of hanging an exec loop forever on a
peer that died outside the channel protocol. The channel plane is also
a chaos surface: ``DROP_CHANNEL`` (a written value lost in flight — the
reader's bounded wait surfaces it) and ``STALL_CHANNEL`` (a late
writer/reader, ``delay_s``) fire at the ``dag.channel`` hook sites,
mirroring the collective fault kinds' eligibility rules (drops are only
eligible at the send side — there is nothing in flight to lose at a
recv).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Optional

from ray_tpu.chaos import harness as _chaos


class ChannelClosedError(Exception):
    pass


class ChannelTimeoutError(TimeoutError):
    """A bounded channel read expired with no value: the writer is dead,
    stalled past the bound, or its value was lost in flight
    (DROP_CHANNEL). Typed so exec loops poison the pipeline instead of
    hanging, and callers can tell a dead peer from a closed channel."""


# default bound on read(timeout=None): long enough for any legitimate
# upstream compute, finite so a dead writer can never park a loop forever
DEFAULT_READ_TIMEOUT = 120.0


def chaos_channel_op(role: str, **attrs) -> bool:
    """Shared chaos hook for every channel flavor (in-process queue, shm
    ring, socket stream): returns True when the op's value should be
    DROPPED (send side only); STALL_CHANNEL sleeps ``delay_s`` inline.
    Fast path: one attribute load when chaos is disabled."""
    if _chaos.ACTIVE is None:
        return False
    kinds = (
        (_chaos.DROP_CHANNEL, _chaos.STALL_CHANNEL)
        if role == "send" else (_chaos.STALL_CHANNEL,)
    )
    drop = False
    for f in _chaos.fire(f"dag.channel.{role}", kinds=kinds, **attrs):
        if f.kind == _chaos.STALL_CHANNEL:
            time.sleep(f.delay_s)
        elif f.kind == _chaos.DROP_CHANNEL:
            drop = True
    return drop


_CLOSED = object()


class Channel:
    """Single-writer, N-reader channel. Each reader gets every value
    (fan-out duplicates the reference's reader-registration model)."""

    def __init__(self, num_readers: int = 1, maxsize: int = 2,
                 default_timeout: float = DEFAULT_READ_TIMEOUT):
        if num_readers < 1:
            raise ValueError("channel needs at least one reader")
        self._queues = [queue.Queue(maxsize=maxsize) for _ in range(num_readers)]
        self._closed = threading.Event()
        self._default_timeout = float(default_timeout)

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        if self._closed.is_set():
            raise ChannelClosedError("channel closed")
        if chaos_channel_op("send"):
            return  # lost in flight: readers' bounded waits surface it
        for q in self._queues:
            q.put(value, timeout=timeout)

    def read(self, reader_idx: int = 0, timeout: Optional[float] = None) -> Any:
        """Read the next value. ``timeout=None`` means the channel's
        default BOUND (not forever): expiry raises the typed
        ``ChannelTimeoutError``. An explicit timeout keeps the legacy
        ``queue.Empty`` contract for pollers."""
        chaos_channel_op("recv")
        bounded_default = timeout is None
        eff = self._default_timeout if bounded_default else timeout
        try:
            v = self._queues[reader_idx].get(timeout=eff)
        except queue.Empty:
            if self._closed.is_set():
                raise ChannelClosedError("channel closed") from None
            if bounded_default:
                raise ChannelTimeoutError(
                    f"channel read parked > {eff}s with no value (writer "
                    "dead, stalled, or value dropped in flight)"
                ) from None
            raise
        if v is _CLOSED:
            raise ChannelClosedError("channel closed")
        return v

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        for q in self._queues:
            try:
                q.put_nowait(_CLOSED)
            except queue.Full:
                # drain one slot so the sentinel always fits
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                try:
                    q.put_nowait(_CLOSED)
                except queue.Full:
                    pass
