"""Channels: reusable single-slot pipes between compiled-DAG actors.

Reference analog: python/ray/experimental/channel/shared_memory_channel.py
(Channel over mutable plasma objects — single writer, registered readers,
slot reused every iteration) and intra_process_channel.py. The C++
substrate there is MutableObjectManager spin-wait buffers
(src/ray/core_worker/experimental_mutable_object_manager.h:49); in one
host process a bounded queue per reader gives the same semantics
(backpressure at capacity, ordered delivery, N-reader fan-out) without
shared-memory ceremony.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Optional


class ChannelClosedError(Exception):
    pass


_CLOSED = object()


class Channel:
    """Single-writer, N-reader channel. Each reader gets every value
    (fan-out duplicates the reference's reader-registration model)."""

    def __init__(self, num_readers: int = 1, maxsize: int = 2):
        if num_readers < 1:
            raise ValueError("channel needs at least one reader")
        self._queues = [queue.Queue(maxsize=maxsize) for _ in range(num_readers)]
        self._closed = threading.Event()

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        if self._closed.is_set():
            raise ChannelClosedError("channel closed")
        for q in self._queues:
            q.put(value, timeout=timeout)

    def read(self, reader_idx: int = 0, timeout: Optional[float] = None) -> Any:
        try:
            v = self._queues[reader_idx].get(timeout=timeout)
        except queue.Empty:
            if self._closed.is_set():
                raise ChannelClosedError("channel closed") from None
            raise
        if v is _CLOSED:
            raise ChannelClosedError("channel closed")
        return v

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        for q in self._queues:
            try:
                q.put_nowait(_CLOSED)
            except queue.Full:
                # drain one slot so the sentinel always fits
                try:
                    q.get_nowait()
                except queue.Empty:
                    pass
                try:
                    q.put_nowait(_CLOSED)
                except queue.Full:
                    pass
