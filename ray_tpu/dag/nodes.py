"""DAG node types + .bind() graph construction.

Reference analog: python/ray/dag/dag_node.py, class_node.py,
input_node.py, collective_node.py. `actor.method.bind(args)` records a
ClassMethodNode; InputNode is the per-execute input; MultiOutputNode
fans multiple leaves into one result tuple; CollectiveOutputNode binds
an allreduce across N actors' intermediate values (reference:
collective_node.py:18 _CollectiveOperation).
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Optional

_node_counter = itertools.count()


class DAGNode:
    def __init__(self, upstream: list["DAGNode"]):
        self.id = next(_node_counter)
        self.upstream = upstream
        self.downstream: list[DAGNode] = []
        for u in upstream:
            u.downstream.append(self)

    def experimental_compile(self, **kwargs):
        from ray_tpu.dag.compiled import CompiledDAG

        return CompiledDAG(self, **kwargs)

    def walk(self, seen: Optional[set] = None) -> list["DAGNode"]:
        """All ancestors + self, topologically ordered (ids are creation-
        ordered, and bind() can only reference existing nodes)."""
        seen = set()
        order: list[DAGNode] = []

        def visit(n: DAGNode):
            if n.id in seen:
                return
            seen.add(n.id)
            for u in n.upstream:
                visit(u)
            order.append(n)

        visit(self)
        return order


class InputNode(DAGNode):
    """Placeholder for the value passed to compiled_dag.execute().
    Context-manager form mirrors the reference (`with InputNode() as inp`)."""

    def __init__(self):
        super().__init__([])

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def __getattr__(self, key: str):
        if key.startswith("_") or key in ("id", "upstream", "downstream"):
            raise AttributeError(key)
        return InputAttributeNode(self, key)

    def __getitem__(self, key) -> "InputAttributeNode":
        return InputAttributeNode(self, key)


class InputAttributeNode(DAGNode):
    """inp.x / inp[k]: extracts a field of the execute() input."""

    def __init__(self, parent: InputNode, key: Any):
        super().__init__([parent])
        self.key = key

    def extract(self, value: Any) -> Any:
        if isinstance(self.key, str) and hasattr(value, self.key) and not isinstance(value, dict):
            return getattr(value, self.key)
        return value[self.key]


class ClassMethodNode(DAGNode):
    """One actor method call per execution (reference: class_node.py)."""

    def __init__(self, actor_handle, method_name: str, args: tuple, kwargs: dict):
        deps = [a for a in args if isinstance(a, DAGNode)]
        deps += [v for v in kwargs.values() if isinstance(v, DAGNode)]
        super().__init__(deps)
        self.actor_handle = actor_handle
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs


class FunctionNode(DAGNode):
    """One remote-function invocation (reference: function_node.py).
    Used by workflows; compiled graphs use ClassMethodNode."""

    def __init__(self, remote_fn, args: tuple, kwargs: dict):
        deps = [a for a in args if isinstance(a, DAGNode)]
        deps += [v for v in kwargs.values() if isinstance(v, DAGNode)]
        super().__init__(deps)
        self.remote_fn = remote_fn
        self.args = args
        self.kwargs = kwargs
        # workflow-specific options (set via .options on the task)
        self.task_name = getattr(remote_fn, "__name__", "task")


class MultiOutputNode(DAGNode):
    """Tuple of leaves -> one result list (reference: output_node.py)."""

    def __init__(self, outputs: list[DAGNode]):
        super().__init__(list(outputs))
        self.outputs = list(outputs)


class CollectiveOutputNode(DAGNode):
    """Elementwise reduction across N actors' values. The reference
    (collective_node.py) lowers this to NCCL allreduce between GPU
    actors; host-side here (DCN-style control reductions). Device-tensor
    allreduce belongs inside an SPMD jitted program (ray_tpu.collective)."""

    def __init__(self, inputs: list[DAGNode], op: Callable[[Any, Any], Any]):
        super().__init__(list(inputs))
        self.inputs = list(inputs)
        self.op = op


def allreduce_bind(inputs: list[DAGNode], op: Callable[[Any, Any], Any] = None):
    """reference: ray.experimental.collective.allreduce.bind(...)"""
    import operator

    node = CollectiveOutputNode(inputs, op or operator.add)
    # each contributing actor observes the reduced value: downstream methods
    # bound to this node receive the same reduction
    return [node] * len(inputs)


def bind_actor_method(actor_handle, method_name: str):
    """Install-time helper: returns a .bind()-capable callable."""

    def bind(*args, **kwargs) -> ClassMethodNode:
        return ClassMethodNode(actor_handle, method_name, args, kwargs)

    return bind
