"""CompiledDAG: lower a bound DAG to per-actor exec loops + channels.

Reference analog: python/ray/dag/compiled_dag_node.py (CompiledDAG:795,
execute:2535, _execute_until:2464) and dag_node_operation.py (per-actor
op schedules). Compile-time work: group method nodes by actor, allocate
one channel per cross-loop edge, precompute every op's argument sources
(const / input / input-field / local cache / channel read). Runtime
work per execute(): ONE input-channel write, loops stream values
through channels — no task submission, no object store, no
serialization (host objects move by reference between loop threads).

Execution runs inside each actor's own executor thread (framework
method __ray_tpu_dag_exec_loop__) so user state stays thread-confined,
exactly like the reference's per-actor exec loop tasks.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

from ray_tpu.dag.channels import (
    Channel,
    ChannelClosedError,
    ChannelTimeoutError,
)
from ray_tpu.dag.nodes import (
    ClassMethodNode,
    CollectiveOutputNode,
    DAGNode,
    InputAttributeNode,
    InputNode,
    MultiOutputNode,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.dag")

_INPUT = "__input__"


class _Op:
    """One step of a loop's per-iteration schedule."""

    def __init__(self, node_id: int, method_name: Optional[str], arg_sources,
                 kwarg_sources, out_channel: Optional[Channel]):
        self.node_id = node_id
        self.method_name = method_name  # None for pure routing ops
        self.arg_sources = arg_sources
        self.kwarg_sources = kwarg_sources
        self.out_channel = out_channel


# bound on mid-iteration channel reads (op args fed by peer loops): the
# upstream loop is already executing this iteration, so a read parked
# past this is a dead peer or a value dropped in flight — surface the
# typed error instead of a hung exec loop
EXEC_READ_TIMEOUT_S = float(os.environ.get("RAY_TPU_DAG_READ_TIMEOUT_S", "120"))


def _bounded_chan_read(ch, reader_idx: int):
    """Exec-loop channel read with the DAG-wide bound, normalized to the
    typed ChannelTimeoutError across channel flavors (the queue-backed
    in-process channel and the shm/socket channels all raise queue.Empty
    on an explicit-timeout expiry)."""
    import queue as _q

    try:
        return ch.read(reader_idx, timeout=EXEC_READ_TIMEOUT_S)
    except _q.Empty:
        raise ChannelTimeoutError(
            f"exec-loop channel read parked > {EXEC_READ_TIMEOUT_S}s "
            "(peer loop dead, stalled, or value dropped in flight)"
        ) from None


def _resolve_source(src, input_value, local: dict, started=None):
    """``started`` is a one-element cell shared across an iteration's
    reads: while False, a channel read is the loop WAITING for its next
    iteration to begin — an idle DAG is legal for any length of time, so
    timeouts there retry (close still exits via ChannelClosedError).
    Once any value has been consumed the iteration is in flight and a
    parked read past the bound is a dead/stalled peer — fatal, typed."""
    kind = src[0]
    if kind == "const":
        return src[1]
    if kind == "input":
        return input_value
    if kind == "input_attr":
        return src[1].extract(input_value)
    if kind == "local":
        return local[src[1]]
    if kind == "chan":
        while True:
            try:
                v = _bounded_chan_read(src[1], src[2])
                break
            except ChannelTimeoutError:
                if started is None or started[0]:
                    raise
        if started is not None:
            started[0] = True
        return v
    raise AssertionError(src)


def _run_loop_iteration(instance, plan, input_value, local: dict,
                        have_input: bool = True):
    started = [have_input]
    for op in plan:
        args = [
            _resolve_source(s, input_value, local, started)
            for s in op.arg_sources
        ]
        kwargs = {
            k: _resolve_source(s, input_value, local, started)
            for k, s in op.kwarg_sources.items()
        }
        out = getattr(instance, op.method_name)(*args, **kwargs)
        started[0] = True
        local[op.node_id] = out
        if op.out_channel is not None:
            op.out_channel.write(out)


_POISON = object()


def _actor_exec_loop(instance, plan, input_source):
    """Runs on the actor's executor thread until channels close.
    input_source: None | ("chan", channel, reader_idx).

    Input reads OVERLAP compute (reference: compiled-graph operation
    scheduling interleaves channel reads with execution,
    dag_node_operation.py): a prefetch thread keeps up to 2 upcoming
    input values decoded while iteration N runs, so the channel wait +
    unpickle of iteration N+1 hides behind N's method calls. Mid-plan
    channel reads (op args fed by peer actors) still happen inline —
    they carry data dependencies the schedule must respect anyway.
    """
    import queue as _q

    prefetch = None
    dead = [False]  # set by the main loop so the prefetch thread exits
    if input_source is not None:
        prefetch = _q.Queue(maxsize=2)

        def _put(item) -> bool:
            while True:
                try:
                    prefetch.put(item, timeout=0.2)
                    return True
                except _q.Full:
                    if dead[0]:
                        return False  # consumer gone: drop and exit

        def _read_ahead():
            while not dead[0]:
                try:
                    # bounded slices, not one unbounded park: an idle DAG
                    # (driver not calling execute()) is legal forever, but
                    # each park re-checks the dead flag and channel close
                    v = input_source[1].read(input_source[2], timeout=1.0)
                except _q.Empty:
                    continue  # idle: no execute() in flight
                except ChannelClosedError:
                    _put(_POISON)
                    return
                except Exception as e:  # noqa: BLE001 — surface in main loop
                    _put(("__err__", e))
                    return
                if not _put((None, v)):
                    return

        threading.Thread(
            target=_read_ahead, name="dag-input-prefetch", daemon=True
        ).start()

    try:
        while True:
            try:
                if prefetch is not None:
                    while True:
                        try:
                            # bounded park (check_timeouts contract): the
                            # prefetch thread owns the unbounded wait in
                            # 1s close-aware slices; this side just polls
                            item = prefetch.get(timeout=0.5)
                            break
                        except _q.Empty:
                            continue
                    if item is _POISON:
                        raise ChannelClosedError("input channel closed")
                    tag, input_value = item
                    if tag == "__err__":
                        raise input_value
                else:
                    input_value = None
                _run_loop_iteration(
                    instance, plan, input_value, {},
                    have_input=prefetch is not None,
                )
            except ChannelClosedError:
                # propagate the poison downstream: close OUR out channels
                # too, else a mid-pipeline failure only unblocks immediate
                # consumers
                for op in plan:
                    if op.out_channel is not None:
                        op.out_channel.close()
                return "dag-loop-exit"
            except Exception:
                # poison the pipeline: close out channels so peers unblock
                logger.exception("compiled DAG actor loop failed")
                for op in plan:
                    if op.out_channel is not None:
                        op.out_channel.close()
                raise
    finally:
        dead[0] = True  # the prefetch thread must not outlive the loop


class CompiledDAGRef:
    """Future for one execute() call (reference: CompiledDAGRef)."""

    def __init__(self, dag: "CompiledDAG", seq: int):
        self._dag = dag
        self._seq = seq
        self._value = None
        self._have = False

    def get(self, timeout: Optional[float] = None):
        if not self._have:
            self._value = self._dag._fetch(self._seq, timeout)
            self._have = True
        return self._value


class CompiledDAG:
    def __init__(self, root: DAGNode, max_in_flight: int = 8,
                 channel_mode: str = "auto"):
        """channel_mode: 'auto' (shm on one host, TCP across hosts),
        'shm', or 'socket' (force TCP — e.g. daemons with divergent
        TMPDIRs, or tests exercising the cross-node path)."""
        import ray_tpu  # noqa: F401  (runtime must be up for actor calls)

        if channel_mode not in ("auto", "shm", "socket"):
            raise ValueError(
                f"channel_mode {channel_mode!r}: 'auto', 'shm' or 'socket'"
            )
        self._lock = threading.Lock()
        self._max_in_flight = max_in_flight
        self._seq = 0
        self._fetched = -1
        self._results: dict[int, Any] = {}
        self._torn_down = False

        nodes = root.walk()
        input_nodes = [n for n in nodes if isinstance(n, InputNode)]
        if len(input_nodes) > 1:
            raise ValueError("compiled DAG supports exactly one InputNode")
        self._input_node = input_nodes[0] if input_nodes else None

        outputs = root.outputs if isinstance(root, MultiOutputNode) else [root]
        self._outputs = outputs
        self._single = not isinstance(root, MultiOutputNode)

        # group executable nodes by loop: one loop per actor, one per
        # collective node (driver-side thread)
        actor_loops: dict = {}  # actor identity -> {handle, nodes}
        collectives: list[CollectiveOutputNode] = []
        self._cluster_mode = False
        for n in nodes:
            if isinstance(n, ClassMethodNode):
                h = n.actor_handle
                if hasattr(h, "_actor"):  # in-process handle
                    key = id(h._actor)
                else:  # cluster handle: PROCESS actor -> shm channels
                    key = h._actor_id
                    self._cluster_mode = True
                loop = actor_loops.setdefault(key, {"handle": h, "nodes": []})
                loop["nodes"].append(n)
            elif isinstance(n, CollectiveOutputNode):
                collectives.append(n)

        loop_of: dict[int, Any] = {}  # node_id -> loop key ('driver' for none)
        for key, loop in actor_loops.items():
            for n in loop["nodes"]:
                loop_of[n.id] = key
        for cn in collectives:
            loop_of[cn.id] = ("coll", cn.id)

        # --- channel allocation -------------------------------------------
        # consumers of node n = downstream executable nodes in OTHER loops
        # (+ the driver if n is an output). readers are indexed per channel.
        def consumers_of(n: DAGNode):
            cons = []
            for d in n.downstream:
                if isinstance(d, (ClassMethodNode, CollectiveOutputNode)):
                    if loop_of[d.id] != loop_of.get(n.id):
                        cons.append(loop_of[d.id])
            # dedupe, keep order
            seen, out = set(), []
            for c in cons:
                if c not in seen:
                    seen.add(c)
                    out.append(c)
            return out

        self._channels: list[Channel] = []
        chan_for: dict[int, Channel] = {}
        reader_idx: dict[tuple, int] = {}  # (node_id, consumer_loop) -> idx

        self._socket_channels = False
        if self._cluster_mode:
            # the shm data plane requires every participant (actors AND
            # the driver, which writes input / reads outputs) to share one
            # /dev/shm; when actors span HOSTS the channels become direct
            # writer->reader TCP streams (dag/socket_channel.py) instead —
            # reference: cross-node compiled-graph channels,
            # experimental/channel/shared_memory_channel.py:151
            hosts = set()
            for loop in actor_loops.values():
                h = loop["handle"]
                if hasattr(h, "_actor"):
                    continue
                info = h._client.gcs.call("get_actor", {"actor_id": h._actor_id})
                addr = (info or {}).get("node_addr") or (info or {}).get(
                    "worker_addr"
                )
                if addr:
                    hosts.add(addr[0])
                hosts.add(h._client.local_daemon_addr[0])
            if channel_mode == "socket" or (
                channel_mode == "auto" and len(hosts) > 1
            ):
                self._socket_channels = True
            elif channel_mode == "shm" and len(hosts) > 1:
                # fail HERE, not with "No such file" deep inside a remote
                # exec loop attaching a mapping that only exists on one host
                raise ValueError(
                    f"channel_mode='shm' requires all actors and the driver "
                    f"on ONE host; got hosts {sorted(hosts)} — use 'auto' "
                    "or 'socket'"
                )

        def make_channel(num_readers: int):
            if self._cluster_mode:
                if self._socket_channels:
                    from ray_tpu.dag.socket_channel import SocketChannel

                    return SocketChannel(
                        num_readers=num_readers, maxsize=max_in_flight
                    )
                # PROCESS actors, one host: named single-writer ring over a
                # shared memory mapping (dag/shm_channel.py) — the plasma-
                # mutable-object channel role
                from ray_tpu.dag.shm_channel import ShmChannel

                return ShmChannel(num_readers=num_readers, maxsize=max_in_flight)
            return Channel(num_readers=num_readers, maxsize=max_in_flight)

        def alloc_channel(n: DAGNode, extra_driver_reads: int):
            cons = consumers_of(n)
            total = len(cons) + extra_driver_reads
            if total == 0:
                return None
            ch = make_channel(total)
            self._channels.append(ch)
            chan_for[n.id] = ch
            for i, c in enumerate(cons):
                reader_idx[(n.id, c)] = i
            if extra_driver_reads:
                reader_idx[(n.id, "driver")] = len(cons)
            return ch

        output_ids = {n.id for n in outputs}
        for n in nodes:
            if isinstance(n, (ClassMethodNode, CollectiveOutputNode)):
                alloc_channel(n, 1 if n.id in output_ids else 0)

        # input channel: read by every loop that consumes the input
        self._input_channel = None
        if self._input_node is not None:
            consuming_loops = []
            for n in nodes:
                if isinstance(n, ClassMethodNode):
                    for a in list(n.args) + list(n.kwargs.values()):
                        if isinstance(a, (InputNode, InputAttributeNode)):
                            lk = loop_of[n.id]
                            if lk not in consuming_loops:
                                consuming_loops.append(lk)
            if any(isinstance(o, (InputNode, InputAttributeNode)) for o in outputs):
                raise ValueError("DAG output cannot be the input itself")
            self._input_consumers = consuming_loops
            if consuming_loops:
                self._input_channel = make_channel(len(consuming_loops))
                self._channels.append(self._input_channel)

        # --- build per-loop plans ------------------------------------------
        def arg_source(loop_key, a):
            if isinstance(a, InputNode):
                return ("input",)
            if isinstance(a, InputAttributeNode):
                return ("input_attr", a)
            if isinstance(a, DAGNode):
                if loop_of.get(a.id) == loop_key:
                    return ("local", a.id)
                return ("chan", chan_for[a.id], reader_idx[(a.id, loop_key)])
            return ("const", a)

        self._loop_handles = []
        for key, loop in actor_loops.items():
            plan = []
            for n in loop["nodes"]:  # creation order == topo order per actor
                plan.append(
                    _Op(
                        n.id,
                        n.method_name,
                        [arg_source(key, a) for a in n.args],
                        {k: arg_source(key, v) for k, v in n.kwargs.items()},
                        chan_for.get(n.id),
                    )
                )
            if self._input_channel is not None and key in self._input_consumers:
                in_src = ("chan", self._input_channel, self._input_consumers.index(key))
            else:
                in_src = None
            self._loop_handles.append(
                _submit_exec_loop(loop["handle"], plan, in_src)
            )

        # collective loops run as driver-side threads
        self._coll_threads = []
        for cn in collectives:
            key = ("coll", cn.id)
            srcs = [arg_source(key, a) for a in cn.inputs]
            out_ch = chan_for.get(cn.id)
            t = threading.Thread(
                target=_collective_loop,
                args=(cn.op, srcs, out_ch),
                daemon=True,
                name=f"dag-collective-{cn.id}",
            )
            t.start()
            self._coll_threads.append(t)

        # driver-side output readers
        self._output_sources = []
        for o in outputs:
            self._output_sources.append(
                ("chan", chan_for[o.id], reader_idx[(o.id, "driver")])
            )

    # -- execution ------------------------------------------------------------

    def execute(self, *args, **kwargs) -> CompiledDAGRef:
        if self._torn_down:
            raise RuntimeError("compiled DAG has been torn down")
        with self._lock:
            # a full pipeline must fail loudly, not self-deadlock: draining
            # requires _fetch, which a blocked write (holding _lock) starves
            if self._seq - self._fetched - 1 >= self._max_in_flight:
                raise RuntimeError(
                    f"compiled DAG has {self._max_in_flight} executions in "
                    f"flight; call .get() on earlier refs before execute()"
                )
            seq = self._seq
            self._seq += 1
            if self._input_channel is not None:
                if kwargs and not args:
                    value = kwargs
                elif len(args) == 1 and not kwargs:
                    value = args[0]
                else:
                    value = args
                self._input_channel.write(value)
        return CompiledDAGRef(self, seq)

    def _fetch(self, seq: int, timeout: Optional[float]):
        import queue as _queue

        from ray_tpu.dag.channels import DEFAULT_READ_TIMEOUT

        # timeout=None means the BOUNDED default for every channel
        # flavor: the shm/socket channels' read(timeout=None) parks
        # forever, and a value dropped on the final output edge (the
        # exec loops all stay healthy) would hang the driver's get()
        eff = DEFAULT_READ_TIMEOUT if timeout is None else timeout
        with self._lock:
            while self._fetched < seq:
                try:
                    vals = [
                        src[1].read(src[2], timeout=eff)
                        for src in self._output_sources
                    ]
                except (_queue.Empty, ChannelTimeoutError):
                    raise TimeoutError(
                        f"compiled DAG output {seq} not ready after {eff}s"
                    ) from None
                self._fetched += 1
                self._results[self._fetched] = (
                    vals[0] if self._single else list(vals)
                )
            out = self._results.pop(seq)
        return out

    # -- lifecycle ------------------------------------------------------------

    def teardown(self) -> None:
        if self._torn_down:
            return
        self._torn_down = True
        for ch in self._channels:
            ch.close()
        import ray_tpu

        for ref in self._loop_handles:
            try:
                ray_tpu.get(ref, timeout=5)
            except Exception:
                pass
        for ch in self._channels:
            if hasattr(ch, "unlink"):  # shm channels: reclaim the mapping
                ch.unlink()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass


def _submit_exec_loop(handle, plan, input_source):
    """Kick off the framework exec-loop task on the actor; returns its ref."""
    if hasattr(handle, "_actor"):  # in-process actor
        from ray_tpu.core.api import ActorMethod

        method = ActorMethod(handle, "__ray_tpu_dag_exec_loop__")
    else:  # cluster (process) actor
        from ray_tpu.cluster.client import _ActorMethod

        method = _ActorMethod(handle, "__ray_tpu_dag_exec_loop__")
    return method.remote(plan, input_source)


def _collective_loop(op, srcs, out_ch):
    while True:
        try:
            started = [False]  # idle-tolerant until the round's first value
            vals = [_resolve_source(s, None, {}, started) for s in srcs]
            acc = vals[0]
            for v in vals[1:]:
                acc = op(acc, v)
            if out_ch is not None:
                out_ch.write(acc)
        except ChannelClosedError:
            if out_ch is not None:
                out_ch.close()  # propagate poison downstream
            return
        except Exception:
            logger.exception("collective loop failed")
            if out_ch is not None:
                out_ch.close()
            return
