"""Shared-memory channel: the cross-PROCESS substrate for compiled DAGs.

Reference analog: mutable plasma-object channels
(python/ray/experimental/channel/shared_memory_channel.py over
src/ray/core_worker/experimental_mutable_object_manager.h spin-wait
buffers). Here a channel is a named ring of sealed objects in one
`ShmObjectStore` mapping that every participant process opens:

  * data slot for seq N: object id H(name|d|N) holding the pickled value;
  * ack for (reader R, seq N): empty object H(name|a|N|R);
  * writer backpressure: before writing seq N it waits for every
    reader's ack of seq N-maxsize, then deletes that round's objects —
    at most `maxsize` values are ever resident;
  * close: a sentinel payload; readers raise ChannelClosedError.

Readers spin with a short adaptive sleep (the reference's C++ channel
spin-waits too); payload bytes move zero-copy out of the mapping.
Single host by design — cross-node DAG edges go through the object
plane, as in the reference.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import time
import uuid
from typing import Any, Optional

from ray_tpu.dag.channels import ChannelClosedError

_CLOSE = b"__ray_tpu_chan_closed__"
_DEFAULT_CAPACITY = 64 << 20


def _oid(name: str, kind: str, *parts) -> bytes:
    h = hashlib.md5(("%s|%s|%s" % (name, kind, "|".join(map(str, parts)))).encode())
    return h.digest()[:16]


class ShmChannel:
    """Single-writer, N-reader, bounded, named, cross-process."""

    def __init__(self, num_readers: int = 1, maxsize: int = 2,
                 name: Optional[str] = None, store_path: Optional[str] = None,
                 capacity: int = _DEFAULT_CAPACITY, _create: bool = True):
        if num_readers < 1:
            raise ValueError("channel needs at least one reader")
        self.name = name or uuid.uuid4().hex
        self.num_readers = num_readers
        self.maxsize = max(1, maxsize)
        from ray_tpu.utils.shm import shm_dir

        self.store_path = store_path or os.path.join(
            shm_dir(), f"ray_tpu-chan-{self.name[:16]}")
        self._capacity = capacity
        self._creator = False
        self._store = None
        self._write_seq = 0
        self._read_seq = [0] * num_readers
        if _create and not os.path.exists(self.store_path):
            from ray_tpu.native.shm import ShmObjectStore

            self._store = ShmObjectStore.create(self.store_path, capacity)
            self._creator = True

    # -- plumbing -------------------------------------------------------------

    def _s(self):
        if self._store is None:
            from ray_tpu.native.shm import ShmObjectStore

            deadline = time.monotonic() + 10.0
            while True:
                try:
                    self._store = ShmObjectStore.open(self.store_path)
                    break
                except OSError:
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.01)
        return self._store

    def __reduce__(self):
        return (_rebuild_shm_channel,
                (self.name, self.num_readers, self.maxsize, self.store_path,
                 self._capacity))

    def _wait_contains(self, oid: bytes, timeout: Optional[float]):
        """Park until `oid` exists. Pending data drains before the closed
        marker is honored (the marker is only consulted while waiting), so
        close() is an orderly drain-then-stop from ANY process — including
        ones that never wrote, which a seq-stream sentinel can't provide."""
        store = self._s()
        closed_oid = _oid(self.name, "x")
        deadline = None if timeout is None else time.monotonic() + timeout
        sleep = 0.0002
        while not store.contains(oid):
            if store.contains(closed_oid):
                raise ChannelClosedError("channel closed")
            if deadline is not None and time.monotonic() >= deadline:
                import queue as _q

                raise _q.Empty()
            time.sleep(sleep)
            sleep = min(sleep * 2, 0.002)

    # -- API (mirrors dag.channels.Channel) -----------------------------------

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        from ray_tpu.dag.channels import chaos_channel_op

        if chaos_channel_op("send", transport="shm"):
            return  # DROP_CHANNEL: lost in flight; readers' bounds surface it
        self._write_payload(pickle.dumps(value, protocol=5), timeout)

    def _write_payload(self, payload: bytes, timeout: Optional[float]) -> None:
        store = self._s()
        seq = self._write_seq
        # backpressure + GC: seq-maxsize must be fully consumed
        old = seq - self.maxsize
        if old >= 0:
            for r in range(self.num_readers):
                self._wait_contains(_oid(self.name, "a", old, r), timeout)
            store.delete(_oid(self.name, "d", old))
            for r in range(self.num_readers):
                store.delete(_oid(self.name, "a", old, r))
        store.put(_oid(self.name, "d", seq), payload)
        self._write_seq = seq + 1

    def read(self, reader_idx: int = 0, timeout: Optional[float] = None) -> Any:
        from ray_tpu.dag.channels import chaos_channel_op

        chaos_channel_op("recv", transport="shm")
        store = self._s()
        seq = self._read_seq[reader_idx]
        oid = _oid(self.name, "d", seq)
        self._wait_contains(oid, timeout)
        data = store.get_bytes(oid)
        if data is None:  # deleted between contains and get: already acked?
            raise ChannelClosedError("channel slot vanished")
        if data == _CLOSE:
            raise ChannelClosedError("channel closed")
        value = pickle.loads(data)
        store.put(_oid(self.name, "a", seq, reader_idx), b"")
        self._read_seq[reader_idx] = seq + 1
        return value

    def close(self) -> None:
        # out-of-band marker first: it unblocks read AND backpressure
        # waiters in every process regardless of whose write cursor this
        # handle holds
        try:
            self._s().put(_oid(self.name, "x"), b"")
        except Exception:  # noqa: BLE001 — already closed / store gone
            pass
        try:
            self._write_payload(_CLOSE, timeout=1.0)
        except Exception:  # noqa: BLE001 — best-effort in-stream sentinel
            pass

    def unlink(self) -> None:
        """Creator-side teardown of the backing mapping."""
        if self._store is not None:
            try:
                self._store.close()
            except Exception:  # noqa: BLE001
                pass
            self._store = None
        if self._creator:
            try:
                os.unlink(self.store_path)
            except OSError:
                pass


def _rebuild_shm_channel(name, num_readers, maxsize, store_path, capacity):
    ch = ShmChannel(num_readers=num_readers, maxsize=maxsize, name=name,
                    store_path=store_path, capacity=capacity, _create=False)
    return ch
