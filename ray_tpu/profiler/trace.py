"""Observability export for StepProfiles.

Three sinks, all already wired to user-visible surfaces:

  * core/events.py TaskEventBuffer — segment spans become Chrome-trace
    "X" events (kind="profile"), so the legacy dashboard /timeline
    route and util.state.timeline() show the step breakdown next to
    task spans;
  * obs/recorder.py SpanRecorder — the same strip lands in the flight
    recorder as one bounded trace (root ``profile:{step}`` + one child
    span per segment), which is the AUTHORITATIVE profile stream for
    the unified /api/trace export: the recorder's drop-oldest caps
    (max_traces / max_spans_per_trace) bound it, and /api/trace filters
    the duplicate task-buffer copy out of its timeline half;
  * util/metrics.py Histograms/Gauges — per-segment wall time and
    step-level coverage/attainment land on the dashboard /metrics
    Prometheus endpoint for free.
"""

from __future__ import annotations

import itertools
import time
from typing import Optional

from ray_tpu.profiler.roofline import StepProfile
from ray_tpu.util.metrics import Gauge, Histogram

_span_counter = itertools.count()

# Boundaries tuned for step segments: micro-segments on CPU smoke models
# sit well under 1 ms; a wedged segment on a tunneled device can reach
# hundreds of ms.
_SEGMENT_MS_BOUNDARIES = [
    0.01, 0.05, 0.1, 0.5, 1, 2, 5, 10, 25, 50, 100, 250, 500, 1000,
]


def segment_histogram() -> Histogram:
    """The per-segment wall-time histogram (same storage every call:
    util.metrics shares series for same-name re-registrations)."""
    return Histogram(
        "profiler_segment_ms",
        description="profiler: attributed wall time per step segment (ms)",
        boundaries=_SEGMENT_MS_BOUNDARIES,
        tag_keys=("step", "segment", "bound"),
    )


def coverage_gauge() -> Gauge:
    from ray_tpu.obs.telemetry import AGG_MAX, declare_aggregation

    # cluster rollup: worst-profiled step wins (a fleet "coverage" sum
    # would be meaningless)
    declare_aggregation("profiler_step_coverage_pct", AGG_MAX)
    return Gauge(
        "profiler_step_coverage_pct",
        description="profiler: % of measured step time attributed to segments",
        tag_keys=("step",),
    )


def step_ms_gauge() -> Gauge:
    from ray_tpu.obs.telemetry import AGG_MAX, declare_aggregation

    declare_aggregation("profiler_step_ms", AGG_MAX)
    return Gauge(
        "profiler_step_ms",
        description="profiler: measured whole-step wall time (ms)",
        tag_keys=("step",),
    )


def export_metrics(profile: StepProfile) -> None:
    """Observe every segment + step-level gauges into the process-wide
    metrics registry (rendered by the dashboard /metrics route)."""
    hist = segment_histogram()
    for seg in profile.segments:
        hist.observe(
            seg.ms,
            tags={"step": profile.step, "segment": seg.name,
                  "bound": seg.bound},
        )
    coverage_gauge().set(profile.coverage_pct, tags={"step": profile.step})
    step_ms_gauge().set(profile.measured_step_ms, tags={"step": profile.step})


def emit_spans(profile: StepProfile, buffer=None, *,
               t_end: Optional[float] = None) -> int:
    """Reconstruct segment spans into the task event buffer.

    Segments are laid out back-to-back ending at ``t_end`` (default now),
    scaled to their attributed durations, so `ray timeline` / the
    dashboard /timeline route renders one profiled step as a contiguous
    strip. Returns the number of spans emitted."""
    if buffer is None:
        from ray_tpu.core import runtime as rt

        buffer = rt.get_runtime().task_events
    from ray_tpu.core.events import TaskState

    end = time.time() if t_end is None else t_end
    in_step = [s for s in profile.segments if s.in_step]
    total_s = sum(s.ms for s in in_step) / 1e3
    start = end - total_s
    n = 0
    cursor = start
    for seg in profile.segments:
        dur = seg.ms / 1e3
        if seg.in_step:
            t0, t1 = cursor, cursor + dur
            cursor = t1
        else:  # standalone segments stack before the step strip
            t0, t1 = start - dur, start
        span_id = f"profile-{profile.step}-{seg.name}-{next(_span_counter)}"
        name = f"profile:{profile.step}:{seg.name}"
        buffer.record(
            span_id, name, TaskState.RUNNING, kind="profile",
            worker=f"profiler:{profile.step}", ts=t0,
        )
        buffer.record(
            span_id, name, TaskState.FINISHED, kind="profile",
            worker=f"profiler:{profile.step}", ts=t1,
        )
        n += 1
    return n


def emit_recorder_spans(profile: StepProfile, recorder=None, *,
                        t_end: Optional[float] = None) -> str:
    """Mirror the profiled step into the obs flight recorder as ONE
    bounded trace: a root span ``profile:{step}`` covering the whole
    strip plus a child span per segment (same back-to-back layout as
    :func:`emit_spans`, standalone segments stacked before the strip).
    The recorder's drop-oldest caps make this the bounded profile
    stream /api/trace serves. Returns the trace id."""
    if recorder is None:
        from ray_tpu.obs.recorder import get_recorder

        recorder = get_recorder()
    from ray_tpu.obs.recorder import Span

    end = time.time() if t_end is None else t_end
    total_s = sum(s.ms for s in profile.segments if s.in_step) / 1e3
    standalone_s = max(
        (s.ms / 1e3 for s in profile.segments if not s.in_step), default=0.0
    )
    start = end - total_s
    trace_id = f"profile-{profile.step}-{next(_span_counter)}"
    root_id = f"{trace_id}-root"
    recorder.add(Span(
        trace_id=trace_id, span_id=root_id, parent_id=None,
        name=f"profile:{profile.step}",
        start=start - standalone_s, end=end,
        attrs={
            "step": profile.step,
            "measured_step_ms": profile.measured_step_ms,
            "coverage_pct": profile.coverage_pct,
        },
    ))
    cursor = start
    for seg in profile.segments:
        dur = seg.ms / 1e3
        if seg.in_step:
            t0, t1 = cursor, cursor + dur
            cursor = t1
        else:
            t0, t1 = start - dur, start
        recorder.add(Span(
            trace_id=trace_id,
            span_id=f"{trace_id}-{seg.name}",
            parent_id=root_id,
            name=f"profile:{profile.step}:{seg.name}",
            start=t0, end=t1,
            attrs={"ms": seg.ms, "bound": seg.bound,
                   "in_step": seg.in_step},
        ))
    return trace_id


def export(profile: StepProfile, buffer=None) -> None:
    """All sinks in one call — what the train/serve hooks use."""
    export_metrics(profile)
    t_end = time.time()
    emit_spans(profile, buffer, t_end=t_end)
    emit_recorder_spans(profile, t_end=t_end)
