"""ray_tpu.profiler — roofline-attribution profiling for train steps
and LLM decode.

The measurement layer the perf roadmap runs on: attribute every
millisecond of a step to a named segment (chained-probe ladder,
segments.py), price each segment with XLA's own FLOPs/bytes estimate
(costs.py), classify compute- vs bandwidth-bound against chip peaks and
report attainment + the largest unattributed residual (roofline.py),
and export spans/histograms to the existing timeline + Prometheus
surfaces (trace.py).

Entry points:

    profile_train_step(config, params, batch, optimizer) -> StepProfile
    profile_decode_step(config, params, ...)             -> StepProfile

both CPU-safe (tier-1 tests run them under JAX_PLATFORMS=cpu).
"""

from __future__ import annotations

from typing import Optional

from ray_tpu.profiler.costs import ChipPeaks, SegmentCost, chip_peaks, compiled_cost
from ray_tpu.profiler.roofline import SegmentProfile, StepProfile
from ray_tpu.profiler.segments import (
    FnPart,
    SegmentTiming,
    allreduce_overlap_segments,
    chained_seconds,
    decode_step_segments,
    profile_segments,
    register_segments,
    segment_builders,
    spec_decode_segments,
    train_step_segments,
)
from ray_tpu.profiler.trace import emit_spans, export, export_metrics

__all__ = [
    "ChipPeaks",
    "FnPart",
    "SegmentCost",
    "SegmentProfile",
    "SegmentTiming",
    "StepProfile",
    "allreduce_overlap_segments",
    "chained_seconds",
    "chip_peaks",
    "compiled_cost",
    "decode_step_segments",
    "emit_spans",
    "export",
    "export_metrics",
    "profile_decode_step",
    "profile_segments",
    "profile_spec_decode_step",
    "profile_train_step",
    "register_segments",
    "segment_builders",
    "spec_decode_segments",
    "train_step_segments",
]


def profile_train_step(
    config,
    params,
    batch: dict,
    optimizer,
    *,
    iters: int = 6,
    warmup: int = 2,
    with_costs: bool = True,
    export_observability: bool = True,
    with_allreduce_probe: bool = True,
    meta: Optional[dict] = None,
) -> StepProfile:
    """Roofline-attributed profile of one llama train step.

    Segments: embed / ln_residual / attention / mlp / lm_head_loss /
    ce_bwd / mlp_bwd / attention_bwd / optimizer_update (the backward is
    split with stop_gradient-scoped rungs — identical primal, telescoped
    grad scopes), plus standalone allreduce / allreduce_exposed probes
    (``in_step=False``) pricing how much of a DP gradient all-reduce
    hides behind the backward; the overlap ratio lands in
    ``meta["allreduce_overlap_ratio"]`` (None below the timing noise
    floor, e.g. single-device). The whole-step reference is the real
    jitted train.step program measured with the same chained runner.
    """
    import jax

    parts, whole_fn = train_step_segments(
        config, params, batch, optimizer, iters=iters, warmup=warmup
    )
    segments = profile_segments(
        parts, iters=iters, warmup=warmup, with_costs=with_costs
    )
    ar_ratio = None
    if with_allreduce_probe:
        ar_segments, ar_ratio = allreduce_overlap_segments(
            config, params, batch, iters=iters, warmup=warmup
        )
        segments.extend(ar_segments)
    whole_ms = whole_fn()
    profile = StepProfile.build(
        "train_step", segments, whole_ms,
        meta={
            "batch": int(batch["tokens"].shape[0]),
            "seq": int(batch["tokens"].shape[1]),
            "model_params": config.num_params(),
            "attention_impl": config.attention_impl,
            "allreduce_overlap_ratio": ar_ratio,
            "allreduce_devices": jax.device_count(),
            **(meta or {}),
        },
    )
    if export_observability:
        export(profile)
    return profile


def profile_decode_step(
    config,
    params,
    *,
    batch_size: int = 4,
    context_len: int = 32,
    block_size: int = 16,
    attn_impl: str = "auto",
    sample_mode: str = "full",
    iters: int = 8,
    warmup: int = 2,
    include_prefill: bool = True,
    with_costs: bool = True,
    export_observability: bool = True,
    meta: Optional[dict] = None,
) -> StepProfile:
    """Roofline-attributed profile of one serving decode step.

    Segments: embed / qkv_rope / kv_write / kv_read_attn / block_mlp /
    lm_head / sampling / stop_mask (+ host_sync from the
    fenced-every-step delta, + standalone prefill and host_overlap
    probes — host_overlap prices what double-buffered dispatch recovers
    of host_sync). The decode step is rebuilt from the same
    llama_decode/sampling/pipeline pieces the engine jits, over a
    scratch paged cache, so profiling never touches live engine state.
    """
    parts, whole_fn = decode_step_segments(
        config, params,
        batch_size=batch_size, context_len=context_len,
        block_size=block_size, attn_impl=attn_impl,
        sample_mode=sample_mode, iters=iters, warmup=warmup,
        include_prefill=include_prefill,
    )
    segments = profile_segments(
        parts, iters=iters, warmup=warmup, with_costs=with_costs
    )
    # the reference is the REAL decode_step + sampler + stop-mask
    # program, measured independently of the ladder — coverage then
    # reports ladder fidelity instead of being ~100% by construction
    chained_real_ms, synced_ms, pipelined_ms = whole_fn()
    # host_sync: what one-token-per-round-trip serving pays on top of the
    # pure device step; the engine's multi-step decode_chunk amortizes it
    segments.append(
        SegmentTiming(
            name="host_sync",
            ms=max(0.0, synced_ms - chained_real_ms),
            cum_ms=synced_ms,
            in_step=True,
        )
    )
    # host_overlap (standalone): the slice of host_sync the pipelined
    # engine hides by dispatching chunk N+1 before fencing chunk N —
    # measured, not inferred (same program, double-buffered fencing)
    segments.append(
        SegmentTiming(
            name="host_overlap",
            ms=max(0.0, synced_ms - pipelined_ms),
            cum_ms=pipelined_ms,
            in_step=False,
        )
    )
    profile = StepProfile.build(
        "decode_step", segments, synced_ms,
        meta={
            "batch_size": batch_size,
            "context_len": context_len,
            "block_size": block_size,
            "model_params": config.num_params(),
            "attn_impl": attn_impl,
            "sample_mode": sample_mode,
            **(meta or {}),
        },
    )
    if export_observability:
        export(profile)
    return profile


def profile_spec_decode_step(
    config,
    params,
    spec,
    *,
    batch_size: int = 4,
    context_len: int = 32,
    block_size: int = 16,
    iters: int = 6,
    warmup: int = 2,
    export_observability: bool = True,
    meta: Optional[dict] = None,
) -> StepProfile:
    """Roofline-attributed profile of one SPECULATIVE decode round.

    Segments: draft (host n-gram lookup) / verify (batched k+1-token
    paged pass) / accept (distribution-preserving sampler) /
    kv_rollback (host block truncate/refill). Rungs mix host and device
    work, so cost-model fields are empty (unknown-bound) — the profile's
    value is the wall-time split: is the win from fewer decode passes
    being eaten by drafting or host bookkeeping?
    """
    parts, whole_fn = spec_decode_segments(
        config, params, spec,
        batch_size=batch_size, context_len=context_len,
        block_size=block_size, iters=iters, warmup=warmup,
    )
    segments = profile_segments(
        parts, iters=iters, warmup=warmup, with_costs=False,
    )
    whole_ms = whole_fn()
    profile = StepProfile.build(
        "spec_decode_step", segments, whole_ms,
        meta={
            "batch_size": batch_size,
            "context_len": context_len,
            "block_size": block_size,
            "num_draft_tokens": spec.num_draft_tokens,
            "spec_method": spec.method,
            "model_params": config.num_params(),
            **(meta or {}),
        },
    )
    if export_observability:
        export(profile)
    return profile
