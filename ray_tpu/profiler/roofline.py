"""Roofline attribution: join measured segment wall time with static
cost into a StepProfile report.

Each segment lands at a point (operational intensity, achieved FLOP/s)
under the chip's roofline (peak FLOPs capped by peak HBM bandwidth x
intensity): segments left of the ridge are bandwidth-bound, right of it
compute-bound; attainment is achieved/attainable for the segment's own
regime. The report also carries the largest unattributed residual — the
profiler's own honesty metric — so a follow-up PR knows whether to
optimize a named segment or go find the missing time first.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Optional

from ray_tpu.profiler.costs import ChipPeaks, chip_peaks
from ray_tpu.profiler.segments import SegmentTiming

COMPUTE_BOUND = "compute"
BANDWIDTH_BOUND = "bandwidth"
UNKNOWN_BOUND = "unknown"


@dataclasses.dataclass
class SegmentProfile:
    name: str
    ms: float
    pct_of_step: float
    flops: float
    bytes_accessed: float
    intensity: Optional[float]        # FLOPs / byte
    achieved_tflops: Optional[float]
    achieved_gbps: Optional[float]
    attainment_pct: Optional[float]   # achieved / attainable in its regime
    bound: str
    in_step: bool = True

    @classmethod
    def build(
        cls, seg: SegmentTiming, step_ms: float, peaks: ChipPeaks
    ) -> "SegmentProfile":
        sec = seg.ms / 1e3
        pct = 100.0 * seg.ms / step_ms if step_ms > 0 else 0.0
        if not seg.cost.populated:
            return cls(
                name=seg.name, ms=round(seg.ms, 4), pct_of_step=round(pct, 2),
                flops=seg.cost.flops, bytes_accessed=seg.cost.bytes_accessed,
                intensity=None, achieved_tflops=None, achieved_gbps=None,
                attainment_pct=None, bound=UNKNOWN_BOUND, in_step=seg.in_step,
            )
        flops, byts = seg.cost.flops, seg.cost.bytes_accessed
        intensity = flops / byts if byts > 0 else None
        # bound classification is STATIC (cost model vs ridge) — valid
        # even when the measured slice is too small to rate
        if intensity is None:
            bound = COMPUTE_BOUND if flops > 0 else UNKNOWN_BOUND
        elif intensity >= peaks.ridge_intensity:
            bound = COMPUTE_BOUND
        else:
            bound = BANDWIDTH_BOUND
        # below ~10us the ladder diff is noise-floor; achieved-rate math
        # on it produces fiction (e.g. >100% attainment)
        if sec <= 1e-5:
            ach_fl = ach_bw = attain = None
        else:
            ach_fl = flops / sec
            ach_bw = byts / sec
            if bound == COMPUTE_BOUND:
                attain = 100.0 * ach_fl / peaks.flops
            elif bound == BANDWIDTH_BOUND:
                attain = 100.0 * ach_bw / peaks.hbm_bytes_s
            else:
                attain = None
        return cls(
            name=seg.name,
            ms=round(seg.ms, 4),
            pct_of_step=round(pct, 2),
            flops=flops,
            bytes_accessed=byts,
            intensity=round(intensity, 3) if intensity is not None else None,
            achieved_tflops=round(ach_fl / 1e12, 4) if ach_fl is not None else None,
            achieved_gbps=round(ach_bw / 1e9, 2) if ach_bw is not None else None,
            attainment_pct=round(attain, 2) if attain is not None else None,
            bound=bound,
            in_step=seg.in_step,
        )


@dataclasses.dataclass
class StepProfile:
    step: str                      # "train_step" | "decode_step" | ...
    device_kind: str
    platform: str
    peak_tflops: float
    peak_hbm_gbps: float
    measured_step_ms: float        # independently measured whole step
    attributed_ms: float           # sum of in-step segment times
    residual_ms: float             # measured - attributed (can be < 0)
    coverage_pct: float            # attributed / measured
    segments: list[SegmentProfile]
    largest_unattributed: str      # residual, or the biggest unknown-bound seg
    meta: dict = dataclasses.field(default_factory=dict)

    # -- construction --------------------------------------------------------

    @classmethod
    def build(
        cls,
        step: str,
        segments: list[SegmentTiming],
        measured_step_ms: float,
        *,
        peaks: Optional[ChipPeaks] = None,
        meta: Optional[dict] = None,
    ) -> "StepProfile":
        import jax

        peaks = peaks or chip_peaks()
        attributed = sum(s.ms for s in segments if s.in_step)
        residual = measured_step_ms - attributed
        profs = [
            SegmentProfile.build(s, measured_step_ms, peaks) for s in segments
        ]
        # honesty pointer: the biggest slice of time with no roofline
        # story — either the unattributed residual or an unknown-bound
        # segment (cost model came back empty)
        candidates = {"residual": max(residual, 0.0)}
        for p in profs:
            if p.in_step and p.bound == UNKNOWN_BOUND:
                candidates[p.name] = p.ms
        largest = max(candidates, key=candidates.get)
        return cls(
            step=step,
            device_kind=peaks.device_kind,
            platform=jax.devices()[0].platform,
            peak_tflops=round(peaks.flops / 1e12, 2),
            peak_hbm_gbps=round(peaks.hbm_bytes_s / 1e9, 2),
            measured_step_ms=round(measured_step_ms, 4),
            attributed_ms=round(attributed, 4),
            residual_ms=round(residual, 4),
            coverage_pct=round(100.0 * attributed / measured_step_ms, 2)
            if measured_step_ms > 0 else 0.0,
            segments=profs,
            largest_unattributed=largest,
            meta=dict(meta or {}),
        )

    # -- rendering -----------------------------------------------------------

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["segments"] = [dataclasses.asdict(s) for s in self.segments]
        return d

    def to_json(self, indent: int = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent)

    def save(self, path: str) -> str:
        with open(path, "w") as f:
            f.write(self.to_json() + "\n")
        return path

    def to_markdown(self) -> str:
        lines = [
            f"# {self.step} profile — {self.device_kind} ({self.platform})",
            "",
            f"Peaks: {self.peak_tflops} TFLOP/s, {self.peak_hbm_gbps} GB/s "
            f"(ridge {self.peak_tflops * 1e12 / (self.peak_hbm_gbps * 1e9):.1f} "
            "FLOPs/byte)",
            f"Whole step: {self.measured_step_ms:.3f} ms measured; "
            f"{self.attributed_ms:.3f} ms attributed "
            f"({self.coverage_pct:.1f}% coverage, "
            f"residual {self.residual_ms:+.3f} ms)",
            f"Largest unattributed: {self.largest_unattributed}",
            "",
            "| segment | ms | % of step | GFLOPs | MB | FLOPs/B | bound "
            "| attainment |",
            "|---|---:|---:|---:|---:|---:|---|---:|",
        ]
        for s in self.segments:
            tag = "" if s.in_step else " (standalone)"
            lines.append(
                f"| {s.name}{tag} | {s.ms:.3f} | {s.pct_of_step:.1f} "
                f"| {s.flops / 1e9:.3f} | {s.bytes_accessed / 1e6:.2f} "
                f"| {s.intensity if s.intensity is not None else '—'} "
                f"| {s.bound} "
                f"| {f'{s.attainment_pct:.1f}%' if s.attainment_pct is not None else '—'} |"
            )
        if self.meta:
            lines.append("")
            for k, v in self.meta.items():
                lines.append(f"- {k}: {v}")
        return "\n".join(lines) + "\n"
