"""Segment registry + chained-probe ladder runner.

The measurement primitive generalizes benchmarks/chained_probe.py: a
segment is one rung of a *cumulative ladder* of jitted programs, each a
superset of the previous rung's work (embed -> +LN/residual ->
+attention -> +MLP -> +loss -> +backward -> +optimizer). Every rung is
timed with chained-probe semantics — K data-dependent iterations, ONE
host fence at the end — so the per-rung time is pure device time, and
segment attribution falls out of telescoping differences: the segments
sum to the final rung (the whole step) by construction, and the gap
between the ladder total and an independently measured real step is
reported honestly as residual.

Chaining: each rung's carry feeds the next iteration (the train ladder
injects a zero-valued function of the rung's result into the embedding
table; the decode ladder feeds sampled/derived tokens forward), so the
final fence cannot land before every iteration's compute has executed —
the same impossible-to-fake guarantee bench.py's timed_steps relies on.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ray_tpu.profiler.costs import SegmentCost

# -- registry ----------------------------------------------------------------

_BUILDERS: dict[str, Callable] = {}


def register_segments(name: str):
    """Register a segment-ladder builder under a step name (the registry
    the benchmarks and `bench.py --profile` resolve builders through)."""

    def deco(fn):
        _BUILDERS[name] = fn
        return fn

    return deco


def segment_builders() -> dict[str, Callable]:
    return dict(_BUILDERS)


# -- primitives --------------------------------------------------------------


@dataclasses.dataclass
class FnPart:
    """One rung: ``fn(carry) -> carry`` closing over everything else.

    ``make_carry`` builds a fresh carry per run so donated rungs never
    invalidate a buffer another rung still references.
    """

    name: str
    fn: Callable
    make_carry: Callable[[], Any]
    donate: bool = False
    prejitted: bool = False  # fn already dispatches a compiled program
    in_step: bool = True     # counts toward the whole-step sum


@dataclasses.dataclass
class SegmentTiming:
    name: str
    ms: float                 # attributed time (ladder diff, clamped >= 0)
    cum_ms: float             # this rung's absolute per-iteration time
    cost: SegmentCost = dataclasses.field(default_factory=SegmentCost)
    in_step: bool = True


def _fence(tree) -> float:
    """Pull one element of the first leaf to the host: the transfer is
    data-dependent on the chain, so it cannot complete early."""
    leaf = jax.tree.leaves(tree)[0]
    return float(jnp.asarray(leaf).ravel()[0])


def _token(x: jax.Array) -> jax.Array:
    """Scalar f32 summary of a tensor; consuming it keeps the producing
    computation alive against DCE."""
    return jnp.sum(x.astype(jnp.float32))


def _effective_donate(want: bool) -> bool:
    # CPU XLA can't alias donated buffers; requesting it just prints a
    # warning per compile. Only donate where it actually goes in-place.
    return want and jax.devices()[0].platform == "tpu"


def chained_seconds(
    fn: Callable,
    make_carry: Callable[[], Any],
    *,
    iters: int = 8,
    warmup: int = 2,
    repeats: int = 3,
    donate: bool = False,
    prejitted: bool = False,
    fence_each: bool = False,
) -> float:
    """Per-iteration seconds of ``fn``: best of ``repeats`` timing loops
    of ``iters`` chained calls each (min-of-means rejects transient host
    contention, the dominant noise source on a shared CPU).

    ``fence_each=True`` fences every iteration instead (the host-sync
    cost probe: the difference vs the chained run is the round-trip the
    serving loop pays per step when it syncs each token).
    """
    jfn = fn if prejitted else jax.jit(
        fn, donate_argnums=(0,) if _effective_donate(donate) else ()
    )
    return _timed(jfn, make_carry, iters=iters, warmup=warmup,
                  repeats=repeats, fence_each=fence_each)


def _timed(jfn, make_carry, *, iters, warmup, repeats, fence_each=False) -> float:
    carry = make_carry()
    for _ in range(max(1, warmup)):
        carry = jfn(carry)
    _fence(carry)
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        for _ in range(iters):
            carry = jfn(carry)
            if fence_each:
                _fence(carry)
        if not fence_each:
            _fence(carry)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def _timed_pipelined(jfn, make_carry, *, iters, warmup, repeats) -> float:
    """Per-iteration seconds with DOUBLE-BUFFERED fencing: dispatch
    iteration N+1 before fencing iteration N's result, so the host
    round-trip overlaps device compute (JAX async dispatch) — the
    measurement model of the engine's pipelined decode path. ``jfn``
    must not donate its carry (the lag-1 fence still reads it)."""
    carry = make_carry()
    for _ in range(max(1, warmup)):
        carry = jfn(carry)
    _fence(carry)
    best = float("inf")
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        prev = jfn(carry)
        for _ in range(iters - 1):
            cur = jfn(prev)
            _fence(prev)  # overlaps cur's device work
            prev = cur
        _fence(prev)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def profile_segments(
    fn_parts: list[FnPart],
    *,
    iters: int = 8,
    warmup: int = 2,
    repeats: int = 2,
    passes: int = 2,
    with_costs: bool = True,
) -> list[SegmentTiming]:
    """Time a cumulative ladder; attribute each rung the difference vs
    the rung before it (independent parts — ``in_step=False`` — get
    their absolute time). Costs telescope the same way, from XLA's
    cost_analysis of each rung's compiled program.

    Timing sweeps the whole ladder ``passes`` times and keeps each
    rung's minimum: a host-contention spike long enough to cover one
    rung's repeats then lands on a DIFFERENT rung next pass instead of
    permanently inflating the same diff.

    Each rung is lowered + compiled ONCE; the timing loop calls the
    compiled executable and the cost model reads cost_analysis() off the
    same object (a second jit would double compile wall time)."""
    from ray_tpu.profiler.costs import cost_from_compiled

    jfns: list = []
    part_costs: list[SegmentCost] = []
    for part in fn_parts:
        if part.prejitted:
            jfns.append(part.fn)
            part_costs.append(SegmentCost())
            continue
        jfn = jax.jit(
            part.fn,
            donate_argnums=(0,) if _effective_donate(part.donate) else (),
        )
        try:
            exe = jfn.lower(part.make_carry()).compile()
            jfns.append(exe)
            part_costs.append(
                cost_from_compiled(exe) if with_costs else SegmentCost()
            )
        except Exception:  # noqa: BLE001 — fall back to plain jit dispatch
            jfns.append(jfn)
            part_costs.append(SegmentCost())

    best_ms: list[float] = [float("inf")] * len(fn_parts)
    for _ in range(max(1, passes)):
        for i, part in enumerate(fn_parts):
            sec = _timed(
                jfns[i], part.make_carry, iters=iters, warmup=warmup,
                repeats=repeats,
            )
            best_ms[i] = min(best_ms[i], sec * 1e3)

    out: list[SegmentTiming] = []
    prev_ms = 0.0
    prev_cost = SegmentCost(populated=True)
    for part, cum_ms, cost in zip(fn_parts, best_ms, part_costs):
        if part.in_step:
            seg = SegmentTiming(
                name=part.name,
                ms=max(0.0, cum_ms - prev_ms),
                cum_ms=cum_ms,
                cost=cost.minus(prev_cost) if cost.populated else cost,
                in_step=True,
            )
            prev_ms, prev_cost = cum_ms, (cost if cost.populated else prev_cost)
        else:
            seg = SegmentTiming(
                name=part.name, ms=cum_ms, cum_ms=cum_ms, cost=cost,
                in_step=False,
            )
        out.append(seg)
    return out


# -- generic train-step ladder (any loss_fn) ---------------------------------


def _inject_first_leaf(tree, tok: jax.Array):
    """Chain link for arbitrary pytrees: fold a zero-valued function of
    the rung's result into element 0 of the first leaf."""
    leaves, treedef = jax.tree.flatten(tree)
    l0 = leaves[0]
    leaves[0] = l0.at[(0,) * l0.ndim].add((tok * 0).astype(l0.dtype))
    return jax.tree.unflatten(treedef, leaves)


def generic_train_segments(
    loss_fn: Callable,
    optimizer,
    state,
    batch,
    *,
    step_body: Optional[Callable] = None,
    iters: int = 6,
    warmup: int = 2,
) -> tuple[list[FnPart], Callable]:
    """Coarse model-agnostic ladder for any ``make_train_step`` program:
    forward -> +backward -> +optimizer-update. ``loss_fn(params, batch)``
    returns a scalar or (loss, weight); ``step_body`` (the un-jitted
    step, when available) is used as the final rung so the ladder total
    telescopes to the real program."""
    import optax

    def scalar_loss(p):
        out = loss_fn(p, batch)
        return out[0] if isinstance(out, (tuple, list)) else out

    def mk_params():
        return jax.tree.map(jnp.copy, state.params)

    def mk_state():
        return jax.tree.map(jnp.copy, state)

    def fwd(p):
        return _inject_first_leaf(p, scalar_loss(p))

    def bwd(p):
        loss, grads = jax.value_and_grad(scalar_loss)(p)
        return _inject_first_leaf(p, loss + optax.global_norm(grads))

    if step_body is not None:
        def full(st):
            new_state, _ = step_body(st, batch)
            return new_state
    else:
        def full(st):
            loss, grads = jax.value_and_grad(scalar_loss)(st.params)
            updates, opt_state = optimizer.update(grads, st.opt_state, st.params)
            params = optax.apply_updates(st.params, updates)
            return dataclasses.replace(
                st, params=_inject_first_leaf(params, loss),
                opt_state=opt_state, step=st.step + 1,
            )

    parts = [
        FnPart("forward", fwd, mk_params),
        FnPart("backward", bwd, mk_params),
        FnPart("optimizer_update", full, mk_state, donate=True),
    ]

    def whole_fn(*, iters_=iters, warmup_=warmup, repeats_=3) -> float:
        return 1e3 * chained_seconds(
            full, mk_state, iters=iters_, warmup=warmup_, repeats=repeats_,
            donate=True,
        )

    return parts, whole_fn


# -- llama train-step ladder -------------------------------------------------


def _inject(params: dict, tok: jax.Array) -> dict:
    """Chain link: fold a zero-valued function of this iteration's result
    into the embedding row every rung reads first."""
    emb = params["embed"]
    return {**params, "embed": emb.at[0, 0].add((tok * 0).astype(emb.dtype))}


@register_segments("train_step")
def train_step_segments(
    config,
    params,
    batch: dict,
    optimizer,
    *,
    iters: int = 6,
    warmup: int = 2,
) -> tuple[list[FnPart], Callable]:
    """Ladder for one llama train step. Returns (parts, whole_fn) where
    ``whole_fn()`` measures the REAL jitted train step (train.step.
    make_train_step) with the same chained runner — the reference the
    ladder's telescoped total is checked against."""
    import optax

    from ray_tpu.models import llama
    from ray_tpu.nn.layers import (
        apply_rope,
        fused_cross_entropy_loss,
        rms_norm,
        rope_frequencies,
        swiglu,
    )
    from ray_tpu.ops.attention import attention
    from ray_tpu.train.step import TrainState, make_train_step

    c = config
    tokens = batch["tokens"]
    B, S = tokens.shape
    positions = jnp.arange(S, dtype=jnp.int32)

    def mk_params():
        # a REAL copy: the real-step reference and the optimizer rung
        # donate their carries, and a donated buffer shared with the
        # caller's params would poison every later rung (and the caller)
        return jax.tree.map(jnp.copy, params)

    def l0_embed(p):
        h = p["embed"].astype(c.dtype)[tokens]
        return _inject(p, _token(h))

    def _ln_block(h, lp, with_attn: bool):
        x = rms_norm(h, lp["ln1"], c.rms_eps)
        if with_attn:
            hd = c.head_dim
            q = jnp.einsum("bsd,dh->bsh", x, lp["wq"].astype(x.dtype)).reshape(
                B, S, c.n_heads, hd
            )
            k = jnp.einsum("bsd,dh->bsh", x, lp["wk"].astype(x.dtype)).reshape(
                B, S, c.n_kv_heads, hd
            )
            v = jnp.einsum("bsd,dh->bsh", x, lp["wv"].astype(x.dtype)).reshape(
                B, S, c.n_kv_heads, hd
            )
            q = apply_rope(q, cos, sin, positions)
            k = apply_rope(k, cos, sin, positions)
            o = attention(q, k, v, causal=True, impl=c.attention_impl)
            o = jnp.einsum(
                "bsh,hd->bsd",
                o.reshape(B, S, c.n_heads * hd),
                lp["wo"].astype(x.dtype),
            )
            h = h + o
        else:
            # keep the norm alive without attention: a zero-free epsilon
            # mix (0 * x would let XLA fold the whole norm away)
            h = h + x * jnp.asarray(1e-6, x.dtype)
        x2 = rms_norm(h, lp["ln2"], c.rms_eps)
        return h + x2 * jnp.asarray(1e-6, x2.dtype)

    cos, sin = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)

    def l1_ln_residual(p):
        h = p["embed"].astype(c.dtype)[tokens]
        h, _ = jax.lax.scan(
            lambda h, lp: (_ln_block(h, lp, with_attn=False), None),
            h, p["layers"],
        )
        h = rms_norm(h, p["final_norm"], c.rms_eps)
        return _inject(p, _token(h))

    def l2_attention(p):
        h = p["embed"].astype(c.dtype)[tokens]
        h, _ = jax.lax.scan(
            lambda h, lp: (_ln_block(h, lp, with_attn=True), None),
            h, p["layers"],
        )
        h = rms_norm(h, p["final_norm"], c.rms_eps)
        return _inject(p, _token(h))

    def l3_mlp(p):
        h = llama.hidden_states(p, tokens, c)
        return _inject(p, _token(h))

    def l4_loss(p):
        loss, _ = llama.loss_and_weight_fn(p, batch, c)
        return _inject(p, loss)

    def loss_for_grad(p):
        return llama.loss_and_weight_fn(p, batch, c)

    # -- backward split: three cumulative grad rungs -------------------------
    # stop_gradient changes d/dp and never the primal, so every rung below
    # runs the identical forward and each rung's backward is a strict
    # superset of the previous one's. Telescoping then prices ce_bwd
    # (lm-head + fused-CE backward), +mlp_bwd (MLP/norm/residual/embed
    # backward), +attention_bwd (qkv/rope/attention/wo backward — the rest).
    seg_ids = batch.get("segment_ids")
    bwd_positions = llama.packed_positions(seg_ids, S)

    def _scoped_loss(p, h):
        return fused_cross_entropy_loss(
            h, llama.output_weight(p), batch["targets"], batch.get("mask")
        )

    def _grad_rung(scoped_loss):
        def rung(p):
            (loss, _), grads = jax.value_and_grad(scoped_loss, has_aux=True)(p)
            # global_norm consumes every grad leaf (keeps the scoped
            # backward alive) and is work the real step does too
            return _inject(p, loss + optax.global_norm(grads))
        return rung

    def loss_ce_scope(p):
        # gradient reaches only the lm-head/CE (tied embedding included
        # via output_weight); the trunk forward still runs, detached
        h = jax.lax.stop_gradient(
            llama.hidden_states(p, tokens, c, segment_ids=seg_ids)
        )
        return _scoped_loss(p, h)

    def _block_mlp_scope(h, lp):
        # mirrors llama._block exactly (identical primal) with the
        # attention branch detached after the wo projection: gradient
        # reaches the MLP, ln2, residual spine and embedding — not
        # qkv/rope/attention/wo (those price into attention_bwd)
        x = rms_norm(h, lp["ln1"], c.rms_eps)
        hd = c.head_dim
        q = jnp.einsum("bsd,dh->bsh", x, lp["wq"].astype(x.dtype)).reshape(
            B, S, c.n_heads, hd
        )
        k = jnp.einsum("bsd,dh->bsh", x, lp["wk"].astype(x.dtype)).reshape(
            B, S, c.n_kv_heads, hd
        )
        v = jnp.einsum("bsd,dh->bsh", x, lp["wv"].astype(x.dtype)).reshape(
            B, S, c.n_kv_heads, hd
        )
        q = apply_rope(q, cos, sin, bwd_positions)
        k = apply_rope(k, cos, sin, bwd_positions)
        o = attention(
            q, k, v, causal=True, segment_ids=seg_ids, impl=c.attention_impl
        )
        o = jax.ad_checkpoint.checkpoint_name(o, "attn_out")
        o = jnp.einsum(
            "bsh,hd->bsd", o.reshape(B, S, c.n_heads * hd),
            lp["wo"].astype(x.dtype),
        )
        h = h + jax.lax.stop_gradient(o)
        x2 = rms_norm(h, lp["ln2"], c.rms_eps)
        return h + swiglu(x2, lp["w_gate"], lp["w_up"], lp["w_down"])

    def loss_mlp_scope(p):
        h = p["embed"].astype(c.dtype)[tokens]
        blk = _block_mlp_scope
        if c.remat:
            # mirror hidden_states' remat wrapping so this rung prices the
            # same rematerialized backward the real step runs
            if c.remat_policy == "dots":
                blk = jax.checkpoint(
                    blk,
                    policy=jax.checkpoint_policies.save_from_both_policies(
                        jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
                        jax.checkpoint_policies.save_only_these_names(
                            "attn_out", "attn_lse"
                        ),
                    ),
                )
            else:
                blk = jax.checkpoint(blk)
        h, _ = jax.lax.scan(
            lambda carry, lp: (blk(carry, lp), None), h, p["layers"]
        )
        h = rms_norm(h, p["final_norm"], c.rms_eps)
        return _scoped_loss(p, h)

    l5a_ce_bwd = _grad_rung(loss_ce_scope)
    l5b_mlp_bwd = _grad_rung(loss_mlp_scope)
    l5c_attention_bwd = _grad_rung(loss_for_grad)

    def mk_state():
        return TrainState.create(mk_params(), optimizer)

    def l6_optimizer(state):
        (loss, _), grads = jax.value_and_grad(loss_for_grad, has_aux=True)(
            state.params
        )
        updates, opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        grad_norm = optax.global_norm(grads)
        return TrainState(
            params=_inject(new_params, loss + grad_norm),
            opt_state=opt_state,
            step=state.step + 1,
        )

    parts = [
        FnPart("embed", l0_embed, mk_params),
        FnPart("ln_residual", l1_ln_residual, mk_params),
        FnPart("attention", l2_attention, mk_params),
        FnPart("mlp", l3_mlp, mk_params),
        FnPart("lm_head_loss", l4_loss, mk_params),
        FnPart("ce_bwd", l5a_ce_bwd, mk_params),
        FnPart("mlp_bwd", l5b_mlp_bwd, mk_params),
        FnPart("attention_bwd", l5c_attention_bwd, mk_params),
        FnPart("optimizer_update", l6_optimizer, mk_state, donate=True),
    ]

    real_step = make_train_step(
        lambda p, b: llama.loss_and_weight_fn(p, b, c), optimizer
    )

    def whole_fn(*, iters_=iters, warmup_=warmup, repeats_=3) -> float:
        """Per-step ms of the real jitted train step, chained."""
        return 1e3 * chained_seconds(
            lambda st: real_step(st, batch)[0], mk_state,
            iters=iters_, warmup=warmup_, repeats=repeats_, prejitted=True,
        )

    return parts, whole_fn


# -- allreduce-overlap probe -------------------------------------------------


def allreduce_overlap_segments(
    config,
    params,
    batch: dict,
    *,
    iters: int = 6,
    warmup: int = 2,
    repeats: int = 3,
) -> tuple[list[SegmentTiming], Optional[float]]:
    """Standalone probe: how much of the gradient all-reduce hides behind
    the backward pass it is scheduled with?

    Three chained measurements — t_bwd (backward alone), t_bwd_ar
    (backward + psum of every grad leaf over a ``dp`` mesh of all local
    devices, one program so XLA may overlap), t_ar (the psum alone on
    grad-shaped buffers). What the schedule failed to hide is
    ``exposed = max(0, t_bwd_ar - t_bwd)``; the overlap ratio is
    ``(t_ar - exposed) / t_ar``.

    Honesty: with one device (tier-1 CPU) the psum lowers to ~a copy and
    t_ar sits at the timing noise floor — the ratio is then reported as
    None, not a fabricated 1.0. The number only means something on a
    multi-chip mesh.

    Returns ``(segments, overlap_ratio)``: two ``in_step=False``
    SegmentTimings ("allreduce" = t_ar, "allreduce_exposed" = exposed)
    that never count toward step coverage.
    """
    import numpy as np
    import optax
    from jax.sharding import Mesh
    from jax.sharding import PartitionSpec as P

    from ray_tpu.models import llama
    from ray_tpu.parallel.sharding import shard_map_compat

    c = config
    devices = jax.devices()
    n_dev = len(devices)
    mesh = Mesh(np.asarray(devices), ("dp",))

    def allreduce(grads):
        def body(g):
            # mean-allreduce, the DP gradient exchange: psum then scale
            return jax.tree.map(
                lambda x: jax.lax.psum(x, "dp") / n_dev, g
            )

        return shard_map_compat(
            body, mesh=mesh, in_specs=P(), out_specs=P()
        )(grads)

    def mk_params():
        return jax.tree.map(jnp.copy, params)

    def loss_for_grad(p):
        return llama.loss_and_weight_fn(p, batch, c)

    def bwd(p):
        (loss, _), grads = jax.value_and_grad(loss_for_grad, has_aux=True)(p)
        return _inject(p, loss + optax.global_norm(grads))

    def bwd_ar(p):
        (loss, _), grads = jax.value_and_grad(loss_for_grad, has_aux=True)(p)
        grads = allreduce(grads)
        return _inject(p, loss + optax.global_norm(grads))

    def ar_only(g):
        # grads share params' pytree/shapes, so param buffers stand in;
        # chaining through the first leaf keeps every psum live
        g2 = allreduce(g)
        return _inject_first_leaf(g2, _token(jax.tree.leaves(g2)[0]))

    t_bwd = 1e3 * chained_seconds(
        bwd, mk_params, iters=iters, warmup=warmup, repeats=repeats
    )
    t_bwd_ar = 1e3 * chained_seconds(
        bwd_ar, mk_params, iters=iters, warmup=warmup, repeats=repeats
    )
    t_ar = 1e3 * chained_seconds(
        ar_only, mk_params, iters=iters, warmup=warmup, repeats=repeats
    )

    exposed = max(0.0, t_bwd_ar - t_bwd)
    # ~10us: below the chained-timer's resolving power the psum cost is
    # indistinguishable from noise and any ratio would be an invention;
    # likewise a single device has no communication to overlap — the
    # one-device psum prices the grad-scaling copy, not an exchange
    noise_floor_ms = 0.01
    if n_dev < 2 or t_ar <= noise_floor_ms:
        ratio: Optional[float] = None
    else:
        ratio = max(0.0, min(1.0, (t_ar - exposed) / t_ar))

    segments = [
        SegmentTiming(name="allreduce", ms=t_ar, cum_ms=t_ar, in_step=False),
        SegmentTiming(
            name="allreduce_exposed", ms=exposed, cum_ms=t_bwd_ar,
            in_step=False,
        ),
    ]
    return segments, ratio


# -- decode-step ladder ------------------------------------------------------


@register_segments("decode_step")
def decode_step_segments(
    config,
    params,
    *,
    batch_size: int = 4,
    context_len: int = 32,
    block_size: int = 16,
    attn_impl: str = "auto",
    sample_mode: str = "full",
    iters: int = 8,
    warmup: int = 2,
    include_prefill: bool = True,
) -> tuple[list[FnPart], Callable]:
    """Ladder for one decode step of the serving engine: embed ->
    +qkv/rope -> +KV-write -> +KV-read (paged attention) -> +out-proj/MLP
    -> +lm-head (decode matmul) -> +sampling. Returns (parts, sync_fn):
    ``sync_fn()`` measures the full rung with a PER-ITERATION host fence,
    whose delta vs the chained run is the host-sync segment."""
    from ray_tpu.llm.sampling import sample_tokens
    from ray_tpu.models.llama_decode import init_cache
    from ray_tpu.nn.layers import apply_rope, rms_norm, rope_frequencies, swiglu
    from ray_tpu.ops.paged_attention import paged_attention

    c = config
    B = batch_size
    ctx = min(context_len, c.max_seq - 1)
    blocks_per_seq = -(-(ctx + 1) // block_size)
    num_slots = B * blocks_per_seq * block_size

    block_tables = jnp.arange(B * blocks_per_seq, dtype=jnp.int32).reshape(
        B, blocks_per_seq
    )
    context_lens = jnp.full((B,), ctx + 1, jnp.int32)
    positions = jnp.full((B,), ctx, jnp.int32)
    pos2 = positions[:, None]
    slot_mapping = (
        block_tables[jnp.arange(B), positions // block_size] * block_size
        + positions % block_size
    )
    temps = jnp.ones((B,), jnp.float32)
    top_ks = jnp.full((B,), 8, jnp.int32)
    top_ps = jnp.full((B,), 0.9, jnp.float32)
    keys = jax.vmap(jax.random.key)(jnp.arange(B, dtype=jnp.uint32))
    cos, sin = rope_frequencies(c.head_dim, c.max_seq, c.rope_theta)
    hd = c.head_dim
    # stop-mask probe constants (the pipelined chunk's in-graph stop
    # ladder, llm/pipeline.py): a 2-wide stop set, per-row budgets, an
    # all-live done mask — representative shapes, never actually firing
    sm_stop_ids = jnp.full((B, 2), -1, jnp.int32).at[:, 0].set(5)
    sm_max_toks = jnp.full((B,), 1 << 20, jnp.int32)
    sm_starts = jnp.zeros((B,), jnp.int32)
    sm_done = jnp.zeros((B,), bool)
    sm_stop_eos = jnp.ones((B,), bool)

    def _stop_mask_epilogue(nxt, lp_):
        """The per-step stop-ladder math the pipelined decode chunk
        runs in-graph: stop-set match + EOS + budget/wall folds + the
        emitted-count update + output masking."""
        hit = jnp.any(sm_stop_ids == nxt[:, None], axis=-1)
        dn = sm_done | hit | (sm_stop_eos & (nxt == 2))
        dn = dn | ((sm_starts + 1) >= sm_max_toks)
        dn = dn | (jnp.full((B,), ctx + 2, jnp.int32) >= c.max_seq)
        ne = (~dn).astype(jnp.int32)
        nxt = jnp.where(dn, 0, nxt)   # output masking
        lp_ = jnp.where(dn, 0.0, lp_)
        return nxt, lp_, _token(dn) + _token(ne)

    def mk_carry():
        cache = init_cache(c, num_slots, trash_slots=block_size)
        toks = (jnp.arange(B, dtype=jnp.int32) + 1) % c.vocab_size
        return (toks, cache)

    # rung order — each feature requires everything before it (the
    # variant body references locals like `q`/`o`/`logits` produced by
    # the earlier features, so a non-cumulative set would NameError at
    # trace time deep inside the scan)
    _ORDER = ("qkv", "write", "attn", "mlp", "head", "sample", "mask")

    def _variant(parts_on: frozenset):
        on = [f for f in _ORDER if f in parts_on]
        assert set(parts_on) <= set(_ORDER) and on == list(_ORDER[: len(on)]), (
            f"decode ladder features must be a cumulative prefix of "
            f"{_ORDER}, got {sorted(parts_on)}"
        )

        def fn(carry):
            toks, cache = carry
            h = params["embed"].astype(c.dtype)[toks][:, None]  # [B, 1, D]
            acc = _token(h)

            def layer_step(lcarry, xs):
                h, acc = lcarry
                lp, kc, vc = xs
                if "qkv" in parts_on:
                    x = rms_norm(h, lp["ln1"], c.rms_eps)
                    q = jnp.einsum(
                        "bsd,dh->bsh", x, lp["wq"].astype(x.dtype)
                    ).reshape(B, 1, c.n_heads, hd)
                    k = jnp.einsum(
                        "bsd,dh->bsh", x, lp["wk"].astype(x.dtype)
                    ).reshape(B, 1, c.n_kv_heads, hd)
                    v = jnp.einsum(
                        "bsd,dh->bsh", x, lp["wv"].astype(x.dtype)
                    ).reshape(B, 1, c.n_kv_heads, hd)
                    q = apply_rope(q, cos, sin, pos2)
                    k = apply_rope(k, cos, sin, pos2)
                    if "write" not in parts_on:
                        acc = acc + _token(k) + _token(v)
                if "write" in parts_on:
                    kc = kc.at[:, slot_mapping].set(
                        k[:, 0].swapaxes(0, 1).astype(kc.dtype)
                    )
                    vc = vc.at[:, slot_mapping].set(
                        v[:, 0].swapaxes(0, 1).astype(vc.dtype)
                    )
                if "attn" in parts_on:
                    o = paged_attention(
                        q[:, 0], kc, vc, block_tables, context_lens,
                        block_size=block_size, impl=attn_impl,
                    )[:, None]
                    if "mlp" not in parts_on:
                        acc = acc + _token(o)
                elif "qkv" in parts_on:
                    acc = acc + _token(q)
                if "mlp" in parts_on:
                    h = h + jnp.einsum(
                        "bsh,hd->bsd",
                        o.reshape(B, 1, c.n_heads * hd),
                        lp["wo"].astype(o.dtype),
                    )
                    x2 = rms_norm(h, lp["ln2"], c.rms_eps)
                    h = h + swiglu(x2, lp["w_gate"], lp["w_up"], lp["w_down"])
                return (h, acc), (kc, vc)

            (h, acc), (nk, nv) = jax.lax.scan(
                layer_step, (h, acc), (params["layers"], cache["k"], cache["v"])
            )
            new_cache = {"k": nk, "v": nv}
            if "head" in parts_on:
                hf = rms_norm(h[:, 0], params["final_norm"], c.rms_eps)
                w_out = params.get("lm_head", None)
                if w_out is None:
                    w_out = params["embed"].T
                logits = jnp.einsum(
                    "bd,dv->bv", hf, w_out.astype(c.dtype)
                ).astype(jnp.float32)
                acc = acc + _token(logits[:, 0])
            if "sample" in parts_on:
                step_keys = jax.vmap(jax.random.fold_in)(keys, toks)
                nxt, lp_ = sample_tokens(
                    logits, temps, top_ks, top_ps, step_keys, mode=sample_mode
                )
                acc = acc + _token(lp_)
                if "mask" in parts_on:
                    nxt, lp_, tok_m = _stop_mask_epilogue(nxt, lp_)
                    acc = acc + tok_m
            else:
                nxt = toks
            nxt = (nxt + (acc * 0).astype(jnp.int32)) % c.vocab_size
            return (nxt, new_cache)

        return fn

    ladder = [
        ("embed", frozenset()),
        ("qkv_rope", frozenset({"qkv"})),
        ("kv_write", frozenset({"qkv", "write"})),
        ("kv_read_attn", frozenset({"qkv", "write", "attn"})),
        ("block_mlp", frozenset({"qkv", "write", "attn", "mlp"})),
        ("lm_head", frozenset({"qkv", "write", "attn", "mlp", "head"})),
        ("sampling", frozenset({"qkv", "write", "attn", "mlp", "head", "sample"})),
        ("stop_mask", frozenset(_ORDER)),
    ]
    parts = [
        FnPart(name, _variant(on), mk_carry, donate=True)
        for name, on in ladder
    ]

    if include_prefill:
        from ray_tpu.models.llama_decode import prefill

        S_pf = min(max(16, 1 << (max(1, ctx - 1)).bit_length()), c.max_seq)
        pf_tokens = jnp.ones((B, S_pf), jnp.int32)
        pf_positions = jnp.tile(jnp.arange(S_pf, dtype=jnp.int32), (B, 1))
        pf_blocks = -(-S_pf // block_size)
        pf_bt = jnp.arange(B * pf_blocks, dtype=jnp.int32).reshape(B, pf_blocks)
        offs = jnp.arange(S_pf, dtype=jnp.int32)
        pf_slots = (
            pf_bt[:, offs // block_size] * block_size + offs % block_size
        )
        pf_lens = jnp.full((B,), S_pf, jnp.int32)

        def mk_pf_carry():
            return init_cache(c, B * pf_blocks * block_size,
                              trash_slots=block_size)

        def pf_fn(cache):
            logits, new_cache = prefill(
                params, pf_tokens, pf_positions, pf_lens, pf_slots, pf_bt,
                pf_lens, cache, c, block_size=block_size,
            )
            k = new_cache["k"]
            return {
                **new_cache,
                "k": k.at[0, 0, 0, 0].add((_token(logits) * 0).astype(k.dtype)),
            }

        parts.append(
            FnPart(f"prefill_s{S_pf}", pf_fn, mk_pf_carry, donate=True,
                   in_step=False)
        )

    # mixed ragged probes (r24, in_step=False like the prefill probe):
    # a packed batch where half the rows serve a Qm-token prefill chunk
    # and half decode — the ONE-dispatch mixed step the mixed_batch
    # engine runs. `ragged_attention` prices the kernel alone;
    # `mixed_step` the full packed program (llama_decode.mixed_step).
    Qm = min(16, max(2, ctx))
    _q_lens = [Qm if i < (B + 1) // 2 else 1 for i in range(B)]
    Tm = sum(_q_lens)
    _cu = [0]
    for ql in _q_lens:
        _cu.append(_cu[-1] + ql)
    mx_cu = jnp.asarray(_cu, jnp.int32)
    _pos = []
    for ql in _q_lens:
        _pos.extend(range(ctx + 1 - ql, ctx + 1))
    mx_positions = jnp.asarray(_pos, jnp.int32)
    _row = []
    for i, ql in enumerate(_q_lens):
        _row.extend([i] * ql)
    _row = jnp.asarray(_row, jnp.int32)
    mx_slots = (
        block_tables[_row, mx_positions // block_size] * block_size
        + mx_positions % block_size
    )
    mx_tokens = jnp.ones((Tm,), jnp.int32)
    mx_q = jax.random.normal(
        jax.random.key(7), (Tm, c.n_heads, hd), c.dtype
    )

    def mk_mx_carry():
        return init_cache(c, num_slots, trash_slots=block_size)

    def ra_fn(cache):
        from ray_tpu.ops.ragged import ragged_attention

        o = ragged_attention(
            mx_q, cache["k"][0], cache["v"][0], block_tables, mx_cu,
            context_lens, block_size=block_size, max_q_len=Qm,
            impl=attn_impl,
        )
        k = cache["k"]
        return {
            **cache,
            "k": k.at[0, 0, 0, 0].add((_token(o) * 0).astype(k.dtype)),
        }

    def mx_fn(cache):
        from ray_tpu.models.llama_decode import mixed_step

        logits, new_cache = mixed_step(
            params, mx_tokens, mx_positions, mx_slots, block_tables,
            mx_cu, context_lens, cache, c, block_size=block_size,
            max_q_len=Qm, attn_impl=attn_impl,
        )
        k = new_cache["k"]
        return {
            **new_cache,
            "k": k.at[0, 0, 0, 0].add((_token(logits) * 0).astype(k.dtype)),
        }

    parts.append(
        FnPart("ragged_attention", ra_fn, mk_mx_carry, donate=True,
               in_step=False)
    )
    parts.append(
        FnPart("mixed_step", mx_fn, mk_mx_carry, donate=True,
               in_step=False)
    )

    def real_step(carry):
        """The REFERENCE program: llama_decode.decode_step + the jitted
        sampler + the pipelined stop-mask epilogue — the same per-step
        composition LLMEngine dispatches per decode round trip.
        Independent of the ladder's reconstruction, so coverage
        actually measures ladder fidelity."""
        from ray_tpu.models.llama_decode import decode_step

        toks, cache = carry
        logits, new_cache = decode_step(
            params, toks, positions, slot_mapping, block_tables,
            context_lens, cache, c, block_size=block_size,
            attn_impl=attn_impl,
        )
        step_keys = jax.vmap(jax.random.fold_in)(keys, toks)
        nxt, lp_ = sample_tokens(
            logits, temps, top_ks, top_ps, step_keys, mode=sample_mode
        )
        nxt, lp_, tok_m = _stop_mask_epilogue(nxt, lp_)
        nxt = (nxt + ((_token(lp_) + tok_m) * 0).astype(jnp.int32)) % c.vocab_size
        return (nxt, new_cache)

    def whole_fn(*, iters_=iters, warmup_=warmup, repeats_=3):
        """(chained_ms, synced_ms, pipelined_ms) of the real decode-step
        program: chained = pure device step; synced = a host fence every
        iteration (what one-token-per-sync serving pays); pipelined =
        double-buffered fencing (dispatch step N+1, THEN fence step N —
        what the async pipelined engine pays). synced - chained is the
        host_sync segment; synced - pipelined is the host_overlap
        saving the r16 pipelined path recovers."""
        jfn = jax.jit(
            real_step,
            donate_argnums=(0,) if _effective_donate(True) else (),
        )
        chained = _timed(jfn, mk_carry, iters=iters_, warmup=warmup_,
                         repeats=repeats_)
        synced = _timed(jfn, mk_carry, iters=iters_, warmup=warmup_,
                        repeats=repeats_, fence_each=True)
        # the overlap probe must NOT donate: the lag-1 fence reads a
        # carry the next dispatch has already consumed
        jfn_nd = jax.jit(real_step)
        pipelined = _timed_pipelined(jfn_nd, mk_carry, iters=iters_,
                                     warmup=warmup_, repeats=repeats_)
        return chained * 1e3, synced * 1e3, pipelined * 1e3

    return parts, whole_fn


# -- speculative-decode ladder ------------------------------------------------


@register_segments("spec_decode_step")
def spec_decode_segments(
    config,
    params,
    spec,
    *,
    batch_size: int = 4,
    context_len: int = 32,
    block_size: int = 16,
    iters: int = 6,
    warmup: int = 2,
) -> tuple[list[FnPart], Callable]:
    """Ladder for one SPECULATIVE decode round of the serving engine:
    draft (host n-gram lookup) -> +verify (one batched k+1-token pass
    through the paged prefill path) -> +accept (distribution-preserving
    acceptance/rejection) -> +kv_rollback (host block truncate/refill).

    Rungs mix host and device work, so every part is ``prejitted`` (the
    device pieces are jitted inside; cost-model fields stay empty and the
    segments classify unknown-bound — coverage is still measured against
    an independently-timed straight-line composition, which is the
    honesty property the regression gate guards). Histories are
    periodic, so the prompt-lookup drafter proposes a full k every round
    and the verify/accept rungs exercise their real shapes."""
    from ray_tpu.llm.kv_cache import BlockAllocator, SequenceBlocks
    from ray_tpu.llm.spec.accept import accept_draft
    from ray_tpu.models.llama_decode import init_cache, verify_tokens

    import numpy as np

    c = config
    B = batch_size
    k = spec.num_draft_tokens
    K1 = k + 1
    ctx = min(context_len, c.max_seq - K1 - 1)
    blocks_per_seq = -(-(ctx + K1 + 1) // block_size)
    num_blocks = B * blocks_per_seq + B  # headroom for the rollback churn
    num_slots = num_blocks * block_size

    # the CONFIGURED drafter, not a hardcoded lookup: with
    # method='draft_model' the draft rung must time the draft model's
    # prefill+decode (the dominant drafting cost), or the report would
    # attribute the wrong mechanism while meta claims spec_method
    drafter = spec.build_drafter(c)
    rng = np.random.default_rng(0)
    histories = []
    for _ in range(B):
        pat = rng.integers(3, c.vocab_size - 1, size=4).tolist()
        histories.append((pat * (ctx // 4 + 1))[:ctx])

    allocator = BlockAllocator(num_blocks, block_size)
    seqs = []
    for _ in range(B):
        s = SequenceBlocks(allocator)
        s.ensure_capacity(ctx + K1)
        s.num_tokens = ctx
        seqs.append(s)
    bt_w = max(len(s.blocks) for s in seqs)
    bt = np.zeros((B, bt_w), np.int32)
    for i, s in enumerate(seqs):
        bt[i, : len(s.blocks)] = s.blocks
    bt = jnp.asarray(bt)
    cache = init_cache(c, num_slots, trash_slots=block_size)

    # knobs with filtering active, matching the decode ladder's sampler
    # probe — mode "sample" then measures the exact-filter accept path
    # (the engine derives the cheaper categorical/greedy modes itself)
    temps = jnp.ones((B,), jnp.float32)
    top_ks = jnp.full((B,), 8, jnp.int32)
    top_ps = jnp.full((B,), 0.9, jnp.float32)
    keys = jax.vmap(jax.random.key)(jnp.arange(B, dtype=jnp.uint32))

    jverify = jax.jit(
        lambda t, p, sm, cl, acc: verify_tokens(
            params, t + (acc * 0).astype(jnp.int32), p, sm, bt, cl, cache,
            c, block_size=block_size,
        )[0]
    )

    def _draft():
        return [drafter.propose(str(i), histories[i], k) for i in range(B)]

    def _build(drafts):
        tokens = np.zeros((B, K1), np.int32)
        positions = np.zeros((B, K1), np.int32)
        slots = np.full((B, K1), num_slots, np.int32)
        ctx_lens = np.zeros(B, np.int32)
        d_toks = np.zeros((B, k), np.int32)
        d_lens = np.zeros(B, np.int32)
        for i, d in enumerate(drafts):
            row = [histories[i][-1]] + d
            tokens[i, : len(row)] = row
            positions[i, : len(row)] = np.arange(ctx - 1, ctx - 1 + len(row))
            for j in range(len(row)):
                slots[i, j] = seqs[i].slot(ctx - 1 + j)
            ctx_lens[i] = ctx + len(d)
            d_toks[i, : len(d)] = d
            d_lens[i] = len(d)
        return tokens, positions, slots, ctx_lens, d_toks, d_lens

    def r_draft(acc):
        drafts = _draft()
        return acc + 0.0 * float(len(drafts[0]))

    def r_verify(acc):
        t, p, sm, cl, _, _ = _build(_draft())
        logits = jverify(jnp.asarray(t), jnp.asarray(p), jnp.asarray(sm),
                         jnp.asarray(cl), acc)
        return _token(logits) * 1e-30

    def r_accept(acc):
        t, p, sm, cl, dt, dl = _build(_draft())
        logits = jverify(jnp.asarray(t), jnp.asarray(p), jnp.asarray(sm),
                         jnp.asarray(cl), acc)
        out, lp, a = accept_draft(
            logits, jnp.asarray(dt), jnp.asarray(dl), temps, top_ks, top_ps,
            keys, mode="sample",
        )
        # chain on tokens+accepts only: lp legitimately contains -inf for
        # zero-probability pad columns and would NaN the chain token
        return _token(a) * 1e-30 + _token(out) * 0.0

    def r_rollback(acc):
        t, p, sm, cl, dt, dl = _build(_draft())
        logits = jverify(jnp.asarray(t), jnp.asarray(p), jnp.asarray(sm),
                         jnp.asarray(cl), acc)
        out, lp, a = accept_draft(
            logits, jnp.asarray(dt), jnp.asarray(dl), temps, top_ks, top_ps,
            keys, mode="sample",
        )
        a_host = [int(x) for x in jnp.asarray(a)]
        for i, s in enumerate(seqs):
            s.num_tokens = ctx + int(dl[i])
            s.truncate_to(ctx + a_host[i])
            s.ensure_capacity(ctx + K1)
            s.num_tokens = ctx
        return _token(a) * 1e-30

    def mk_carry():
        return jnp.zeros((), jnp.float32)

    parts = [
        FnPart("draft", r_draft, mk_carry, prejitted=True),
        FnPart("verify", r_verify, mk_carry, prejitted=True),
        FnPart("accept", r_accept, mk_carry, prejitted=True),
        FnPart("kv_rollback", r_rollback, mk_carry, prejitted=True),
    ]

    def whole_fn(*, iters_=iters, warmup_=warmup, repeats_=3) -> float:
        """Per-round ms of the straight-line draft->verify->accept->
        rollback composition (independent of the ladder variants)."""
        return 1e3 * chained_seconds(
            r_rollback, mk_carry, iters=iters_, warmup=warmup_,
            repeats=repeats_, prejitted=True,
        )

    return parts, whole_fn
