"""Static cost model: FLOPs and bytes-accessed per compiled segment.

Costs come from XLA's own compiler estimate —
``jax.jit(fn).lower(*args).compile().cost_analysis()`` — so they track
the program XLA actually emits (remat re-computation, fused epilogues,
layout copies), not a hand-derived formula. The chip-peak table turns
those counts into roofline coordinates; on CPU the nominal fallback
peaks keep the arithmetic well-defined so tier-1 tests run under
``JAX_PLATFORMS=cpu``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

# bf16 peak matmul FLOP/s and HBM bandwidth (bytes/s) by device
# generation. FLOPs numbers match bench.py's PEAK_FLOPS ladder; HBM
# figures are the published per-chip memory bandwidths.
CHIP_PEAKS = [
    # (device_kind substring, flops/s, HBM bytes/s)
    ("v5 lite", 197e12, 819e9),
    ("v5e", 197e12, 819e9),
    ("v5p", 459e12, 2765e9),
    ("v6 lite", 918e12, 1640e9),
    ("v6e", 918e12, 1640e9),
    ("v4", 275e12, 1228e9),
    ("v3", 123e12, 900e9),
]

# Nominal CPU/unknown peaks: a laptop-class core's ~1 TFLOP/s and
# ~50 GB/s memory bus. Deliberately round numbers — the CPU profile is
# for exercising the machinery, not for publishing attainment.
CPU_PEAKS = (1e12, 50e9)


@dataclasses.dataclass(frozen=True)
class ChipPeaks:
    device_kind: str
    flops: float      # peak FLOP/s
    hbm_bytes_s: float  # peak memory bandwidth, bytes/s

    @property
    def ridge_intensity(self) -> float:
        """FLOPs/byte above which the chip is compute-bound."""
        return self.flops / self.hbm_bytes_s


def chip_peaks(device=None) -> ChipPeaks:
    """Peak table lookup for a jax device (default: devices()[0])."""
    if device is None:
        import jax

        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "cpu") or "cpu"
    low = kind.lower()
    for key, fl, bw in CHIP_PEAKS:
        if key in low:
            return ChipPeaks(kind, fl, bw)
    return ChipPeaks(kind, *CPU_PEAKS)


@dataclasses.dataclass
class SegmentCost:
    """Compiler-estimated cost of one compiled program."""

    flops: float = 0.0
    bytes_accessed: float = 0.0
    populated: bool = False
    raw: Optional[dict] = None

    def minus(self, other: "SegmentCost") -> "SegmentCost":
        """Ladder difference (clamped at 0: XLA may fuse a later rung
        tighter than an earlier one)."""
        return SegmentCost(
            flops=max(0.0, self.flops - other.flops),
            bytes_accessed=max(0.0, self.bytes_accessed - other.bytes_accessed),
            populated=self.populated and other.populated,
        )


def _flatten_cost_analysis(ca: Any) -> Optional[dict]:
    """cost_analysis() shape varies by jax version: a dict, or a list of
    per-computation dicts (one per partition). Merge to one dict."""
    if ca is None:
        return None
    if isinstance(ca, dict):
        return dict(ca)
    if isinstance(ca, (list, tuple)):
        merged: dict = {}
        for entry in ca:
            if not isinstance(entry, dict):
                continue
            for k, v in entry.items():
                try:
                    merged[k] = merged.get(k, 0.0) + float(v)
                except (TypeError, ValueError):
                    continue
        return merged or None
    return None


def cost_from_compiled(compiled) -> SegmentCost:
    """Pull XLA's cost estimate from an already-compiled jax.stages
    Compiled object (never raises: a cost model must not take down the
    measurement path)."""
    try:
        raw = _flatten_cost_analysis(compiled.cost_analysis())
    except Exception:  # noqa: BLE001 — backend without cost analysis
        return SegmentCost()
    if not raw:
        return SegmentCost()
    return SegmentCost(
        flops=float(raw.get("flops", 0.0)),
        bytes_accessed=float(raw.get("bytes accessed", 0.0)),
        populated=True,
        raw=raw,
    )


def compiled_cost(fn: Callable, *args, donate_argnums=()) -> SegmentCost:
    """Lower + compile ``fn`` for ``args`` and pull XLA's cost estimate.

    Prefer cost_from_compiled when a compiled executable already exists
    (profile_segments does — compiling twice doubles a 400M-model
    profile's compile wall time for no new information).
    """
    import jax

    try:
        compiled = (
            jax.jit(fn, donate_argnums=donate_argnums).lower(*args).compile()
        )
    except Exception:  # noqa: BLE001
        return SegmentCost()
    return cost_from_compiled(compiled)
