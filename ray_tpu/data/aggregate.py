"""Aggregations for Dataset.groupby / Dataset.aggregate.

Same accumulate/merge/finalize shape as the reference
(python/ray/data/aggregate.py) so distributed two-phase aggregation
(per-block partial → cross-block merge) works over the task runtime.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.data.block import Block


@dataclasses.dataclass
class AggregateFn:
    name: str
    init: Callable[[], Any]
    accumulate_block: Callable[[Any, Block], Any]  # (acc, block) -> acc
    merge: Callable[[Any, Any], Any]
    finalize: Callable[[Any], Any] = lambda a: a


def _col(block: Block, on: Optional[str]) -> np.ndarray:
    if on is None:
        cols = list(block.columns)
        if len(cols) != 1:
            raise ValueError(f"aggregation needs on= with multiple columns {cols}")
        on = cols[0]
    return block.columns[on]


def Count() -> AggregateFn:
    return AggregateFn(
        name="count()",
        init=lambda: 0,
        accumulate_block=lambda a, b: a + b.num_rows,
        merge=lambda a, b: a + b,
    )


def _np_agg(name, npfn, on, merge, finalize=lambda a: a):
    def acc(a, block):
        col = _col(block, on)
        if len(col) == 0:
            return a
        val = npfn(col)
        return val if a is None else merge(a, val)

    return AggregateFn(
        name=f"{name}({on or ''})",
        init=lambda: None,
        accumulate_block=acc,
        merge=lambda a, b: b if a is None else (a if b is None else merge(a, b)),
        finalize=lambda a: None if a is None else finalize(a),
    )


def Sum(on: Optional[str] = None) -> AggregateFn:
    return _np_agg("sum", np.sum, on, lambda a, b: a + b)


def Min(on: Optional[str] = None) -> AggregateFn:
    return _np_agg("min", np.min, on, min)


def Max(on: Optional[str] = None) -> AggregateFn:
    return _np_agg("max", np.max, on, max)


def Mean(on: Optional[str] = None) -> AggregateFn:
    def acc(a, block):
        col = _col(block, on)
        s, n = a
        return (s + (np.sum(col) if len(col) else 0.0), n + len(col))

    return AggregateFn(
        name=f"mean({on or ''})",
        init=lambda: (0.0, 0),
        accumulate_block=acc,
        merge=lambda a, b: (a[0] + b[0], a[1] + b[1]),
        finalize=lambda a: None if a[1] == 0 else a[0] / a[1],
    )


def Std(on: Optional[str] = None, ddof: int = 1) -> AggregateFn:
    # Chan et al. parallel variance: track (n, mean, M2).
    def acc(a, block):
        col = np.asarray(_col(block, on), np.float64)
        if len(col) == 0:
            return a
        b = (len(col), float(np.mean(col)), float(np.var(col) * len(col)))
        return _merge(a, b)

    def _merge(a, b):
        if a[0] == 0:
            return b
        if b[0] == 0:
            return a
        n = a[0] + b[0]
        delta = b[1] - a[1]
        mean = a[1] + delta * b[0] / n
        m2 = a[2] + b[2] + delta * delta * a[0] * b[0] / n
        return (n, mean, m2)

    return AggregateFn(
        name=f"std({on or ''})",
        init=lambda: (0, 0.0, 0.0),
        accumulate_block=acc,
        merge=_merge,
        finalize=lambda a: None if a[0] <= ddof else float(np.sqrt(a[2] / (a[0] - ddof))),
    )
