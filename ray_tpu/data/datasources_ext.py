"""Extended datasources: TFRecord, Arrow/Feather, SQL, images, webdataset.

Reference analog: python/ray/data/_internal/datasource/ — the tfrecords,
arrow/feather, sql, image, and webdataset readers (of its 38 modules,
these are the ones a TPU training stack actually feeds from). All pure
stdlib + pyarrow + PIL; each reader yields one Block per file/shard so
the streaming executor parallelizes per-file.
"""

from __future__ import annotations

import io
import struct
from typing import Any, Callable, Optional, Sequence

import numpy as np

from ray_tpu.data.block import Block
from ray_tpu.data.datasource import Datasource, FileDatasource, ReadTask, _expand_paths


# ---------------------------------------------------------------------------
# TFRecord (the TPU-classic input format)
# ---------------------------------------------------------------------------


def _read_tfrecord_records(path: str):
    """Raw records from a TFRecord file (format: u64 length, u32 masked
    crc(length), payload, u32 masked crc(payload)); CRCs are skipped —
    corruption surfaces as a struct error, matching fast-path readers."""
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,) = struct.unpack("<Q", header[:8])
            payload = f.read(length)
            f.read(4)  # data crc
            yield payload


def _parse_tf_example(payload: bytes) -> dict:
    """Minimal tf.train.Example proto parser (features -> python values).

    Wire format: Example{1: Features{1: map<string, Feature>}} where
    Feature is one of bytes_list(1)/float_list(2)/int64_list(3). A full
    protobuf runtime is deliberately avoided (hermetic hosts)."""

    def read_varint(buf, i):
        out = shift = 0
        while True:
            b = buf[i]
            i += 1
            out |= (b & 0x7F) << shift
            if not b & 0x80:
                return out, i
            shift += 7

    def read_fields(buf):
        i = 0
        while i < len(buf):
            tag, i = read_varint(buf, i)
            field, wire = tag >> 3, tag & 7
            if wire == 2:  # length-delimited
                n, i = read_varint(buf, i)
                yield field, buf[i:i + n]
                i += n
            elif wire == 0:
                v, i = read_varint(buf, i)
                yield field, v
            elif wire == 5:
                yield field, buf[i:i + 4]
                i += 4
            elif wire == 1:
                yield field, buf[i:i + 8]
                i += 8
            else:
                raise ValueError(f"unsupported wire type {wire}")

    def parse_feature(buf):
        for field, val in read_fields(buf):
            if field == 1:  # bytes_list
                return [v for f, v in read_fields(val) if f == 1]
            if field == 2:  # float_list: packed or repeated
                floats = []
                for f, v in read_fields(val):
                    if f == 1:
                        if isinstance(v, (bytes, bytearray)) and len(v) != 4:
                            floats.extend(
                                struct.unpack(f"<{len(v)//4}f", v)
                            )
                        elif isinstance(v, (bytes, bytearray)):
                            floats.append(struct.unpack("<f", v)[0])
                        else:
                            floats.append(v)
                return floats
            if field == 3:  # int64_list
                def signed(x):  # varints are unsigned on the wire
                    return x - (1 << 64) if x >= 1 << 63 else x

                ints = []
                for f, v in read_fields(val):
                    if f == 1:
                        if isinstance(v, (bytes, bytearray)):
                            i = 0
                            while i < len(v):
                                x, i = read_varint(v, i)
                                ints.append(signed(x))
                        else:
                            ints.append(signed(v))
                return ints
        return []

    out = {}
    for field, features_buf in read_fields(payload):
        if field != 1:
            continue
        for f, entry in read_fields(features_buf):
            if f != 1:
                continue
            name = value = None
            for ef, ev in read_fields(entry):
                if ef == 1:
                    name = ev.decode()
                elif ef == 2:
                    value = parse_feature(ev)
            if name is not None:
                out[name] = value
    return out


class TFRecordDatasource(FileDatasource):
    """tf.train.Example TFRecords -> columns (single-element lists are
    scalarized, matching the reference's tfrecords reader)."""

    def _read_file(self, path: str) -> Block:
        rows = []
        for payload in _read_tfrecord_records(path):
            ex = _parse_tf_example(payload)
            rows.append({
                k: (v[0] if isinstance(v, list) and len(v) == 1 else v)
                for k, v in ex.items()
            })
        # tf.train.Example features are optional per record: union the
        # keys (missing -> None) so heterogeneous records neither crash
        # schema inference nor silently drop late-appearing features
        keys = sorted({k for r in rows for k in r})
        rows = [{k: r.get(k) for k in keys} for r in rows]
        return [Block.from_rows(rows)]


def write_tfrecord_block(block: Block, path: str) -> None:
    """Write a block as tf.train.Example TFRecords (masked CRCs zeroed —
    readers that verify CRCs should use the parquet path instead)."""

    def varint(n: int) -> bytes:
        n &= 0xFFFFFFFFFFFFFFFF  # negatives: 10-byte two's-complement varint
        out = b""
        while True:
            b = n & 0x7F
            n >>= 7
            out += bytes([b | (0x80 if n else 0)])
            if not n:
                return out

    def field(num: int, payload: bytes, wire: int = 2) -> bytes:
        return varint((num << 3) | wire) + varint(len(payload)) + payload

    def feature(value) -> bytes:
        if isinstance(value, (bytes, str)):
            raw = value.encode() if isinstance(value, str) else value
            return field(1, field(1, raw))
        arr = np.asarray(value).reshape(-1)
        if np.issubdtype(arr.dtype, np.integer):
            body = b"".join(varint(int(x)) for x in arr)
            return field(3, field(1, body))
        body = struct.pack(f"<{arr.size}f", *arr.astype(np.float32))
        return field(2, field(1, body))

    with open(path, "wb") as f:
        for row in block.iter_rows():
            entries = b"".join(
                field(1, field(1, k.encode()) + field(2, feature(v)))
                for k, v in row.items()
            )
            example = field(1, entries)
            f.write(struct.pack("<Q", len(example)) + b"\x00" * 4)
            f.write(example + b"\x00" * 4)


# ---------------------------------------------------------------------------
# Arrow IPC / Feather + interop
# ---------------------------------------------------------------------------


class ArrowDatasource(FileDatasource):
    """Arrow IPC / Feather files -> Blocks (zero-copy numpy columns where
    the types allow)."""

    def _read_file(self, path: str) -> Block:
        import pyarrow.feather as feather

        return [block_from_arrow(feather.read_table(path))]


def block_from_arrow(table) -> Block:
    cols = {}
    for name in table.column_names:
        col = table.column(name).combine_chunks()
        try:
            cols[name] = col.to_numpy(zero_copy_only=False)
        except Exception:
            cols[name] = np.asarray(col.to_pylist(), dtype=object)
    return Block(cols)


def block_to_arrow(block: Block):
    import pyarrow as pa

    return pa.table({k: pa.array(v) for k, v in block.columns.items()})


def write_arrow_block(block: Block, path: str) -> None:
    import pyarrow.feather as feather

    feather.write_feather(block_to_arrow(block), path)


# ---------------------------------------------------------------------------
# SQL (sqlite3 or any DB-API connection factory)
# ---------------------------------------------------------------------------


class SQLDatasource(Datasource):
    """One ReadTask per query: `connection_factory() -> DB-API conn`.
    (reference: ray.data.read_sql)"""

    def __init__(self, sql: str, connection_factory: Callable[[], Any],
                 parallelism_queries: Optional[Sequence[str]] = None):
        self.sql = sql
        self.factory = connection_factory
        self.queries = list(parallelism_queries or [sql])

    def get_read_tasks(self, parallelism: int) -> list[ReadTask]:
        def make(query: str):
            def read():
                conn = self.factory()
                try:
                    cur = conn.cursor()  # DB-API 2.0 (conn.execute is sqlite-only)
                    cur.execute(query)
                    names = [d[0] for d in cur.description]
                    rows = [dict(zip(names, r)) for r in cur.fetchall()]
                finally:
                    conn.close()
                return [Block.from_rows(rows)]

            return read

        return [ReadTask(make(q)) for q in self.queries]


# ---------------------------------------------------------------------------
# images
# ---------------------------------------------------------------------------


class ImageDatasource(FileDatasource):
    """Image files -> {"image": HWC uint8, "path": str} (reference:
    ray.data.read_images)."""

    def __init__(self, paths, size: Optional[tuple] = None, mode: str = "RGB"):
        super().__init__(paths)
        self.size = size
        self.mode = mode

    def _read_file(self, path: str):
        from PIL import Image

        img = Image.open(path).convert(self.mode)
        if self.size is not None:
            h, w = self.size  # reference convention (height, width)
            img = img.resize((w, h))
        arr = np.asarray(img)
        if self.size is None:
            # mixed sizes must survive Block.concat: object column
            col = np.empty(1, object)
            col[0] = arr
        else:
            col = arr[None]
        return [Block({
            "image": col,
            "path": np.asarray([path]),
        })]


# ---------------------------------------------------------------------------
# webdataset (tar shards of grouped files)
# ---------------------------------------------------------------------------


class WebDatasetDatasource(FileDatasource):
    """Tar shards where `key.ext` members group into one sample per key
    (reference: ray.data.read_webdataset). Decoding: .txt/.cls utf-8,
    .json json, image extensions via PIL, rest raw bytes."""

    IMG_EXTS = {"jpg", "jpeg", "png", "bmp", "gif", "webp"}

    def _read_file(self, path: str) -> Block:
        import json
        import tarfile

        samples: dict[str, dict] = {}
        with tarfile.open(path) as tar:
            for member in tar.getmembers():
                if not member.isfile():
                    continue
                key, dot, ext = member.name.rpartition(".")
                if not dot:
                    # extensionless member (README, LICENSE, ...): no
                    # sample key to group under — lumping them into one
                    # "" sample would cross-contaminate the shard
                    continue
                data = tar.extractfile(member).read()
                ext = ext.lower()
                if ext in ("txt", "cls"):
                    value: Any = data.decode()
                    if ext == "cls":
                        value = int(value)
                elif ext == "json":
                    value = json.loads(data)
                elif ext in self.IMG_EXTS:
                    from PIL import Image

                    value = np.asarray(Image.open(io.BytesIO(data)).convert("RGB"))
                else:
                    value = data
                samples.setdefault(key, {"__key__": key})[ext] = value
        rows = list(samples.values())
        # heterogeneous shards: union the keys (missing fields -> None) so
        # a caption-less sample doesn't KeyError the columnar build
        all_keys = sorted({k for r in rows for k in r})
        rows = [{k: r.get(k) for k in all_keys} for r in rows]
        return [Block.from_rows(rows)]
