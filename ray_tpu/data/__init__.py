"""ray_tpu.data: streaming distributed datasets (reference: python/ray/data/).

Lazy logical plans over columnar numpy blocks, executed by a pull-based
streaming executor on the task/actor runtime, terminating in
`iter_jax_batches` — prefetched, sharded device feeds for SPMD training.
"""

from ray_tpu.data.aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from ray_tpu.data.block import Block, BlockMetadata
from ray_tpu.data.dataset import (
    ActorPoolStrategy,
    Dataset,
    from_items,
    from_numpy,
    from_pandas,
    range,
    from_arrow,
    read_arrow,
    read_binary_files,
    read_csv,
    read_datasource,
    read_images,
    read_json,
    read_parquet,
    read_sql,
    read_text,
    read_tfrecords,
    read_webdataset,
)
from ray_tpu.data.datasource import Datasource, ReadTask
from ray_tpu.data.iterator import DataIterator

__all__ = [
    "ActorPoolStrategy",
    "AggregateFn",
    "Block",
    "BlockMetadata",
    "Count",
    "DataIterator",
    "Dataset",
    "Datasource",
    "Max",
    "Mean",
    "Min",
    "ReadTask",
    "Std",
    "Sum",
    "from_arrow",
    "from_items",
    "from_numpy",
    "from_pandas",
    "range",
    "read_arrow",
    "read_binary_files",
    "read_csv",
    "read_datasource",
    "read_images",
    "read_json",
    "read_parquet",
    "read_sql",
    "read_text",
    "read_tfrecords",
    "read_webdataset",
]
