"""Datasources: parallel read task generation (reference:
python/ray/data/_internal/datasource/ — 38 modules; here the core set,
each a thin ReadTask factory so reads parallelize over the task runtime).
"""

from __future__ import annotations

import csv
import dataclasses
import glob
import io
import json
import os
from typing import Any, Callable, Iterable, List, Optional

import numpy as np

from ray_tpu.data.block import ITEM_COLUMN, Block, BlockMetadata


@dataclasses.dataclass
class ReadTask:
    """A no-arg callable producing blocks, plus a size estimate for the
    optimizer. Executed remotely by the read operator."""

    fn: Callable[[], Iterable[Block]]
    estimated_rows: Optional[int] = None

    def __call__(self) -> Iterable[Block]:
        return self.fn()


class Datasource:
    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        raise NotImplementedError

    def estimated_num_rows(self) -> Optional[int]:
        return None


class RangeDatasource(Datasource):
    def __init__(self, n: int, use_column: bool = True):
        self.n = n
        self.use_column = use_column

    def estimated_num_rows(self):
        return self.n

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        parallelism = max(1, min(parallelism, self.n or 1))
        splits = np.array_split(np.arange(self.n, dtype=np.int64), parallelism)

        def make(chunk):
            return ReadTask(
                lambda: [Block({ITEM_COLUMN: chunk})], estimated_rows=len(chunk)
            )

        return [make(c) for c in splits if len(c) or parallelism == 1]


class ItemsDatasource(Datasource):
    def __init__(self, items: list):
        self.items = list(items)

    def estimated_num_rows(self):
        return len(self.items)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = len(self.items)
        parallelism = max(1, min(parallelism, n or 1))
        bounds = np.linspace(0, n, parallelism + 1).astype(int)

        def make(lo, hi):
            chunk = self.items[lo:hi]
            return ReadTask(
                lambda: [Block.from_rows(chunk)], estimated_rows=len(chunk)
            )

        return [make(lo, hi) for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo or n == 0]


class NumpyDatasource(Datasource):
    def __init__(self, arrays: dict[str, np.ndarray]):
        self.arrays = arrays

    def estimated_num_rows(self):
        return len(next(iter(self.arrays.values()))) if self.arrays else 0

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        n = self.estimated_num_rows()
        parallelism = max(1, min(parallelism, n or 1))
        bounds = np.linspace(0, n, parallelism + 1).astype(int)

        def make(lo, hi):
            chunk = {k: v[lo:hi] for k, v in self.arrays.items()}
            return ReadTask(lambda: [Block(chunk)], estimated_rows=hi - lo)

        return [make(lo, hi) for lo, hi in zip(bounds[:-1], bounds[1:]) if hi > lo or n == 0]


def _expand_paths(paths) -> list[str]:
    if isinstance(paths, str):
        paths = [paths]
    out = []
    for p in paths:
        if os.path.isdir(p):
            out.extend(sorted(glob.glob(os.path.join(p, "*"))))
        elif any(ch in p for ch in "*?["):
            out.extend(sorted(glob.glob(p)))
        else:
            out.append(p)
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


class FileDatasource(Datasource):
    """One read task per file (files are the natural parallelism unit)."""

    def __init__(self, paths):
        self.paths = _expand_paths(paths)

    def get_read_tasks(self, parallelism: int) -> List[ReadTask]:
        return [ReadTask(lambda p=p: self._read_file(p)) for p in self.paths]

    def _read_file(self, path: str) -> Iterable[Block]:
        raise NotImplementedError


class CSVDatasource(FileDatasource):
    def _read_file(self, path):
        with open(path, newline="") as f:
            rows = list(csv.DictReader(f))
        # numeric inference column-wise
        if rows:
            block = Block.from_rows(rows)
            cols = {}
            for k, v in block.columns.items():
                try:
                    cols[k] = v.astype(np.int64)
                except (ValueError, TypeError):
                    try:
                        cols[k] = v.astype(np.float64)
                    except (ValueError, TypeError):
                        cols[k] = v
            return [Block(cols)]
        return [Block({})]


class JSONDatasource(FileDatasource):
    """JSONL or a top-level JSON array per file."""

    def _read_file(self, path):
        with open(path) as f:
            text = f.read().strip()
        if not text:
            return [Block({})]
        if text.startswith("["):
            rows = json.loads(text)
        else:
            rows = [json.loads(line) for line in text.splitlines() if line.strip()]
        return [Block.from_rows(rows)]


class TextDatasource(FileDatasource):
    def _read_file(self, path):
        with open(path) as f:
            lines = [line.rstrip("\n") for line in f]
        return [Block({"text": np.array(lines, dtype=object)})]


class ParquetDatasource(FileDatasource):
    def _read_file(self, path):
        pq = _require_pyarrow_parquet()
        table = pq.read_table(path)
        return [
            Block({name: table.column(name).to_numpy(zero_copy_only=False)
                   for name in table.column_names})
        ]


class BinaryDatasource(FileDatasource):
    def _read_file(self, path):
        with open(path, "rb") as f:
            data = f.read()
        return [Block({"bytes": np.array([data], dtype=object),
                       "path": np.array([path], dtype=object)})]


def _require_pyarrow_parquet():
    try:
        import pyarrow.parquet as pq  # noqa: PLC0415
    except ImportError as e:  # pragma: no cover - env without pyarrow
        raise ImportError(
            "read_parquet/write_parquet require pyarrow, which is not "
            "installed in this environment"
        ) from e
    return pq


# -- writers (one file per block, executed as remote tasks) -----------------


def write_csv_block(block: Block, path: str) -> None:
    cols = list(block.columns)
    with open(path, "w", newline="") as f:
        w = csv.writer(f)
        w.writerow(cols)
        for i in range(block.num_rows):
            w.writerow([block.columns[c][i] for c in cols])


def write_json_block(block: Block, path: str) -> None:
    with open(path, "w") as f:
        for row in block.iter_rows():
            if not isinstance(row, dict):
                row = {ITEM_COLUMN: row}
            f.write(json.dumps({k: _json_safe(v) for k, v in row.items()}) + "\n")


def write_parquet_block(block: Block, path: str) -> None:
    pq = _require_pyarrow_parquet()
    import pyarrow as pa

    table = pa.table({k: list(v) for k, v in block.columns.items()})
    pq.write_table(table, path)


def _json_safe(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    return v
