"""Batch iteration + device feed: the Dataset → TPU boundary.

Reference: python/ray/data/iterator.py + stream_split_iterator.py. The
TPU-first piece is `iter_jax_batches`: numpy batches are `jax.device_put`
one step ahead of consumption (double-buffered host→HBM copies hide
transfer latency behind the running step), optionally placed with a
NamedSharding so each step's input is born sharded for the SPMD program.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator, Optional

import numpy as np

from ray_tpu.core import api
from ray_tpu.data.block import Batch, Block, iter_batches_from_blocks


class DataIterator:
    """One consumer's view of a block stream."""

    def __init__(self, ref_meta_iter_factory):
        self._factory = ref_meta_iter_factory

    def _iter_blocks(self) -> Iterator[Block]:
        for ref, _ in self._factory():
            yield api.get(ref)

    def iter_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        drop_last: bool = False,
        local_shuffle_buffer_size: Optional[int] = None,
        local_shuffle_seed: Optional[int] = None,
    ) -> Iterator[Batch]:
        blocks = self._iter_blocks()
        if local_shuffle_buffer_size:
            blocks = _shuffling_blocks(
                blocks, local_shuffle_buffer_size, local_shuffle_seed
            )
        for b in iter_batches_from_blocks(blocks, batch_size, drop_last=drop_last):
            yield b.to_batch()

    def iter_rows(self) -> Iterator[Any]:
        for b in self._iter_blocks():
            yield from b.iter_rows()

    def iter_jax_batches(
        self,
        *,
        batch_size: Optional[int] = 256,
        drop_last: bool = False,
        sharding=None,
        dtypes: Optional[dict] = None,
        prefetch: int = 1,
        local_shuffle_buffer_size: Optional[int] = None,
    ) -> Iterator[dict]:
        """Batches as (sharded) jax.Arrays, transferred ahead of consumption."""
        import jax

        def to_device(batch: Batch) -> dict:
            out = {}
            for k, v in batch.items():
                if dtypes and k in dtypes:
                    v = v.astype(dtypes[k])
                out[k] = jax.device_put(v, sharding) if sharding is not None else jax.device_put(v)
            return out

        it = (
            to_device(b)
            for b in self.iter_batches(
                batch_size=batch_size,
                drop_last=drop_last,
                local_shuffle_buffer_size=local_shuffle_buffer_size,
            )
        )
        yield from _prefetched(it, prefetch)

    def iter_torch_batches(
        self, *, batch_size: Optional[int] = 256, drop_last: bool = False
    ) -> Iterator[dict]:
        import torch

        for batch in self.iter_batches(batch_size=batch_size, drop_last=drop_last):
            yield {
                k: torch.from_numpy(np.ascontiguousarray(v))
                if v.dtype.kind != "O"
                else list(v)
                for k, v in batch.items()
            }


def _prefetched(it: Iterator, depth: int) -> Iterator:
    """Run `it` in a background thread, keeping `depth` items ready."""
    if depth <= 0:
        yield from it
        return
    q: queue.Queue = queue.Queue(maxsize=depth)
    DONE, ERR = object(), object()

    def pump():
        try:
            for x in it:
                q.put(x)
            q.put(DONE)
        except BaseException as e:  # noqa: BLE001 - must surface to consumer
            q.put((ERR, e))

    t = threading.Thread(target=pump, daemon=True, name="data-prefetch")
    t.start()
    while True:
        x = q.get()
        if x is DONE:
            return
        if isinstance(x, tuple) and len(x) == 2 and x[0] is ERR:
            raise x[1]
        yield x


def _shuffling_blocks(
    blocks: Iterator[Block], buffer_rows: int, seed: Optional[int]
) -> Iterator[Block]:
    """Local (non-global) shuffle: maintain a row buffer, emit random samples."""
    rng = np.random.default_rng(seed)
    buf: list[Block] = []
    buffered = 0
    for b in blocks:
        buf.append(b)
        buffered += b.num_rows
        while buffered >= 2 * buffer_rows:
            merged = Block.concat(buf)
            perm = rng.permutation(merged.num_rows)
            yield merged.take_indices(perm[:buffer_rows])
            buf = [merged.take_indices(perm[buffer_rows:])]
            buffered = buf[0].num_rows
    if buf:
        merged = Block.concat(buf)
        yield merged.take_indices(rng.permutation(merged.num_rows))


class StreamSplitIterator:
    """streaming_split(n): one producer thread feeds n consumer queues
    (reference: stream_split_iterator.py's coordinator actor; thread-mode
    runtime makes a thread + bounded queues the equivalent construct).

    One streaming pass total: each split is consumable once; a second
    iteration of an exhausted split yields nothing (instead of blocking).
    `close()` (called by e.g. JaxTrainer when the gang fails) unblocks the
    pump so unconsumed splits can't wedge the producer forever."""

    _DONE = object()

    def __init__(self, ref_meta_iter_factory, n: int, equal: bool, maxsize: int = 4):
        self._factory = ref_meta_iter_factory
        self._n = n
        self._equal = equal
        self._maxsize = maxsize
        self._lock = threading.Lock()
        self._queues: Optional[list[queue.Queue]] = None
        self._closed = threading.Event()
        self._finished = [False] * n

    def close(self) -> None:
        """Stop the pump; pending/future consumers see end-of-stream."""
        self._closed.set()

    def _ensure_started(self):
        with self._lock:
            if self._queues is not None:
                return
            self._queues = [queue.Queue(maxsize=self._maxsize) for _ in range(self._n)]
            t = threading.Thread(target=self._pump, daemon=True, name="stream-split")
            t.start()

    def _put(self, q: queue.Queue, item) -> bool:
        """Timed put loop so a stalled consumer can't wedge the pump once
        close() is called. Returns False if closed."""
        while not self._closed.is_set():
            try:
                q.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _pump(self):
        try:
            i = 0
            for ref, meta in self._factory():
                if not self._put(self._queues[i % self._n], (ref, meta)):
                    return
                i += 1
        except BaseException as e:  # noqa: BLE001
            for q in self._queues:
                self._put(q, ("__error__", e))
            return
        for q in self._queues:
            self._put(q, self._DONE)

    def split(self, idx: int) -> DataIterator:
        def factory():
            self._ensure_started()
            if self._finished[idx]:
                return
            q = self._queues[idx]
            while True:
                try:
                    item = q.get(timeout=0.2)
                except queue.Empty:
                    if self._closed.is_set():
                        self._finished[idx] = True
                        return
                    continue
                if item is self._DONE:
                    self._finished[idx] = True
                    return
                if isinstance(item, tuple) and item[0] == "__error__":
                    self._finished[idx] = True
                    raise item[1]
                yield item

        it = DataIterator(factory)
        it.splitter = self
        return it
