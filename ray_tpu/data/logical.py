"""Logical plan: what the user asked for, before physical planning.

Mirrors the reference's logical-operator layer
(python/ray/data/_internal/logical/) — a linear op chain per Dataset,
with Union/Zip referencing other chains. The streaming executor
(ray_tpu.data.executor) lowers each op to a physical operator.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

from ray_tpu.data.aggregate import AggregateFn
from ray_tpu.data.datasource import Datasource


@dataclasses.dataclass
class ComputeStrategy:
    """Tasks by default; ActorPoolStrategy pins a pool of stateful workers
    (reference: python/ray/data/_internal/compute.py)."""


@dataclasses.dataclass
class TaskPoolStrategy(ComputeStrategy):
    pass


@dataclasses.dataclass
class ActorPoolStrategy(ComputeStrategy):
    size: int = 2


@dataclasses.dataclass
class LogicalOp:
    pass


@dataclasses.dataclass
class Read(LogicalOp):
    datasource: Datasource
    parallelism: int = -1


@dataclasses.dataclass
class MapBatches(LogicalOp):
    fn: Any  # callable or callable class
    batch_size: Optional[int] = None
    compute: Optional[ComputeStrategy] = None
    fn_args: tuple = ()
    fn_kwargs: dict = dataclasses.field(default_factory=dict)
    fn_constructor_args: tuple = ()
    fn_constructor_kwargs: dict = dataclasses.field(default_factory=dict)
    num_cpus: Optional[float] = None
    zero_copy_batch: bool = True


@dataclasses.dataclass
class MapRows(LogicalOp):
    fn: Callable
    compute: Optional[ComputeStrategy] = None


@dataclasses.dataclass
class Filter(LogicalOp):
    fn: Callable
    compute: Optional[ComputeStrategy] = None


@dataclasses.dataclass
class FlatMap(LogicalOp):
    fn: Callable
    compute: Optional[ComputeStrategy] = None


@dataclasses.dataclass
class Repartition(LogicalOp):
    num_blocks: int
    shuffle: bool = False


@dataclasses.dataclass
class RandomShuffle(LogicalOp):
    seed: Optional[int] = None


@dataclasses.dataclass
class Sort(LogicalOp):
    keys: Sequence[str]
    descending: bool = False


@dataclasses.dataclass
class GroupByAggregate(LogicalOp):
    keys: Sequence[str]
    aggs: Sequence[AggregateFn]


@dataclasses.dataclass
class Limit(LogicalOp):
    n: int


@dataclasses.dataclass
class Union(LogicalOp):
    others: list  # list[LogicalPlan]


@dataclasses.dataclass
class Zip(LogicalOp):
    other: Any  # LogicalPlan


@dataclasses.dataclass
class LogicalPlan:
    ops: list[LogicalOp]

    def then(self, op: LogicalOp) -> "LogicalPlan":
        return LogicalPlan(self.ops + [op])
