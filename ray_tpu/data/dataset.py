"""Dataset: the public data API (reference: python/ray/data/dataset.py).

Lazy: every transform appends a logical op; execution happens on
iteration/consumption through the streaming executor, so pipelines
stream blocks through task/actor pools with backpressure instead of
materializing. `materialize()` pins the block list for reuse.
"""

from __future__ import annotations

import builtins
import os
from typing import Any, Callable, Iterable, Iterator, Optional, Sequence, Union

import numpy as np

from ray_tpu.core import api
from ray_tpu.data import logical as L
from ray_tpu.data.aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from ray_tpu.data.block import ITEM_COLUMN, Block, BlockMetadata
from ray_tpu.data.datasource import (
    BinaryDatasource,
    CSVDatasource,
    Datasource,
    ItemsDatasource,
    JSONDatasource,
    NumpyDatasource,
    ParquetDatasource,
    RangeDatasource,
    TextDatasource,
    write_csv_block,
    write_json_block,
    write_parquet_block,
)
from ray_tpu.data.executor import ExecStats, aggregate_global, execute_plan
from ray_tpu.data.iterator import DataIterator, StreamSplitIterator

ActorPoolStrategy = L.ActorPoolStrategy


class Dataset:
    def __init__(self, plan: L.LogicalPlan, materialized: Optional[list] = None):
        self._plan = plan
        self._materialized = materialized  # list[(ref, meta)] when pinned
        self._stats = ExecStats()

    # -- execution ----------------------------------------------------------

    def _ref_metas(self) -> Iterator[tuple]:
        if self._materialized is not None:
            return iter(self._materialized)
        return execute_plan(self._plan, self._stats)

    def materialize(self) -> "Dataset":
        """Execute now; the result holds pinned block refs."""
        if self._materialized is not None:
            return self
        return Dataset(self._plan, materialized=list(self._ref_metas()))

    def stats(self) -> str:
        return self._stats.summary()

    # -- transforms (lazy) --------------------------------------------------

    def _with(self, op: L.LogicalOp) -> "Dataset":
        return Dataset(self._plan.then(op))

    def map_batches(
        self,
        fn,
        *,
        batch_size: Optional[int] = None,
        compute=None,
        fn_args: tuple = (),
        fn_kwargs: Optional[dict] = None,
        fn_constructor_args: tuple = (),
        fn_constructor_kwargs: Optional[dict] = None,
        num_cpus: Optional[float] = None,
        concurrency: Optional[int] = None,
        **_ignored,
    ) -> "Dataset":
        # a callable CLASS is stateful per-worker by definition: default it
        # onto an actor pool (reference: map_batches requires concurrency/
        # ActorPoolStrategy for classes) instead of constructing per batch
        if isinstance(fn, type) and compute is None:
            compute = L.ActorPoolStrategy(size=concurrency or 1)
        return self._with(
            L.MapBatches(
                fn,
                batch_size=batch_size,
                compute=compute,
                fn_args=fn_args,
                fn_kwargs=fn_kwargs or {},
                fn_constructor_args=fn_constructor_args,
                fn_constructor_kwargs=fn_constructor_kwargs or {},
                num_cpus=num_cpus,
            )
        )

    def map(self, fn, *, compute=None) -> "Dataset":
        return self._with(L.MapRows(fn, compute=compute))

    def filter(self, fn, *, compute=None) -> "Dataset":
        return self._with(L.Filter(fn, compute=compute))

    def flat_map(self, fn, *, compute=None) -> "Dataset":
        return self._with(L.FlatMap(fn, compute=compute))

    def add_column(self, name: str, fn) -> "Dataset":
        def add(batch):
            batch = dict(batch)
            batch[name] = fn(batch)
            return batch

        return self.map_batches(add)

    def drop_columns(self, cols: Sequence[str]) -> "Dataset":
        return self.map_batches(
            lambda b, _c=tuple(cols): {k: v for k, v in b.items() if k not in _c}
        )

    def select_columns(self, cols: Sequence[str]) -> "Dataset":
        return self.map_batches(
            lambda b, _c=tuple(cols): {k: b[k] for k in _c}
        )

    def rename_columns(self, mapping: dict) -> "Dataset":
        return self.map_batches(
            lambda b, _m=dict(mapping): {_m.get(k, k): v for k, v in b.items()}
        )

    def repartition(self, num_blocks: int, *, shuffle: bool = False) -> "Dataset":
        return self._with(L.Repartition(num_blocks, shuffle=shuffle))

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(L.RandomShuffle(seed=seed))

    def sort(self, key: Union[str, Sequence[str]], descending: bool = False) -> "Dataset":
        keys = [key] if isinstance(key, str) else list(key)
        return self._with(L.Sort(keys, descending=descending))

    def limit(self, n: int) -> "Dataset":
        return self._with(L.Limit(n))

    def union(self, *others: "Dataset") -> "Dataset":
        return self._with(L.Union([o._plan for o in others]))

    def zip(self, other: "Dataset") -> "Dataset":
        return self._with(L.Zip(other._plan))

    def groupby(self, key: Union[str, Sequence[str]]) -> "GroupedData":
        keys = [key] if isinstance(key, str) else list(key)
        return GroupedData(self, keys)

    def random_split(
        self, fractions: list[float], *, seed: Optional[int] = None
    ) -> list["Dataset"]:
        mat = self.materialize()
        rows = list(mat.iter_rows())
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(rows))
        bounds = np.cumsum([0.0] + list(fractions))
        if abs(bounds[-1] - 1.0) > 1e-6:
            raise ValueError("fractions must sum to 1")
        out = []
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            idx = perm[int(lo * len(rows)) : int(hi * len(rows))]
            out.append(from_items([rows[i] for i in idx]))
        return out

    def split(self, n: int) -> list["Dataset"]:
        mat = self.materialize()
        rows = list(mat.iter_rows())
        bounds = np.linspace(0, len(rows), n + 1).astype(int)
        return [from_items(rows[lo:hi]) for lo, hi in zip(bounds[:-1], bounds[1:])]

    # -- consumption --------------------------------------------------------

    def iterator(self) -> DataIterator:
        return DataIterator(self._ref_metas)

    def iter_rows(self):
        return self.iterator().iter_rows()

    def iter_batches(self, **kw):
        return self.iterator().iter_batches(**kw)

    def iter_jax_batches(self, **kw):
        return self.iterator().iter_jax_batches(**kw)

    def iter_torch_batches(self, **kw):
        return self.iterator().iter_torch_batches(**kw)

    def iter_internal_blocks(self) -> Iterator[Block]:
        for ref, _ in self._ref_metas():
            yield api.get(ref)

    def streaming_split(self, n: int, *, equal: bool = True) -> list[DataIterator]:
        """n concurrent iterators over one shared execution (reference:
        dataset.py:1598 — the Train integration point)."""
        splitter = StreamSplitIterator(self._ref_metas, n, equal)
        return [splitter.split(i) for i in builtins.range(n)]

    def take(self, n: int = 20) -> list:
        out = []
        for row in self.limit(n).iter_rows():
            out.append(row)
            if len(out) >= n:
                break
        return out

    def take_all(self) -> list:
        return list(self.iter_rows())

    def show(self, n: int = 20) -> None:
        for row in self.take(n):
            print(row)

    def count(self) -> int:
        return sum(m.num_rows for _, m in self._ref_metas())

    def schema(self) -> Optional[dict[str, str]]:
        for _, meta in self._ref_metas():
            if meta.schema:
                return meta.schema
        return None

    def columns(self) -> Optional[list[str]]:
        s = self.schema()
        return list(s) if s else None

    def num_blocks(self) -> int:
        return sum(1 for _ in self._ref_metas())

    def size_bytes(self) -> int:
        return sum(m.size_bytes for _, m in self._ref_metas())

    def aggregate(self, *aggs: AggregateFn) -> dict:
        inputs = list(self._ref_metas())
        vals = aggregate_global(inputs, list(aggs))
        return {a.name: v for a, v in zip(aggs, vals)}

    def sum(self, on: Optional[str] = None):
        return self.aggregate(Sum(on))[f"sum({on or ''})"]

    def min(self, on: Optional[str] = None):
        return self.aggregate(Min(on))[f"min({on or ''})"]

    def max(self, on: Optional[str] = None):
        return self.aggregate(Max(on))[f"max({on or ''})"]

    def mean(self, on: Optional[str] = None):
        return self.aggregate(Mean(on))[f"mean({on or ''})"]

    def std(self, on: Optional[str] = None):
        return self.aggregate(Std(on))[f"std({on or ''})"]

    def to_pandas(self):
        blocks = list(self.iter_internal_blocks())
        return Block.concat(blocks).to_pandas()

    # -- writers ------------------------------------------------------------

    def _write(self, path: str, writer, ext: str) -> None:
        os.makedirs(path, exist_ok=True)
        write = api.remote(
            lambda block, p: (writer(block, p), None)[1]
        )
        refs = []
        for i, (ref, _) in enumerate(self._ref_metas()):
            out = os.path.join(path, f"part-{i:05d}.{ext}")
            # pass the ref: the task resolves it from the object store
            # (blocks never round-trip through the driver)
            refs.append(write.remote(ref, out))
        api.get(refs)

    def write_csv(self, path: str) -> None:
        self._write(path, write_csv_block, "csv")

    def write_json(self, path: str) -> None:
        self._write(path, write_json_block, "json")

    def write_parquet(self, path: str) -> None:
        self._write(path, write_parquet_block, "parquet")

    def __repr__(self):
        ops = " -> ".join(type(o).__name__ for o in self._plan.ops)
        return f"Dataset({ops})"


class GroupedData:
    def __init__(self, ds: Dataset, keys: list[str]):
        self._ds = ds
        self._keys = keys

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        return self._ds._with(L.GroupByAggregate(self._keys, list(aggs)))

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on: Optional[str] = None) -> Dataset:
        return self.aggregate(Std(on))

    def map_groups(self, fn) -> Dataset:
        keys = self._keys

        def per_group(batch):
            block = Block.from_batch(batch)
            tags = [
                tuple(block.columns[k][i] for k in keys)
                for i in builtins.range(block.num_rows)
            ]
            by_tag: dict = {}
            for i, tag in enumerate(tags):
                by_tag.setdefault(tag, []).append(i)
            outs = []
            for idx in by_tag.values():
                group = block.take_indices(np.asarray(idx))
                outs.append(Block.from_batch(fn(group.to_batch())))
            return Block.concat(outs).to_batch()

        # group rows together first via a sort exchange, then map per group
        return self._ds.sort(keys[0]).map_batches(per_group, batch_size=None)


# ---------------------------------------------------------------------------
# constructors (module-level API, reference: ray.data.range etc.)
# ---------------------------------------------------------------------------


def _read(ds: Datasource, parallelism: int = -1) -> Dataset:
    return Dataset(L.LogicalPlan([L.Read(ds, parallelism)]))


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    return _read(RangeDatasource(n), parallelism)


def from_items(items: list, *, parallelism: int = -1) -> Dataset:
    return _read(ItemsDatasource(items), parallelism)


def from_numpy(arrays: Union[np.ndarray, dict], *, parallelism: int = -1) -> Dataset:
    if isinstance(arrays, np.ndarray):
        arrays = {ITEM_COLUMN: arrays}
    return _read(NumpyDatasource(arrays), parallelism)


def from_pandas(df) -> Dataset:
    return _read(NumpyDatasource({c: df[c].to_numpy() for c in df.columns}))


def read_csv(paths, *, parallelism: int = -1) -> Dataset:
    return _read(CSVDatasource(paths), parallelism)


def read_json(paths, *, parallelism: int = -1) -> Dataset:
    return _read(JSONDatasource(paths), parallelism)


def read_text(paths, *, parallelism: int = -1) -> Dataset:
    return _read(TextDatasource(paths), parallelism)


def read_parquet(paths, *, parallelism: int = -1) -> Dataset:
    return _read(ParquetDatasource(paths), parallelism)


def read_binary_files(paths, *, parallelism: int = -1) -> Dataset:
    return _read(BinaryDatasource(paths), parallelism)


def read_datasource(ds: Datasource, *, parallelism: int = -1) -> Dataset:
    return _read(ds, parallelism)


def read_tfrecords(paths, *, parallelism: int = -1) -> Dataset:
    from ray_tpu.data.datasources_ext import TFRecordDatasource

    return _read(TFRecordDatasource(paths), parallelism)


def read_arrow(paths, *, parallelism: int = -1) -> Dataset:
    from ray_tpu.data.datasources_ext import ArrowDatasource

    return _read(ArrowDatasource(paths), parallelism)


def read_sql(sql: str, connection_factory, *, parallelism_queries=None,
             parallelism: int = -1) -> Dataset:
    from ray_tpu.data.datasources_ext import SQLDatasource

    return _read(
        SQLDatasource(sql, connection_factory, parallelism_queries), parallelism
    )


def read_images(paths, *, size=None, mode="RGB", parallelism: int = -1) -> Dataset:
    from ray_tpu.data.datasources_ext import ImageDatasource

    return _read(ImageDatasource(paths, size=size, mode=mode), parallelism)


def read_webdataset(paths, *, parallelism: int = -1) -> Dataset:
    from ray_tpu.data.datasources_ext import WebDatasetDatasource

    return _read(WebDatasetDatasource(paths), parallelism)


def from_arrow(tables) -> Dataset:
    """Datasets from in-memory pyarrow Tables (reference: from_arrow) —
    dtype-preserving (columns convert via to_numpy, not a row round trip)."""
    from ray_tpu.data.datasources_ext import block_from_arrow

    if not isinstance(tables, (list, tuple)):
        tables = [tables]
    merged = Block.concat([block_from_arrow(t) for t in tables])
    return _read(NumpyDatasource(dict(merged.columns)))
