"""Streaming executor: lowers a LogicalPlan to remote tasks/actor pools.

Reference: python/ray/data/_internal/execution/streaming_executor.py — a
pull-based scheduling loop with backpressure. Here the pull chain *is*
the Python generator stack: each physical operator is a generator over
(block_ref, metadata) pairs that keeps at most `window` tasks in flight,
so downstream consumption rate bounds upstream submission (backpressure
without a central controller). All-to-all ops (shuffle/sort/groupby/
repartition) are barriers, implemented as classic two-phase map/reduce
exchanges over the task runtime — the same design as the reference's
push-based shuffle scheduler, minus cross-node block placement (the
scheduler owns that).
"""

from __future__ import annotations

import collections
import time
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

from ray_tpu.core import api
from ray_tpu.data import logical as L
from ray_tpu.data.aggregate import AggregateFn
from ray_tpu.data.block import Block, BlockMetadata, iter_batches_from_blocks

RefMeta = tuple  # (ObjectRef[Block], BlockMetadata)

DEFAULT_WINDOW = 8  # max in-flight tasks per operator


# ---------------------------------------------------------------------------
# remote task bodies (plain functions; wrapped by api.remote lazily so that
# importing ray_tpu.data never boots the runtime)
# ---------------------------------------------------------------------------


def _exec_read(task) -> tuple:
    blocks = [b for b in task() if b.num_rows > 0]
    block = blocks[0] if len(blocks) == 1 else Block.concat(blocks)
    return block, block.metadata()


def _exec_map(fn, *blocks) -> tuple:
    out = fn(Block.concat(list(blocks)) if len(blocks) != 1 else blocks[0])
    return out, out.metadata()


def _exec_split(block, n: int, assign, block_idx: int):
    """Map side of an exchange: route each row to one of n partitions."""
    part = assign(block, block_idx)
    return tuple(block.take_indices(np.nonzero(part == j)[0]) for j in range(n))


def _exec_merge(postprocess, part_idx, *parts) -> tuple:
    out = Block.concat(list(parts))
    if postprocess is not None:
        out = postprocess(out, part_idx)
    return out, out.metadata()


def _exec_slices(slices, *blocks) -> tuple:
    """Reduce side of shuffle-free repartition: concat row ranges."""
    out = Block.concat([b.slice(lo, hi) for b, (lo, hi) in zip(blocks, slices)])
    return out, out.metadata()


def _exec_partial_agg(aggs: list[AggregateFn], block) -> list:
    return [a.accumulate_block(a.init(), block) for a in aggs]


_REMOTES: dict = {}


def _remote(fn, **opts):
    key = (fn, tuple(sorted(opts.items())))
    if key not in _REMOTES:
        _REMOTES[key] = api.remote(**opts)(fn) if opts else api.remote(fn)
    return _REMOTES[key]


# ---------------------------------------------------------------------------
# stats
# ---------------------------------------------------------------------------


class ExecStats:
    def __init__(self):
        self.ops: dict[str, dict] = {}

    def record(self, op: str, n_tasks: int = 0, n_blocks: int = 0, rows: int = 0):
        d = self.ops.setdefault(op, {"tasks": 0, "blocks": 0, "rows": 0})
        d["tasks"] += n_tasks
        d["blocks"] += n_blocks
        d["rows"] += rows

    def summary(self) -> str:
        lines = [f"{op}: {d}" for op, d in self.ops.items()]
        return "\n".join(lines) or "(no ops executed)"


# ---------------------------------------------------------------------------
# physical operators (generator-based)
# ---------------------------------------------------------------------------


def _windowed(submit: Callable[[Any], tuple], inputs: Iterable, window: int):
    """Submit with at most `window` outstanding; yield in submission order."""
    pending = collections.deque()
    for item in inputs:
        if len(pending) >= window:
            yield _resolve(pending.popleft())
        pending.append(submit(item))
    while pending:
        yield _resolve(pending.popleft())


def _resolve(refs) -> RefMeta:
    block_ref, meta_ref = refs
    return block_ref, api.get(meta_ref)


def _read_op(op: L.Read, stats: ExecStats, window: int) -> Iterator[RefMeta]:
    parallelism = op.parallelism if op.parallelism > 0 else 16
    tasks = op.datasource.get_read_tasks(parallelism)
    stats.record("read", n_tasks=len(tasks))
    run = _remote(_exec_read, num_returns=2)
    yield from _windowed(lambda t: run.remote(t), tasks, window)


def _make_block_fn(op: L.LogicalOp) -> Callable[[Block], Block]:
    """Lower a row/batch-level logical op to a Block -> Block function."""
    if isinstance(op, L.MapBatches):
        fn, args, kwargs = op.fn, op.fn_args, op.fn_kwargs
        batch_size = op.batch_size

        def run(block: Block, _fn=None) -> Block:
            f = _fn if _fn is not None else fn
            outs = [
                Block.from_batch(f(b.to_batch(), *args, **kwargs))
                for b in iter_batches_from_blocks([block], batch_size)
            ]
            return Block.concat(outs) if outs else Block({})

        return run
    if isinstance(op, L.MapRows):

        def run(block: Block, _fn=None) -> Block:
            f = _fn if _fn is not None else op.fn
            return Block.from_rows([f(r) for r in block.iter_rows()])

        return run
    if isinstance(op, L.Filter):

        def run(block: Block, _fn=None) -> Block:
            f = _fn if _fn is not None else op.fn
            keep = np.fromiter(
                (bool(f(r)) for r in block.iter_rows()), bool, count=block.num_rows
            )
            return block.take_indices(np.nonzero(keep)[0])

        return run
    if isinstance(op, L.FlatMap):

        def run(block: Block, _fn=None) -> Block:
            f = _fn if _fn is not None else op.fn
            rows = []
            for r in block.iter_rows():
                rows.extend(f(r))
            return Block.from_rows(rows)

        return run
    raise TypeError(f"not a map-like op: {op}")


class _MapWorker:
    """Actor wrapping a callable class for ActorPoolStrategy compute."""

    def __init__(self, cls, ctor_args, ctor_kwargs, block_fn):
        self._callable = cls(*ctor_args, **ctor_kwargs)
        self._block_fn = block_fn

    def apply(self, *blocks):
        block = Block.concat(list(blocks)) if len(blocks) != 1 else blocks[0]
        out = self._block_fn(block, _fn=self._callable)
        return out, out.metadata()


def _map_op(
    op: L.LogicalOp, upstream: Iterator[RefMeta], stats: ExecStats, window: int
) -> Iterator[RefMeta]:
    name = type(op).__name__.lower()
    block_fn = _make_block_fn(op)
    compute = getattr(op, "compute", None)

    batch_size = getattr(op, "batch_size", None)

    def bundles() -> Iterator[list]:
        """Group upstream refs so each task sees >= batch_size rows."""
        if batch_size is None:
            for rm in upstream:
                stats.record(name, n_blocks=1, rows=rm[1].num_rows)
                yield [rm[0]]
            return
        buf, buffered = [], 0
        for ref, meta in upstream:
            stats.record(name, n_blocks=1, rows=meta.num_rows)
            buf.append(ref)
            buffered += meta.num_rows
            if buffered >= batch_size:
                yield buf
                buf, buffered = [], 0
        if buf:
            yield buf

    if isinstance(compute, L.ActorPoolStrategy):
        if not (isinstance(op, L.MapBatches) and isinstance(op.fn, type)):
            raise ValueError("ActorPoolStrategy requires map_batches with a class")
        Worker = api.remote(_MapWorker)
        pool = [
            Worker.remote(op.fn, op.fn_constructor_args, op.fn_constructor_kwargs, block_fn)
            for _ in range(compute.size)
        ]
        rr = [0]

        def submit(refs):
            actor = pool[rr[0] % len(pool)]
            rr[0] += 1
            return actor.apply.options(num_returns=2).remote(*refs)

        try:
            yield from _windowed(submit, bundles(), max(window, len(pool)))
        finally:
            for a in pool:
                api.kill(a)
        return

    opts = {"num_returns": 2}
    if getattr(op, "num_cpus", None):
        opts["num_cpus"] = op.num_cpus
    run = _remote(_exec_map, **opts)
    yield from _windowed(lambda refs: run.remote(block_fn, *refs), bundles(), window)


def _materialize(upstream: Iterator[RefMeta]) -> list[RefMeta]:
    return list(upstream)


def _holder_map(all_refs) -> "tuple[dict, dict] | None":
    """ONE batched locality snapshot for a whole exchange: object id ->
    holder addrs, plus addr -> node_id. The locality signal for
    push-based reduce placement (reference:
    exchange/push_based_shuffle_task_scheduler.py:400 — merges pipeline
    on the nodes that already hold the map outputs, so partition bytes
    never transit the driver or a third node). Two GCS RPCs total, not
    two per partition."""
    from ray_tpu.core.api import _cluster

    cb = _cluster()
    if cb is None:
        return None
    try:
        client = cb.client
        ids = [getattr(r, "id", None) for r in all_refs]
        ids = [i for i in ids if i is not None]
        if not ids:
            return None
        locs = client.gcs.call("locate_many", {"object_ids": ids}, timeout=5)
        addr_node = {
            tuple(n["addr"]): n["node_id"]
            for n in client.gcs.call("list_nodes", None, timeout=5)
        }
        return (locs or {}), addr_node
    except Exception:  # noqa: BLE001 — locality is an optimization only
        return None


def _majority_holder(refs, holder_map) -> "str | None":
    """node_id holding the most of these split outputs, or None."""
    if holder_map is None:
        return None
    locs, addr_node = holder_map
    counts: dict = {}
    for r in refs:
        for a in locs.get(getattr(r, "id", None)) or ():
            counts[tuple(a)] = counts.get(tuple(a), 0) + 1
    if not counts:
        return None
    return addr_node.get(max(counts, key=counts.get))


def _exchange(
    inputs: list[RefMeta],
    n_out: int,
    assign: Callable[[Block], np.ndarray],
    postprocess: Optional[Callable[[Block], Block]],
    stats: ExecStats,
    name: str,
) -> Iterator[RefMeta]:
    """Two-phase all-to-all: split every input block into n_out partitions,
    then merge partition j across all inputs. On a cluster, each merge is
    scheduled (soft affinity) on the node holding most of its partition's
    split outputs — block bytes move holder -> reducer directly through
    the object plane, never via the driver."""
    if not inputs:
        return
    from ray_tpu.core.api import _cluster

    split = _remote(_exec_split, num_returns=n_out) if n_out > 1 else None
    parts: list[tuple] = []  # per input: tuple of n_out refs
    for i, (ref, _) in enumerate(inputs):
        if n_out == 1:
            parts.append((ref,))
        else:
            out = split.remote(ref, n_out, assign, i)
            parts.append(tuple(out))
    stats.record(f"{name}.map", n_tasks=len(inputs))
    merge = _remote(_exec_merge, num_returns=2)
    holder_map = None
    if _cluster() is not None and n_out > 1:
        # the locality lookup needs the split outputs to EXIST; a short
        # bounded wait trades a little pipelining for placed reduces
        try:
            api.wait(
                [p[0] for p in parts], num_returns=len(parts), timeout=10.0
            )
        except Exception:  # noqa: BLE001
            pass
        holder_map = _holder_map([r for p in parts for r in p])
    for j in range(n_out):
        refs_j = [p[j] for p in parts]
        node = _majority_holder(refs_j, holder_map)
        m = merge
        if node is not None:
            m = merge.options(
                scheduling_strategy=api.NodeAffinitySchedulingStrategy(
                    node, soft=True
                )
            )
        refs = m.remote(postprocess, j, *refs_j)
        stats.record(f"{name}.reduce", n_tasks=1)
        yield _resolve(refs)


def _random_shuffle_op(op, upstream, stats, window):
    inputs = _materialize(upstream)
    n = max(1, len(inputs))
    rng_seed = op.seed if op.seed is not None else int(time.time() * 1e6) % (2**31)

    def assign(block: Block, block_idx: int, _n=n, _seed=rng_seed) -> np.ndarray:
        # distinct stream per input block, or equal-sized blocks would all
        # draw identical assignment vectors
        rng = np.random.default_rng([_seed, block_idx])
        return rng.integers(0, _n, block.num_rows)

    def postprocess(block: Block, part_idx: int, _seed=rng_seed) -> Block:
        rng = np.random.default_rng([_seed ^ 0x5EED, part_idx])
        return block.take_indices(rng.permutation(block.num_rows))

    yield from _exchange(inputs, n, assign, postprocess, stats, "random_shuffle")


def _sort_op(op, upstream, stats, window):
    inputs = _materialize(upstream)
    if not inputs:
        return
    keys = list(op.keys)
    n = len(inputs)
    # boundary sampling on the first key (reference: sort_task_scheduler)
    samples = []
    for ref, _ in inputs:
        block: Block = api.get(ref)
        col = block.columns.get(keys[0])
        if col is not None and len(col):
            take = np.linspace(0, len(col) - 1, min(20, len(col))).astype(int)
            samples.append(np.asarray(col)[take])
    allsamp = np.sort(np.concatenate(samples)) if samples else np.array([])
    bounds = (
        allsamp[np.linspace(0, len(allsamp) - 1, n + 1).astype(int)[1:-1]]
        if len(allsamp)
        else np.array([])
    )

    def assign(block: Block, block_idx: int, _b=bounds, _k=keys[0]) -> np.ndarray:
        if not len(_b):
            return np.zeros(block.num_rows, np.int64)
        return np.searchsorted(_b, block.columns[_k], side="right")

    def postprocess(block: Block, part_idx: int) -> Block:
        return block.sort_by(keys, op.descending)

    out = _exchange(inputs, max(1, n), assign, postprocess, stats, "sort")
    yield from (reversed(list(out)) if op.descending else out)


def _groupby_op(op, upstream, stats, window):
    inputs = _materialize(upstream)
    if not inputs:
        return
    keys = list(op.keys)
    aggs = list(op.aggs)
    n = min(len(inputs), 8) or 1

    def assign(block: Block, block_idx: int, _k=keys, _n=n) -> np.ndarray:
        h = np.zeros(block.num_rows, np.uint64)
        for k in _k:
            col = block.columns[k]
            h = h * np.uint64(1000003) + np.array(
                [hash(x) & 0xFFFFFFFF for x in col], np.uint64
            )
        return (h % np.uint64(_n)).astype(np.int64)

    def postprocess(block: Block, part_idx: int, _k=keys, _aggs=aggs) -> Block:
        if block.num_rows == 0:
            return Block({})
        rows = []
        keycols = [block.columns[k] for k in _k]
        tags = np.array([hash(tuple(kc[i] for kc in keycols)) for i in range(block.num_rows)])
        for tag in dict.fromkeys(tags.tolist()):
            idx = np.nonzero(tags == tag)[0]
            group = block.take_indices(idx)
            row = {k: group.columns[k][0] for k in _k}
            for a in _aggs:
                row[a.name] = a.finalize(a.accumulate_block(a.init(), group))
            rows.append(row)
        return Block.from_rows(rows)

    yield from _exchange(inputs, n, assign, postprocess, stats, "groupby")


def _repartition_op(op, upstream, stats, window):
    inputs = _materialize(upstream)
    n_out = op.num_blocks
    if op.shuffle:
        def assign(block: Block, block_idx: int, _n=n_out) -> np.ndarray:
            rng = np.random.default_rng([17, block_idx])
            return rng.integers(0, _n, block.num_rows)

        yield from _exchange(inputs, n_out, assign, None, stats, "repartition")
        return
    # shuffle=False: contiguous re-slicing preserving order
    total = sum(m.num_rows for _, m in inputs)
    bounds = np.linspace(0, total, n_out + 1).astype(int)
    run = _remote(_exec_slices, num_returns=2)
    # global row offset of each input block
    offsets = np.cumsum([0] + [m.num_rows for _, m in inputs])
    for j in range(n_out):
        lo, hi = int(bounds[j]), int(bounds[j + 1])
        needed, slices = [], []
        for (ref, m), off in zip(inputs, offsets[:-1]):
            s, e = max(lo, off), min(hi, off + m.num_rows)
            if e > s:
                needed.append(ref)
                slices.append((s - off, e - off))
        if not needed and total > 0:
            # empty output split (more splits than rows)
            needed, slices = [inputs[0][0]], [(0, 0)]
        stats.record("repartition", n_tasks=1)
        yield _resolve(run.remote(slices, *needed))


def _limit_op(op, upstream, stats, window):
    remaining = op.n
    run = _remote(_exec_map, num_returns=2)
    for ref, meta in upstream:
        if remaining <= 0:
            return
        if meta.num_rows <= remaining:
            remaining -= meta.num_rows
            yield ref, meta
        else:
            take = remaining
            remaining = 0
            yield _resolve(run.remote(lambda b, _t=take: b.slice(0, _t), ref))
            return


# ---------------------------------------------------------------------------
# plan execution
# ---------------------------------------------------------------------------


def execute_plan(
    plan: L.LogicalPlan, stats: Optional[ExecStats] = None, window: int = DEFAULT_WINDOW
) -> Iterator[RefMeta]:
    """Lower + run. Returns a pull-based iterator of (block_ref, meta)."""
    stats = stats if stats is not None else ExecStats()
    stream: Optional[Iterator[RefMeta]] = None
    for op in plan.ops:
        if isinstance(op, L.Read):
            stream = _read_op(op, stats, window)
        elif isinstance(op, (L.MapBatches, L.MapRows, L.Filter, L.FlatMap)):
            stream = _map_op(op, stream, stats, window)
        elif isinstance(op, L.RandomShuffle):
            stream = _random_shuffle_op(op, stream, stats, window)
        elif isinstance(op, L.Sort):
            stream = _sort_op(op, stream, stats, window)
        elif isinstance(op, L.GroupByAggregate):
            stream = _groupby_op(op, stream, stats, window)
        elif isinstance(op, L.Repartition):
            stream = _repartition_op(op, stream, stats, window)
        elif isinstance(op, L.Limit):
            stream = _limit_op(op, stream, stats, window)
        elif isinstance(op, L.Union):
            parts = [stream] + [execute_plan(p, stats, window) for p in op.others]

            def chain(parts=parts):
                for p in parts:
                    yield from p

            stream = chain()
        elif isinstance(op, L.Zip):
            stream = _zip_op(op, stream, stats, window)
        else:
            raise TypeError(f"unknown logical op {op}")
    assert stream is not None, "empty plan"
    return stream


def _zip_op(op, upstream, stats, window):
    left = _materialize(upstream)
    right = _materialize(execute_plan(op.other, stats, window))

    def rows(side):
        return sum(m.num_rows for _, m in side)

    if rows(left) != rows(right):
        raise ValueError(f"zip: row counts differ ({rows(left)} vs {rows(right)})")

    def _zip_blocks(lrefs, rrefs, lslices, rslices):
        lb = Block.concat([api.get(r).slice(lo, hi) for r, (lo, hi) in zip(lrefs, lslices)])
        rb = Block.concat([api.get(r).slice(lo, hi) for r, (lo, hi) in zip(rrefs, rslices)])
        cols = dict(lb.columns)
        for k, v in rb.columns.items():
            cols[k if k not in cols else f"{k}_1"] = v
        out = Block(cols)
        return out, out.metadata()

    # align on left block boundaries
    run = _remote(_zip_blocks, num_returns=2)
    loff = 0
    roffsets = np.cumsum([0] + [m.num_rows for _, m in right])
    for ref, meta in left:
        lo, hi = loff, loff + meta.num_rows
        loff = hi
        rrefs, rslices = [], []
        for (rref, rm), off in zip(right, roffsets[:-1]):
            s, e = max(lo, off), min(hi, off + rm.num_rows)
            if e > s:
                rrefs.append(rref)
                rslices.append((s - off, e - off))
        stats.record("zip", n_tasks=1)
        yield _resolve(run.remote([ref], rrefs, [(0, meta.num_rows)], rslices))


def aggregate_global(
    inputs: list[RefMeta], aggs: list[AggregateFn]
) -> list:
    """Tree aggregation without keys: per-block partials, merged on driver."""
    run = _remote(_exec_partial_agg)
    partial_refs = [run.remote(aggs, ref) for ref, _ in inputs]
    accs = [a.init() for a in aggs]
    for pref in partial_refs:
        partials = api.get(pref)
        accs = [a.merge(acc, p) for a, acc, p in zip(aggs, accs, partials)]
    return [a.finalize(acc) for a, acc in zip(aggs, accs)]
