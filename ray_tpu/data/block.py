"""Block: the unit of data movement in ray_tpu.data.

The reference's blocks are Arrow tables in plasma
(python/ray/data/_internal/ — SURVEY.md §2.5). Here a block is a dict of
equal-length numpy columns (object dtype for ragged/python values) held
in the framework object store; in thread-worker mode block hand-off
between operators is zero-copy by construction, which is the plasma
property that mattered. Numpy columns are the right terminus for a TPU
pipeline: `jax.device_put` of a contiguous ndarray is the fast host→HBM
path.
"""

from __future__ import annotations

import dataclasses
import sys
from typing import Any, Callable, Iterable, Iterator, Optional

import numpy as np

# Non-dict rows (ds.from_items([1,2,3])) live in a single default column,
# like the reference's "item" column for simple datasets.
ITEM_COLUMN = "item"

Batch = dict[str, np.ndarray]


@dataclasses.dataclass(frozen=True)
class BlockMetadata:
    """Driver-side stats that travel with a block ref (reference:
    python/ray/data/block.py BlockMetadata)."""

    num_rows: int
    size_bytes: int
    schema: Optional[dict[str, str]] = None  # column -> dtype str


def _as_column(values: list) -> np.ndarray:
    try:
        return np.asarray(values)
    except ValueError:
        # ragged rows (variable-length lists/arrays): object column
        arr = np.empty(len(values), object)
        arr[:] = values
        return arr


class Block:
    """Immutable columnar block."""

    __slots__ = ("columns",)

    def __init__(self, columns: dict[str, np.ndarray]):
        lens = {k: len(v) for k, v in columns.items()}
        if len(set(lens.values())) > 1:
            raise ValueError(f"ragged columns: {lens}")
        self.columns = columns

    # -- constructors -------------------------------------------------------

    @staticmethod
    def from_rows(rows: list) -> "Block":
        if not rows:
            return Block({})
        if isinstance(rows[0], dict):
            cols = {}
            for key in rows[0]:
                cols[key] = _as_column([r[key] for r in rows])
            return Block(cols)
        return Block({ITEM_COLUMN: _as_column(rows)})

    @staticmethod
    def from_batch(batch: Any) -> "Block":
        if isinstance(batch, Block):
            return batch
        if isinstance(batch, dict):
            return Block({k: np.asarray(v) for k, v in batch.items()})
        if isinstance(batch, np.ndarray):
            return Block({ITEM_COLUMN: batch})
        if _is_pandas(batch):
            return Block({c: batch[c].to_numpy() for c in batch.columns})
        raise TypeError(f"cannot build a block from {type(batch)}")

    @staticmethod
    def concat(blocks: list["Block"]) -> "Block":
        blocks = [b for b in blocks if b.num_rows > 0]
        if not blocks:
            return Block({})
        keys = list(blocks[0].columns)
        for b in blocks:
            if list(b.columns) != keys:
                raise ValueError(
                    f"cannot concat blocks with schemas {keys} vs {list(b.columns)}"
                )
        return Block(
            {k: np.concatenate([b.columns[k] for b in blocks]) for k in keys}
        )

    # -- accessors ----------------------------------------------------------

    @property
    def num_rows(self) -> int:
        if not self.columns:
            return 0
        return len(next(iter(self.columns.values())))

    @property
    def size_bytes(self) -> int:
        total = 0
        for v in self.columns.values():
            if v.dtype.kind == "O":
                total += sum(sys.getsizeof(x) for x in v[:64]) * max(1, len(v) // 64)
            else:
                total += v.nbytes
        return total

    def schema(self) -> dict[str, str]:
        return {k: str(v.dtype) for k, v in self.columns.items()}

    def metadata(self) -> BlockMetadata:
        return BlockMetadata(self.num_rows, self.size_bytes, self.schema())

    def slice(self, start: int, stop: int) -> "Block":
        return Block({k: v[start:stop] for k, v in self.columns.items()})

    def take_indices(self, idx: np.ndarray) -> "Block":
        return Block({k: v[idx] for k, v in self.columns.items()})

    def to_batch(self) -> Batch:
        return dict(self.columns)

    def iter_rows(self) -> Iterator[Any]:
        cols = self.columns
        if list(cols) == [ITEM_COLUMN]:
            yield from cols[ITEM_COLUMN]
            return
        for i in range(self.num_rows):
            yield {k: v[i] for k, v in cols.items()}

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame({k: list(v) if v.ndim > 1 else v for k, v in self.columns.items()})

    # -- compute helpers used by physical operators -------------------------

    def sort_by(self, keys: list[str], descending: bool = False) -> "Block":
        if self.num_rows == 0:
            return self
        order = np.lexsort([self.columns[k] for k in reversed(keys)])
        if descending:
            order = order[::-1]
        return self.take_indices(order)

    def __repr__(self):
        return f"Block({self.schema()}, num_rows={self.num_rows})"


def _is_pandas(x) -> bool:
    mod = getattr(type(x), "__module__", "")
    return mod.startswith("pandas") and type(x).__name__ == "DataFrame"


def batch_to_output(out: Any) -> Block:
    """Normalize a user map_batches return value to a Block."""
    return Block.from_batch(out)


def iter_batches_from_blocks(
    blocks: Iterable[Block],
    batch_size: Optional[int],
    *,
    drop_last: bool = False,
) -> Iterator[Block]:
    """Re-batch a block stream to exactly batch_size rows (coalescing across
    block boundaries). batch_size=None yields blocks as-is."""
    if batch_size is None:
        for b in blocks:
            if b.num_rows:
                yield b
        return
    # merged-once cursor: emitting a batch slices views out of the current
    # merged buffer instead of rebuilding the remainder (keeps iteration
    # linear in total rows, not quadratic per block).
    buf: list[Block] = []
    buffered = 0
    merged: Optional[Block] = None
    offset = 0
    for b in blocks:
        if b.num_rows == 0:
            continue
        buf.append(b)
        buffered += b.num_rows
        if buffered < batch_size:
            continue
        if merged is not None and offset < merged.num_rows:
            buf.insert(0, merged.slice(offset, merged.num_rows))
        merged = buf[0] if len(buf) == 1 else Block.concat(buf)
        offset = 0
        buf, buffered = [], 0
        while merged.num_rows - offset >= batch_size:
            yield merged.slice(offset, offset + batch_size)
            offset += batch_size
        buffered = merged.num_rows - offset
    tail = []
    if merged is not None and offset < merged.num_rows:
        tail.append(merged.slice(offset, merged.num_rows))
    tail.extend(buf)
    if tail and not drop_last:
        yield Block.concat(tail)
