"""Standalone dashboard process for cluster deployments.

`python -m ray_tpu.dashboard --gcs HOST:PORT [--host H] [--port P]`

Reference analog: the dashboard head process `ray start` boots next to
the GCS (python/ray/dashboard/dashboard.py). The CLI's head mode spawns
this when --dashboard-port is given; the k8s head manifest uses it so
the Service's 8265 port has a real listener behind it.
"""

from __future__ import annotations

import argparse
import threading


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--gcs", required=True, help="GCS address HOST:PORT")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8265)
    args = p.parse_args()

    from ray_tpu.dashboard import Dashboard

    dash = Dashboard(host=args.host, port=args.port, gcs_address=args.gcs)
    print(f"DASHBOARD_ADDRESS {args.host}:{dash.port}", flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        dash.shutdown()


if __name__ == "__main__":
    main()
