"""Minimal dashboard: HTTP state + metrics endpoints.

Reference analog: python/ray/dashboard/ (head.py:62 DashboardHead + the
modules/ API routes + metrics pipeline). Single-host collapse: one
aiohttp server exposing

  /api/tasks /api/actors /api/objects /api/nodes /api/placement_groups
  /api/summary /api/cluster_status   — JSON state (util/state.py)
  /metrics                           — Prometheus text (util/metrics.py)
  /timeline                          — Chrome trace JSON
  /healthz                           — liveness

A React UI is out of scope; the JSON surface is the contract the
reference's UI consumes.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.dashboard")

_dashboard: Optional["Dashboard"] = None


class Dashboard:
    """`gcs_address` switches on the CLUSTER view: /api/cluster/* routes
    aggregate the GCS tables plus per-node stats pulled live from every
    node daemon's RPC server — each daemon IS the per-node dashboard
    agent (reference: dashboard/agent.py processes colocated with each
    raylet; here the daemon's rpc_stats/rpc_timeline endpoints fill that
    role without a separate process)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8265,
                 gcs_address: Optional[str] = None):
        self.host = host
        self.port = port
        self.gcs_address = gcs_address
        self._started = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="ray_tpu-dashboard", daemon=True
        )
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError(f"dashboard failed to bind {host}:{port}")

    def _serve(self) -> None:
        from aiohttp import web

        from ray_tpu.util import metrics as metrics_mod
        from ray_tpu.util import state

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        def offload(fn, *args):
            return asyncio.get_event_loop().run_in_executor(None, fn, *args)

        async def healthz(_req):
            return web.Response(text="success")

        async def tasks(req):
            st = req.query.get("state")
            rows = await offload(lambda: [vars(r) for r in state.list_tasks(st)])
            return web.json_response(rows)

        async def actors(_req):
            return web.json_response(await offload(state.list_actors))

        async def objects(_req):
            return web.json_response(await offload(state.list_objects))

        async def nodes(_req):
            return web.json_response(await offload(state.list_nodes))

        async def pgs(_req):
            return web.json_response(await offload(state.list_placement_groups))

        async def summary(_req):
            return web.json_response(await offload(state.summarize_tasks))

        async def cluster_status(_req):
            import ray_tpu

            return web.json_response(
                {
                    "cluster_resources": await offload(ray_tpu.cluster_resources),
                    "available_resources": await offload(ray_tpu.available_resources),
                }
            )

        async def metrics(_req):
            return web.Response(
                text=metrics_mod.prometheus_text(),
                content_type="text/plain",
            )

        async def timeline(_req):
            return web.json_response(await offload(state.timeline))

        # -- cluster view: GCS tables + live per-daemon agent stats --------
        def _gcs_call(method, payload=None):
            from ray_tpu.cluster.rpc import RpcClient

            host, port = self.gcs_address.rsplit(":", 1)
            c = RpcClient(host, int(port), timeout=10.0).connect()
            try:
                return c.call(method, payload)
            finally:
                c.close()

        def _agent_stats(n):
            from ray_tpu.cluster.rpc import RpcClient

            try:  # the daemon doubles as the per-node agent
                host, port = n["addr"]
                c = RpcClient(host, port, timeout=5.0).connect()
                try:
                    n["stats"] = c.call("stats", None)
                finally:
                    c.close()
            except Exception as e:  # noqa: BLE001
                n["stats_error"] = repr(e)[:120]
            return n

        def _cluster_nodes():
            from concurrent.futures import ThreadPoolExecutor

            nodes = _gcs_call("list_nodes")
            alive = [n for n in nodes if n.get("alive")]
            if alive:  # fan out: one wedged daemon must not serialize all
                with ThreadPoolExecutor(max_workers=min(16, len(alive))) as ex:
                    list(ex.map(_agent_stats, alive))
            return nodes

        async def cluster_nodes(_req):
            return web.json_response(await offload(_cluster_nodes))

        async def cluster_actors(_req):
            rows = await offload(lambda: _gcs_call("list_actors"))
            for r in rows:
                r.pop("creation_spec", None)  # pickled blob, not JSON
            return web.json_response(_jsonable(rows))

        def _jsonable(x):
            if isinstance(x, bytes):
                return x.hex()
            if isinstance(x, dict):
                return {k: _jsonable(v) for k, v in x.items()}
            if isinstance(x, (list, tuple)):
                return [_jsonable(v) for v in x]
            return x

        async def cluster_pgs(_req):
            rows = await offload(lambda: _gcs_call("list_pgs"))
            return web.json_response(_jsonable(rows))

        async def cluster_demand(_req):
            return web.json_response(
                await offload(lambda: _gcs_call("cluster_demand"))
            )

        app = web.Application()
        app.router.add_get("/healthz", healthz)
        if self.gcs_address:
            app.router.add_get("/api/cluster/nodes", cluster_nodes)
            app.router.add_get("/api/cluster/actors", cluster_actors)
            app.router.add_get("/api/cluster/placement_groups", cluster_pgs)
            app.router.add_get("/api/cluster/demand", cluster_demand)
        app.router.add_get("/api/tasks", tasks)
        app.router.add_get("/api/actors", actors)
        app.router.add_get("/api/objects", objects)
        app.router.add_get("/api/nodes", nodes)
        app.router.add_get("/api/placement_groups", pgs)
        app.router.add_get("/api/summary", summary)
        app.router.add_get("/api/cluster_status", cluster_status)
        app.router.add_get("/metrics", metrics)
        app.router.add_get("/timeline", timeline)

        runner = web.AppRunner(app, access_log=None)

        async def _run():
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self._started.set()
            while not self._stop.is_set():
                await asyncio.sleep(0.1)
            await runner.cleanup()

        try:
            loop.run_until_complete(_run())
        except Exception:
            logger.exception("dashboard crashed")
        finally:
            loop.close()

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def start_dashboard(host: str = "127.0.0.1", port: int = 8265,
                    gcs_address: Optional[str] = None) -> Dashboard:
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port, gcs_address=gcs_address)
    return _dashboard


def shutdown_dashboard() -> None:
    global _dashboard
    if _dashboard is not None:
        _dashboard.shutdown()
        _dashboard = None
