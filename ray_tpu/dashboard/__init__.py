"""Minimal dashboard: HTTP state + metrics endpoints.

Reference analog: python/ray/dashboard/ (head.py:62 DashboardHead + the
modules/ API routes + metrics pipeline). Single-host collapse: one
aiohttp server exposing

  /api/tasks /api/actors /api/objects /api/nodes /api/placement_groups
  /api/summary /api/cluster_status   — JSON state (util/state.py)
  /metrics                           — Prometheus text (util/metrics.py)
  /timeline                          — Chrome trace JSON (task events)
  /api/trace[?trace_id=]             — task timeline merged with request
                                       spans + profiler strips (ray_tpu.obs
                                       flight recorder is the one bounded
                                       stream; task-buffer profile copies
                                       are deduped out)
  /api/requests                      — flight-recorder trace listing
  /api/perf                          — sampled step-profiling rollup
                                       (obs.perfwatch, cluster view)
  /healthz                           — liveness

A React UI is out of scope; the JSON surface is the contract the
reference's UI consumes.
"""

from __future__ import annotations

import asyncio
import threading
from typing import Optional

from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.dashboard")

_dashboard: Optional["Dashboard"] = None


class Dashboard:
    """`gcs_address` switches on the CLUSTER view: /api/cluster/* routes
    aggregate the GCS tables plus per-node stats pulled live from every
    node daemon's RPC server — each daemon IS the per-node dashboard
    agent (reference: dashboard/agent.py processes colocated with each
    raylet; here the daemon's rpc_stats/rpc_timeline endpoints fill that
    role without a separate process)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8265,
                 gcs_address: Optional[str] = None):
        self.host = host
        self.port = port
        self.gcs_address = gcs_address
        self._started = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._serve, name="ray_tpu-dashboard", daemon=True
        )
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError(f"dashboard failed to bind {host}:{port}")

    def _serve(self) -> None:
        from aiohttp import web

        from ray_tpu.util import metrics as metrics_mod
        from ray_tpu.util import state

        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)

        def offload(fn, *args):
            return asyncio.get_event_loop().run_in_executor(None, fn, *args)

        async def healthz(_req):
            return web.Response(text="success")

        async def tasks(req):
            st = req.query.get("state")
            rows = await offload(lambda: [vars(r) for r in state.list_tasks(st)])
            return web.json_response(rows)

        async def actors(_req):
            return web.json_response(await offload(state.list_actors))

        async def objects(_req):
            return web.json_response(await offload(state.list_objects))

        async def nodes(_req):
            return web.json_response(await offload(state.list_nodes))

        async def pgs(_req):
            return web.json_response(await offload(state.list_placement_groups))

        async def summary(_req):
            return web.json_response(await offload(state.summarize_tasks))

        async def cluster_status(_req):
            import ray_tpu

            return web.json_response(
                {
                    "cluster_resources": await offload(ray_tpu.cluster_resources),
                    "available_resources": await offload(ray_tpu.available_resources),
                }
            )

        async def metrics(_req):
            return web.Response(
                text=metrics_mod.prometheus_text(),
                content_type="text/plain",
            )

        async def timeline(_req):
            return web.json_response(await offload(state.timeline))

        async def api_trace(req):
            """Request spans (ray_tpu.obs flight recorder) merged with the
            task/profiler timeline as one Chrome trace; ?trace_id= narrows
            both halves to one request. The response is BOUNDED
            (?limit=, default 50k events) with an explicit truncated flag
            — a runaway trace can't produce an export that nothing can
            ship or open."""
            trace_id = req.query.get("trace_id")
            try:
                limit = int(req.query.get("limit", 50_000))
            except ValueError:
                limit = 50_000

            def build():
                from ray_tpu.obs import get_recorder

                # profiler strips reach BOTH sinks (task buffer for the
                # legacy /timeline, flight recorder for this route); the
                # bounded recorder copy is authoritative here, so drop
                # the task-buffer duplicates instead of double-counting
                events = [
                    e for e in state.timeline()
                    if e.get("cat") != "profile"
                ]
                if trace_id:
                    events = [
                        e for e in events
                        if e.get("args", {}).get("trace_id") == trace_id
                    ]
                rec = get_recorder().chrome_trace_bounded(
                    trace_id=trace_id, max_events=limit
                )
                events += rec["events"]
                total = len(events) + (rec["total_spans"]
                                       - len(rec["events"]))
                truncated = rec["truncated"]
                if len(events) > limit:
                    events.sort(key=lambda e: e.get("ts", 0.0))
                    events = events[:limit]
                    truncated = True
                return {"events": events, "truncated": truncated,
                        "total_events": total}

            return web.json_response(await offload(build))

        async def api_requests(_req):
            from ray_tpu.obs import get_recorder

            return web.json_response(get_recorder().traces())

        # -- cluster view: GCS tables + live per-daemon agent stats --------
        # one cached connection per address (reference: rpc client pools);
        # per-request connect/teardown churn would spawn and abandon a
        # reader thread per node per poll
        from ray_tpu.cluster.rpc import ClientPool

        pool = ClientPool(timeout=5.0)
        self._pool = pool

        def _gcs_call(method, payload=None):
            host, port = self.gcs_address.rsplit(":", 1)
            return pool.get((host, int(port))).call(method, payload)

        def _node_call(n, method, payload=None):
            """One agent RPC; evict the cached connection on failure so a
            recovered daemon re-dials clean."""
            addr = tuple(n["addr"])
            try:
                return pool.get(addr).call(method, payload)
            except Exception:
                pool.invalidate(addr)
                raise

        def _agent_stats(n):
            try:  # the daemon doubles as the per-node agent
                n["stats"] = _node_call(n, "stats")
            except Exception as e:  # noqa: BLE001
                n["stats_error"] = repr(e)[:120]
            return n

        def _fan_out(nodes, fn):
            from concurrent.futures import ThreadPoolExecutor

            alive = [n for n in nodes if n.get("alive")]
            if not alive:
                return []
            # fan out: one wedged daemon must not serialize the sweep
            with ThreadPoolExecutor(max_workers=min(16, len(alive))) as ex:
                return list(ex.map(fn, alive))

        def _cluster_nodes():
            nodes = _gcs_call("list_nodes")
            _fan_out(nodes, _agent_stats)
            return nodes

        async def cluster_nodes(_req):
            return web.json_response(await offload(_cluster_nodes))

        async def cluster_actors(_req):
            rows = await offload(lambda: _gcs_call("list_actors"))
            for r in rows:
                r.pop("creation_spec", None)  # pickled blob, not JSON
            return web.json_response(_jsonable(rows))

        def _jsonable(x):
            if isinstance(x, bytes):
                return x.hex()
            if isinstance(x, dict):
                return {k: _jsonable(v) for k, v in x.items()}
            if isinstance(x, (list, tuple)):
                return [_jsonable(v) for v in x]
            return x

        async def cluster_pgs(_req):
            rows = await offload(lambda: _gcs_call("list_pgs"))
            return web.json_response(_jsonable(rows))

        async def cluster_demand(_req):
            return web.json_response(
                await offload(lambda: _gcs_call("cluster_demand"))
            )

        def _cluster_timeline():
            """Chrome-trace events of worker-side execution spans across
            all node daemons (the cross-process half of `ray timeline`;
            driver-side lease/exec spans live in the driver's client)."""

            import time as _time

            # bounded window: shipping each daemon's whole 20k-span
            # buffer per poll grows linearly with cluster size
            since = _time.time() - 600.0

            def pull(n):
                try:
                    return n["node_id"], _node_call(n, "timeline",
                                                    {"since": since})
                except Exception:  # noqa: BLE001
                    return n["node_id"], []

            events = []
            for node_id, spans in _fan_out(_gcs_call("list_nodes"), pull):
                for s in spans:
                    events.append({
                        "name": s.get("desc", "task"),
                        "ph": "X",
                        "ts": float(s.get("start", 0.0)) * 1e6,
                        "dur": max(
                            0.0,
                            float(s.get("end", 0.0)) - float(s.get("start", 0.0)),
                        ) * 1e6,
                        "pid": node_id,
                        "tid": s.get("worker_id", "worker"),
                        "cat": "exec" if s.get("ok", True) else "error",
                        **({"args": {"trace_id": s["trace_id"],
                                     "span_id": s.get("span_id")}}
                           if s.get("trace_id") else {}),
                    })
            return events

        async def cluster_timeline(_req):
            return web.json_response(await offload(_cluster_timeline))

        # -- telemetry plane (ray_tpu.obs.telemetry via the GCS store) -----

        async def api_metrics_cluster(_req):
            """Cluster-level aggregate: counter sums + rates, gauge
            rollups, merged histograms w/ percentiles, staleness."""
            return web.json_response(
                await offload(lambda: _gcs_call("telemetry_cluster"))
            )

        async def api_slo(_req):
            """Per-model-tag SLO grades from the MERGED TTFT/TPOT/queue
            histograms (the autoscaler's input)."""
            return web.json_response(
                await offload(lambda: _gcs_call("telemetry_slo"))
            )

        async def api_perf(_req):
            """Sampled step-profiling rollup (obs.perfwatch): per-step
            segment times, coverage, MFU, overlap, and regression grades
            vs the best-seen sample."""
            return web.json_response(
                await offload(lambda: _gcs_call("telemetry_perf"))
            )

        async def metrics_cluster(_req):
            """Merged Prometheus exposition: the fleet analog of each
            process's /metrics."""
            return web.Response(
                text=await offload(lambda: _gcs_call("telemetry_prometheus")),
                content_type="text/plain",
            )

        app = web.Application()
        app.router.add_get("/healthz", healthz)
        if self.gcs_address:
            app.router.add_get("/api/cluster/nodes", cluster_nodes)
            app.router.add_get("/api/cluster/actors", cluster_actors)
            app.router.add_get("/api/cluster/placement_groups", cluster_pgs)
            app.router.add_get("/api/cluster/demand", cluster_demand)
            app.router.add_get("/api/cluster/timeline", cluster_timeline)
            app.router.add_get("/api/metrics/cluster", api_metrics_cluster)
            app.router.add_get("/api/slo", api_slo)
            app.router.add_get("/api/perf", api_perf)
            app.router.add_get("/metrics/cluster", metrics_cluster)
        app.router.add_get("/api/tasks", tasks)
        app.router.add_get("/api/actors", actors)
        app.router.add_get("/api/objects", objects)
        app.router.add_get("/api/nodes", nodes)
        app.router.add_get("/api/placement_groups", pgs)
        app.router.add_get("/api/summary", summary)
        app.router.add_get("/api/cluster_status", cluster_status)
        app.router.add_get("/metrics", metrics)
        app.router.add_get("/timeline", timeline)
        app.router.add_get("/api/trace", api_trace)
        app.router.add_get("/api/requests", api_requests)

        runner = web.AppRunner(app, access_log=None)

        async def _run():
            await runner.setup()
            site = web.TCPSite(runner, self.host, self.port)
            await site.start()
            self._started.set()
            while not self._stop.is_set():
                await asyncio.sleep(0.1)
            await runner.cleanup()

        try:
            loop.run_until_complete(_run())
        except Exception:
            logger.exception("dashboard crashed")
        finally:
            if getattr(self, "_pool", None) is not None:
                self._pool.close_all()
            loop.close()

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def start_dashboard(host: str = "127.0.0.1", port: int = 8265,
                    gcs_address: Optional[str] = None) -> Dashboard:
    global _dashboard
    if _dashboard is None:
        _dashboard = Dashboard(host, port, gcs_address=gcs_address)
    return _dashboard


def shutdown_dashboard() -> None:
    global _dashboard
    if _dashboard is not None:
        _dashboard.shutdown()
        _dashboard = None
