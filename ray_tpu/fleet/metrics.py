"""Fleet-plane metrics: adapter residency churn, canary outcomes, and
per-tenant routing volume. All cluster-aggregated (SUM for counters,
SUM for gauges) and declared via the telemetry helpers so
scripts/check_metrics.py can verify the aggregation contract.
"""

from __future__ import annotations


def adapter_load_counter():
    """Adapter loads into an engine slot, by model. Together with the
    eviction counter it prices slot-budget pressure: a high evict/load
    ratio means max_loras is too small for the working set."""
    from ray_tpu.obs.telemetry import cluster_counter

    return cluster_counter(
        "fleet_adapter_loads_total",
        description="LoRA adapters loaded into an engine slot by the "
        "fleet manager, by base model",
        tag_keys=("model",),
    )


def adapter_evict_counter():
    from ray_tpu.obs.telemetry import cluster_counter

    return cluster_counter(
        "fleet_adapter_evictions_total",
        description="LoRA adapters LRU-evicted from an engine slot to "
        "make room, by base model",
        tag_keys=("model",),
    )


def canary_counter():
    """Canary rollouts by terminal outcome (promoted / rolled_back /
    aborted): the fleet's weight-rollout audit trail."""
    from ray_tpu.obs.telemetry import cluster_counter

    return cluster_counter(
        "fleet_canary_rollouts_total",
        description="canary weight rollouts completed, by base model "
        "and outcome (promoted/rolled_back/aborted)",
        tag_keys=("model", "outcome"),
    )


def tenant_requests_counter():
    """Requests routed per (tenant, model): the denominator for the
    per-tenant shed rate llm_admission_rejected_total{tenant} is the
    numerator of."""
    from ray_tpu.obs.telemetry import cluster_counter

    return cluster_counter(
        "fleet_tenant_requests_total",
        description="requests admitted and routed by the fleet, by "
        "tenant and base model",
        tag_keys=("tenant", "model"),
    )


def resident_adapters_gauge():
    from ray_tpu.obs.telemetry import cluster_gauge

    return cluster_gauge(
        "fleet_resident_adapters",
        description="LoRA adapters currently resident across a model's "
        "replicas (sums across replicas)",
        tag_keys=("model",),
    )


def register_metrics() -> None:
    """scripts/check_metrics.py hook: force lazy metrics to register."""
    adapter_load_counter()
    adapter_evict_counter()
    canary_counter()
    tenant_requests_counter()
    resident_adapters_gauge()
