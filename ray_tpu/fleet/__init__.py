"""ray_tpu.fleet — multi-tenant model fleet (ROADMAP item 3, r21).

Maps {base models x LoRA adapters x tenants} onto replica pools:
model-aware prefix/residency routing, dynamic adapter load/evict
against each engine's slot budget, per-tenant weighted-fair admission
with priority preemption, and a versioned canary weight-rollout plane
over the fabric (promote-on-green / rollback-on-red, bitwise-gated).
"""

from ray_tpu.fleet.config import (
    AdapterSpec,
    CanaryStateError,
    FleetError,
    FleetSpec,
    ModelSpec,
    TenantSpec,
    UnknownModelError,
    UnknownTenantError,
)
from ray_tpu.fleet.ingress import FleetServer
from ray_tpu.fleet.manager import (
    FleetAdmissionRejected,
    FleetManager,
    FleetReplica,
    FleetTicket,
)
from ray_tpu.fleet.qos import TenantQoSController
from ray_tpu.fleet.weights import (
    FleetWeightPlane,
    bitwise_equal,
    local_slo_histograms,
)

__all__ = [
    "AdapterSpec",
    "CanaryStateError",
    "FleetAdmissionRejected",
    "FleetError",
    "FleetManager",
    "FleetReplica",
    "FleetServer",
    "FleetSpec",
    "FleetTicket",
    "FleetWeightPlane",
    "ModelSpec",
    "TenantSpec",
    "TenantQoSController",
    "UnknownModelError",
    "UnknownTenantError",
    "bitwise_equal",
    "local_slo_histograms",
]
