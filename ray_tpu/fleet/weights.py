"""FleetWeightPlane: versioned weight distribution + the canary ladder.

Generalizes the r15 learner->rollout publish plane (train/weight_sync)
from ONE params stream to the fleet's {base model x adapter} matrix:

 * **per-(model, adapter) version vectors** — every base model keeps its
   own ``WeightPublisher`` (monotonic versions, checksum-verified device
   bundles, publish_latest for cold-started late joiners); adapter
   payloads ride the same fabric transport as ``(target, (A, B))``
   bundles to a per-replica adapter endpoint, so base and adapter
   updates share one verification and versioning discipline;
 * **canary-one-replica rollout** — ``begin_canary`` applies a new
   version to exactly ONE replica (replica engine tags are
   replica-scoped, so the r11 grade machinery can grade the canary in
   isolation); ``canary_grade`` grades only traffic observed SINCE the
   canary started (delta against a histogram snapshot — SLO histograms
   are cumulative); ``promote`` ships the same bundle to the rest of the
   pool, ``rollback`` re-publishes the retained previous weights (as a
   NEW monotonic version — subscribers never apply backwards);
 * **bitwise identity gates** — promote verifies every replica's
   resident arrays equal the canary's bit-for-bit; rollback verifies the
   canary equals the retained pre-canary weights. A checksum-green
   transfer that still produced divergent residency is a refused
   rollout, not a warning;
 * **scoped invalidation** — a base swap drops every salt's prefix
   chains (all were computed under the old weights: subscriber
   ``apply_to_engine`` cascades the full drop); an adapter swap drops
   exactly the swapped adapter's salt (``remove_lora`` scopes the
   cascade) so co-resident tenants keep their cached prefixes.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.fabric.transport import DeviceTransport, FabricTransferError
from ray_tpu.fleet import metrics as fleet_metrics
from ray_tpu.fleet.config import CanaryStateError, FleetError
from ray_tpu.obs import slo as slo_metrics
from ray_tpu.obs.telemetry import (
    GRADE_GREEN,
    GRADE_RED,
    SLO_HISTOGRAMS,
    SLOThresholds,
    evaluate_slo,
)
from ray_tpu.train.weight_sync import (
    WeightPublisher,
    WeightSubscriber,
    WeightSyncError,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.fleet.weights")

_SLO_SOURCES = {
    "ttft": slo_metrics.ttft_histogram,
    "tpot": slo_metrics.tpot_histogram,
    "queue_wait": slo_metrics.queue_wait_histogram,
}


def _slo_snapshot() -> dict:
    """Process-local SLO histograms in ``evaluate_slo``'s input shape:
    {registry_name: {tag: {"boundaries","buckets","sum","count"}}}."""
    out: dict = {}
    for short, fq in SLO_HISTOGRAMS.items():
        h = _SLO_SOURCES[short]()
        per: dict = {}
        for key, (buckets, total, count) in h.hist_data().items():
            tag = key[0] if key else ""
            per[tag] = {
                "boundaries": list(h.boundaries),
                "buckets": list(buckets),
                "sum": float(total),
                "count": int(count),
            }
        out[fq] = per
    return out


def local_slo_histograms(baseline: Optional[dict] = None) -> dict:
    """Current process-local SLO histograms, optionally as the DELTA
    since ``baseline`` (an earlier ``local_slo_histograms()`` result).
    Histograms are cumulative, so grading a canary means grading the
    difference — pre-canary traffic must not vote."""
    snap = _slo_snapshot()
    if baseline is None:
        return snap
    for name, per in snap.items():
        base_per = baseline.get(name) or {}
        for tag, h in per.items():
            b = base_per.get(tag)
            if b is None:
                continue
            h["buckets"] = [
                max(0, n - m) for n, m in zip(h["buckets"], b["buckets"])
            ]
            h["sum"] = max(0.0, h["sum"] - b["sum"])
            h["count"] = max(0, h["count"] - b["count"])
    return snap


def _tree_leaves_np(tree: Any) -> list:
    import jax
    import numpy as np

    return [np.asarray(leaf) for leaf in jax.tree_util.tree_leaves(tree)]


def _resident_adapter(engine: Any, adapter_id: str,
                      targets) -> Optional[dict]:
    """The adapter arrays a replica is actually serving (its slot slices
    of the stacked LoRA buffers), or None when not resident."""
    slot = engine._lora_slots.get(adapter_id)
    if slot is None:
        return None
    return {
        t: (engine._lora[f"{t}_A"][:, slot], engine._lora[f"{t}_B"][:, slot])
        for t in targets
    }


def _cast_payload(payload: dict, dtype: Any) -> dict:
    """A host payload as the engine will hold it — ``add_lora`` casts to
    the model dtype, so the bitwise gate must compare post-cast bytes
    (what the replica serves), not the host-side float32 source."""
    import jax.numpy as jnp

    return {
        t: (jnp.asarray(A, dtype), jnp.asarray(B, dtype))
        for t, (A, B) in payload.items()
    }


def bitwise_equal(a: Any, b: Any) -> bool:
    """Bit-for-bit identity of two pytrees (same leaf count, every leaf
    array_equal). The promotion gate: a rollout that changed anything it
    wasn't asked to change is refused."""
    import numpy as np

    la, lb = _tree_leaves_np(a), _tree_leaves_np(b)
    if len(la) != len(lb):
        return False
    return all(
        x.shape == y.shape and x.dtype == y.dtype and np.array_equal(x, y)
        for x, y in zip(la, lb)
    )


class FleetWeightPlane:
    """The fleet's weight-distribution control plane. One instance per
    FleetManager; replicas attach/detach as pools grow and shrink."""

    def __init__(self, manager: Any, namespace: str = "fleet-weights",
                 thresholds: Optional[SLOThresholds] = None):
        self.manager = manager
        self.thresholds = thresholds or SLOThresholds()
        self.transport = DeviceTransport(namespace=namespace)
        self._lock = threading.RLock()
        self._pubs: Dict[str, WeightPublisher] = {}      # model -> publisher
        self._subs: Dict[str, WeightSubscriber] = {}     # tag -> base sub
        self._targets: Dict[str, tuple] = {}             # tag -> base target
        self._adapter_eps: Dict[str, str] = {}           # tag -> endpoint id
        self._adapter_targets: Dict[str, tuple] = {}     # tag -> send target
        # the version vector: (model, adapter|None) -> fleet-wide version
        self.versions: Dict[Tuple[str, Optional[str]], int] = {}
        # newest registered adapter payloads ({target: (A, B)}, host-side)
        self._adapters: Dict[Tuple[str, str], dict] = {}
        # what each replica is actually serving:
        # (tag, adapter) -> resident version
        self._resident: Dict[Tuple[str, str], int] = {}
        self._canary: Optional[dict] = None
        self.timeline: List[dict] = []
        self._t0 = time.monotonic()

    # -- replica attach/detach ------------------------------------------------

    def _publisher(self, model_id: str) -> WeightPublisher:
        with self._lock:
            pub = self._pubs.get(model_id)
            if pub is None:
                pub = WeightPublisher(transport=self.transport)
                self._pubs[model_id] = pub
            return pub

    def attach_replica(self, replica: Any) -> None:
        """Register a replica's base + adapter endpoints; a late joiner
        (pool scale-up after a publish) streams the fleet's current base
        weights at the current version before taking traffic."""
        pub = self._publisher(replica.model_id)
        base_ep = f"fleet/{replica.tag}/base"
        target = pub.register_rollout(base_ep)
        adapter_ep = f"fleet/{replica.tag}/adapters"
        adapter_target = self.transport.register_endpoint(adapter_ep)
        with self._lock:
            self._targets[replica.tag] = target
            self._subs[replica.tag] = WeightSubscriber(self.transport, base_ep)
            self._adapter_eps[replica.tag] = adapter_ep
            self._adapter_targets[replica.tag] = adapter_target
        if pub.latest_version > 0:
            try:
                pub.publish_latest(target)
                self._apply_base(replica)
            except WeightSyncError:
                logger.exception("late-join stream to %s failed", replica.tag)

    def detach_replica(self, replica: Any) -> None:
        with self._lock:
            self._targets.pop(replica.tag, None)
            sub = self._subs.pop(replica.tag, None)
            adapter_ep = self._adapter_eps.pop(replica.tag, None)
            self._adapter_targets.pop(replica.tag, None)
            for key in [k for k in self._resident if k[0] == replica.tag]:
                self._resident.pop(key, None)
        if sub is not None:
            sub.close()
        if adapter_ep is not None:
            try:
                while self.transport.recv_arrays(
                        adapter_ep, timeout_s=0.0) is not None:
                    pass
            except FabricTransferError:
                pass

    def _event(self, event: str, **fields) -> dict:
        row = {"t_s": round(time.monotonic() - self._t0, 4),
               "event": event, **fields}
        with self._lock:
            self.timeline.append(row)
        return row

    # -- base-weight distribution ---------------------------------------------

    def _apply_base(self, replica: Any) -> Optional[int]:
        with self._lock:
            sub = self._subs.get(replica.tag)
        if sub is None:
            return None
        with replica.runner.lock:
            return sub.apply_to_engine(replica.engine)

    def publish_base(self, model_id: str, params: Any,
                     exclude: tuple = ()) -> int:
        """Ship a new base-weight version to every replica of
        ``model_id`` (minus ``exclude`` tags) and apply it. Returns the
        published version; the version vector advances."""
        replicas = [
            r for r in self.manager.replicas(model_id)
            if r.tag not in exclude
        ]
        pub = self._publisher(model_id)
        with self._lock:
            targets = [self._targets[r.tag] for r in replicas]
        version = pub.publish(params, targets)
        for r in replicas:
            self._apply_base(r)
        with self._lock:
            self.versions[(model_id, None)] = version
        self._event("publish_base", model=model_id, version=version,
                    replicas=[r.tag for r in replicas])
        return version

    # -- adapter distribution (same fabric, per-replica endpoints) ------------

    def _ship_adapter(self, tag: str, model_id: str, adapter_id: str,
                      payload: dict, version: int,
                      timeout_s: float = 30.0) -> dict:
        """Send one adapter bundle over the fabric to a replica's
        adapter endpoint and receive it back verified — the adapter path
        gets the same checksum gate as base weights. Returns the
        RECEIVED payload (the bytes the replica will actually load)."""
        with self._lock:
            ep = self._adapter_eps.get(tag)
            send_target = self._adapter_targets.get(tag)
        if ep is None or send_target is None:
            raise FleetError(f"replica {tag!r} not attached")
        arrays = {}
        for t, (A, B) in payload.items():
            arrays[f"{t}.A"] = A
            arrays[f"{t}.B"] = B
        meta = {"kind": "adapter", "model": model_id, "adapter": adapter_id,
                "version": int(version), "targets": sorted(payload)}
        try:
            self.transport.send_arrays(
                send_target, arrays, meta=meta, timeout_s=timeout_s,
                bundle_id=f"adapter-{adapter_id}-v{version}",
            )
        except FabricTransferError as e:
            raise WeightSyncError(
                f"adapter publish {adapter_id!r} v{version} to {tag} "
                f"failed: {e}"
            ) from e
        newest = None
        while True:
            b = self.transport.recv_arrays(ep, timeout_s=timeout_s)
            if b is None:
                break
            timeout_s = 0.0
            if not b.verify():
                continue
            if newest is None or int(b.meta["version"]) >= int(
                    newest.meta["version"]):
                newest = b
        if newest is None:
            raise WeightSyncError(
                f"adapter bundle {adapter_id!r} v{version} never arrived "
                f"verified at {tag}"
            )
        return {
            t: (newest.arrays[f"{t}.A"], newest.arrays[f"{t}.B"])
            for t in newest.meta["targets"]
        }

    def _swap_adapter(self, replica: Any, adapter_id: str,
                      payload: dict, version: int) -> bool:
        """Load ``payload`` as ``adapter_id`` on one replica (removing
        the resident copy first — a scoped prefix drop for exactly this
        adapter's salt). Returns False when in-flight requests pin the
        slot (the replica keeps serving its resident version)."""
        received = self._ship_adapter(
            replica.tag, replica.model_id, adapter_id, payload, version
        )
        with replica.runner.lock:
            eng = replica.engine
            if adapter_id in eng._lora_slots:
                try:
                    eng.remove_lora(adapter_id)
                except ValueError:
                    return False  # in-flight refs pin the old version
            eng.add_lora(adapter_id, received, evict=True)
        with self._lock:
            self._resident[(replica.tag, adapter_id)] = version
        fleet_metrics.adapter_load_counter().inc(
            1, tags={"model": replica.model_id}
        )
        return True

    def publish_adapter(self, model_id: str, adapter_id: str,
                        payload: dict) -> int:
        """Register (or version-bump) an adapter. Replicas where it is
        resident are swapped in place over the fabric; elsewhere it
        loads lazily at routing time. Returns the new version."""
        with self._lock:
            version = self.versions.get((model_id, adapter_id), 0) + 1
            self.versions[(model_id, adapter_id)] = version
            self._adapters[(model_id, adapter_id)] = dict(payload)
        deferred = []
        for r in self.manager.replicas(model_id):
            if adapter_id in r.engine._lora_slots:
                if not self._swap_adapter(r, adapter_id, payload, version):
                    deferred.append(r.tag)
        self._event("publish_adapter", model=model_id, adapter=adapter_id,
                    version=version, deferred=deferred)
        return version

    def adapter_payload(self, model_id: str, adapter_id: str) -> dict:
        with self._lock:
            payload = self._adapters.get((model_id, adapter_id))
        if payload is None:
            raise FleetError(
                f"adapter {adapter_id!r} not registered for model "
                f"{model_id!r}"
            )
        return payload

    def adapter_version(self, model_id: str, adapter_id: str) -> int:
        with self._lock:
            return self.versions.get((model_id, adapter_id), 0)

    def resident_version(self, tag: str, adapter_id: str) -> int:
        with self._lock:
            return self._resident.get((tag, adapter_id), 0)

    def note_resident(self, tag: str, adapter_id: str, version: int) -> None:
        with self._lock:
            self._resident[(tag, adapter_id)] = version

    def resident_payloads(self, model_id: str):
        """(adapter_id, payload) pairs for every registered adapter of a
        model — the rung-3 engine-rebuild reload set."""
        with self._lock:
            return [
                (aid, dict(p)) for (mid, aid), p in self._adapters.items()
                if mid == model_id
            ]

    # -- the canary ladder ----------------------------------------------------

    @property
    def canary(self) -> Optional[dict]:
        with self._lock:
            return dict(self._canary) if self._canary else None

    def begin_canary(self, model_id: str, params: Any = None,
                     adapter_id: Optional[str] = None,
                     payload: Optional[dict] = None) -> dict:
        """Apply a candidate version to exactly ONE replica and start
        grading it. Pass ``params`` for a base rollout or
        ``adapter_id`` + ``payload`` for an adapter rollout."""
        if (params is None) == (payload is None):
            raise ValueError("pass exactly one of params / adapter payload")
        with self._lock:
            if self._canary is not None:
                raise CanaryStateError(
                    f"canary already in flight: {self._canary['model']} "
                    f"v{self._canary['version']}"
                )
        replicas = self.manager.replicas(model_id)
        canary = replicas[-1]  # newest replica: least accumulated history
        if params is not None:
            pub = self._publisher(model_id)
            prev = pub._latest_params
            if prev is None:
                prev = canary.engine.params
            with self._lock:
                target = self._targets[canary.tag]
            version = pub.publish(params, [target])
            self._apply_base(canary)
            kind = "base"
        else:
            if payload is None or adapter_id is None:
                raise ValueError("adapter canary needs adapter_id + payload")
            with self._lock:
                prev = self._adapters.get((model_id, adapter_id))
                version = self.versions.get((model_id, adapter_id), 0) + 1
            if not self._swap_adapter(canary, adapter_id, payload, version):
                raise CanaryStateError(
                    f"canary slot for {adapter_id!r} pinned by in-flight "
                    f"requests on {canary.tag}"
                )
            kind = "adapter"
        state = {
            "model": model_id, "kind": kind, "adapter": adapter_id,
            "version": version, "replica": canary.tag,
            "prev": prev, "new": params if params is not None else payload,
            "baseline": _slo_snapshot(),
        }
        with self._lock:
            self._canary = state
        fleet_metrics.canary_counter().inc(
            1, tags={"model": model_id, "outcome": "started"}
        )
        self._event("canary_begin", model=model_id, kind=kind,
                    adapter=adapter_id, version=version, replica=canary.tag)
        return {k: state[k] for k in
                ("model", "kind", "adapter", "version", "replica")}

    def _require_canary(self) -> dict:
        with self._lock:
            if self._canary is None:
                raise CanaryStateError("no canary in flight")
            return self._canary

    def canary_grade(self) -> dict:
        """Grade the canary replica on traffic SINCE the canary began.
        Returns {"grade", "detail"} — the r11 grade ladder's verdict
        scoped to the one replica-tagged series."""
        state = self._require_canary()
        hists = local_slo_histograms(baseline=state["baseline"])
        report = evaluate_slo(hists, self.thresholds)
        entry = report["model_tags"].get(state["replica"])
        grade = entry["grade"] if entry else "no_data"
        self._event("canary_grade", replica=state["replica"], grade=grade)
        return {"grade": grade, "detail": entry}

    def _canary_replica(self, state: dict) -> Any:
        for r in self.manager.replicas(state["model"]):
            if r.tag == state["replica"]:
                return r
        raise FleetError(f"canary replica {state['replica']} left the pool")

    def promote(self) -> dict:
        """Roll the canary's version out to every other replica, gated
        on bitwise identity: after the fan-out, each replica's resident
        weights must equal the canary's bit-for-bit."""
        state = self._require_canary()
        model_id, version = state["model"], state["version"]
        canary = self._canary_replica(state)
        others = [
            r for r in self.manager.replicas(model_id) if r.tag != canary.tag
        ]
        if state["kind"] == "base":
            pub = self._publisher(model_id)
            with self._lock:
                targets = [self._targets[r.tag] for r in others]
            if targets:
                pub.publish(state["new"], targets, version=version)
            for r in others:
                self._apply_base(r)
            with self._lock:
                self.versions[(model_id, None)] = version
            mismatched = [
                r.tag for r in others
                if not bitwise_equal(r.engine.params, canary.engine.params)
            ]
        else:
            adapter_id = state["adapter"]
            with self._lock:
                self.versions[(model_id, adapter_id)] = version
                self._adapters[(model_id, adapter_id)] = dict(state["new"])
            canary_resident = _resident_adapter(
                canary.engine, adapter_id, state["new"]
            )
            mismatched = []
            for r in others:
                if adapter_id not in r.engine._lora_slots:
                    continue  # loads lazily (and freshly) at routing time
                if not self._swap_adapter(
                        r, adapter_id, state["new"], version):
                    mismatched.append(r.tag)
                    continue
                resident = _resident_adapter(
                    r.engine, adapter_id, state["new"]
                )
                if canary_resident is None or resident is None or not all(
                        bitwise_equal(resident[t], canary_resident[t])
                        for t in state["new"]):
                    mismatched.append(r.tag)
        if mismatched:
            fleet_metrics.canary_counter().inc(
                1, tags={"model": model_id, "outcome": "promote_failed"}
            )
            self._event("canary_promote_failed", model=model_id,
                        version=version, mismatched=mismatched)
            raise WeightSyncError(
                f"promote v{version} refused: replicas {mismatched} are "
                "not bitwise-identical to the canary after fan-out"
            )
        with self._lock:
            self._canary = None
        fleet_metrics.canary_counter().inc(
            1, tags={"model": model_id, "outcome": "promoted"}
        )
        self._event("canary_promote", model=model_id, version=version,
                    replicas=[r.tag for r in others])
        return {"outcome": "promoted", "model": model_id,
                "version": version, "replicas": [r.tag for r in others]}

    def rollback(self) -> dict:
        """Revert the canary replica to the retained pre-canary weights.
        Subscribers never apply backwards, so the old bytes ship as a
        NEW monotonic version — gated on bitwise identity with the
        retained copy."""
        state = self._require_canary()
        model_id = state["model"]
        canary = self._canary_replica(state)
        prev = state["prev"]
        if prev is None:
            raise WeightSyncError(
                f"rollback of {state['adapter']!r}: no previous version "
                "retained (canary was the first publish)"
            )
        if state["kind"] == "base":
            pub = self._publisher(model_id)
            with self._lock:
                target = self._targets[canary.tag]
            rb_version = pub.publish(prev, [target])
            self._apply_base(canary)
            identical = bitwise_equal(canary.engine.params, prev)
        else:
            adapter_id = state["adapter"]
            rb_version = state["version"] + 1
            ok = self._swap_adapter(canary, adapter_id, prev, rb_version)
            resident = (
                _resident_adapter(canary.engine, adapter_id, prev)
                if ok else None
            )
            expected = _cast_payload(prev, canary.engine.config.model.dtype)
            identical = resident is not None and all(
                bitwise_equal(resident[t], expected[t]) for t in prev
            )
            with self._lock:
                # the fleet's registered payload stays the pre-canary one
                self._adapters[(model_id, adapter_id)] = dict(prev)
                self.versions[(model_id, adapter_id)] = rb_version
        with self._lock:
            self._canary = None
        if not identical:
            fleet_metrics.canary_counter().inc(
                1, tags={"model": model_id, "outcome": "rollback_failed"}
            )
            raise WeightSyncError(
                f"rollback on {canary.tag} is NOT bitwise-identical to "
                "the retained pre-canary weights"
            )
        fleet_metrics.canary_counter().inc(
            1, tags={"model": model_id, "outcome": "rolled_back"}
        )
        self._event("canary_rollback", model=model_id,
                    version=rb_version, replica=canary.tag)
        return {"outcome": "rolled_back", "model": model_id,
                "version": rb_version, "replica": canary.tag}

    def decide(self, grade: Optional[str] = None) -> dict:
        """The closed-loop step: promote on green, roll back on red,
        hold on yellow/no_data (more traffic decides)."""
        if grade is None:
            grade = self.canary_grade()["grade"]
        if grade == GRADE_GREEN:
            return self.promote()
        if grade == GRADE_RED:
            return self.rollback()
        self._event("canary_hold", grade=grade)
        return {"outcome": "hold", "grade": grade}

    # -- observability / lifecycle --------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            canary = self._canary
            return {
                "versions": {
                    f"{m}:{a}" if a else m: v
                    for (m, a), v in sorted(
                        self.versions.items(),
                        key=lambda kv: (kv[0][0], kv[0][1] or ""),
                    )
                },
                "registered_adapters": sorted(
                    f"{m}:{a}" for m, a in self._adapters
                ),
                "canary": (
                    {k: canary[k] for k in
                     ("model", "kind", "adapter", "version", "replica")}
                    if canary else None
                ),
                "timeline_events": len(self.timeline),
            }

    def close(self) -> None:
        with self._lock:
            pubs = list(self._pubs.values())
            subs = list(self._subs.values())
            self._pubs.clear()
            self._subs.clear()
            self._targets.clear()
            self._adapter_eps.clear()
        for sub in subs:
            sub.close()
        for pub in pubs:
            pub.close()
        self.transport.close()
