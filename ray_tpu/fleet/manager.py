"""FleetManager: {base models x LoRA adapters x tenants} onto replica pools.

The composition layer ROADMAP item 3 names: every ingredient exists —
engine LoRA slots (r12), prefix-aware routing (r17/r18), the admission/
preemption ladder (r09), per-tag SLO grading (r11), the weight-publish
plane (r15) — and this module wires them into one multi-tenant fleet:

 * **replica pools** — per base model, each replica an ``LLMEngine``
   behind the reused ``_EngineRunner`` loop (crash recovery, idempotent
   delivery, and the 3-rung ladder come for free);
 * **model-aware routing** — the r17/r18 prefix-aware pick layered with
   adapter residency and queue depth: ``route()`` scores each replica by
   tier-discounted resident prefix tokens (LoRA ids already salt the
   chains) + an adapter-residency bonus - load;
 * **dynamic adapter residency** — ``ensure_adapter`` loads a requested
   adapter into the replica's slot budget, LRU-evicting an idle one when
   full (``AdapterSlotsExhausted`` falls back to the next-best replica);
 * **tenant QoS** — admission rides qos.TenantQoSController; the
   tenant's priority rides every request into the engine where it orders
   admission and arms priority preemption.

Replica engine tags are replica-scoped (``model@rN``) so the SLO plane
can grade a single replica (the canary ladder's input); tenant-scoped
series ride each request's ``slo_tag``.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue as queue_mod
import threading
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.fleet import metrics as fleet_metrics
from ray_tpu.fleet.config import (
    FleetError,
    FleetSpec,
    UnknownModelError,
)
from ray_tpu.fleet.qos import TenantQoSController
from ray_tpu.llm.engine import (
    AdapterSlotsExhausted,
    EngineConfig,
    LLMEngine,
    SamplingParams,
)
from ray_tpu.llm.openai_api import _EngineRunner
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.fleet.manager")

# routing weights: a discounted resident-prefix token is worth admitting
# ~W_PREFIX queue positions of extra load (same shape as the disagg
# decode pick); adapter residency saves a load+possible-evict, priced as
# a flat bonus
W_PREFIX = 0.05
W_LOAD = 1.0
RESIDENT_BONUS = 2.0


class FleetAdmissionRejected(FleetError):
    """QoS shed: carries the 429/503 OpenAI-style payload."""

    def __init__(self, payload: dict):
        self.payload = payload
        err = payload.get("error", {})
        super().__init__(err.get("message", "admission rejected"))

    @property
    def code(self) -> int:
        return int(self.payload.get("error", {}).get("code", 429))


@dataclasses.dataclass
class FleetTicket:
    """One admitted request: the runner queue to consume plus the
    bookkeeping ``collect``/``abort`` need to settle QoS state."""

    request_id: str
    queue: Any
    replica: "FleetReplica"
    tenant_id: str
    model_id: str
    adapter_id: Optional[str] = None
    _released: bool = False


class FleetReplica:
    """One serving replica: an engine behind an _EngineRunner loop."""

    def __init__(self, model_id: str, tag: str, runner: _EngineRunner):
        self.model_id = model_id
        self.tag = tag
        self.runner = runner

    @property
    def engine(self) -> LLMEngine:
        return self.runner.engine

    def load(self) -> int:
        eng = self.engine
        return len(eng.waiting) + len(eng.running)

    def resident_adapters(self) -> List[str]:
        return list(self.engine._lora_slots)

    def prefix_score(self, prompt_ids: list,
                     adapter_id: Optional[str]) -> float:
        """Tier-discounted resident prefix tokens for this prompt under
        the right LoRA salt (0.0 when the adapter isn't resident — its
        chains can't be resident either)."""
        eng = self.engine
        if adapter_id is not None and adapter_id not in eng._lora_slots:
            return 0.0
        try:
            got = eng.peek_prefix_tiered(prompt_ids, lora_id=adapter_id)
            return float(got.get("discounted", 0.0))
        except Exception:  # noqa: BLE001 — scoring must not fail routing
            return 0.0

    def shutdown(self) -> None:
        self.runner.shutdown()


class FleetManager:
    """The fleet control plane: pools, routing, QoS, adapter residency.

    ``engine_config`` may be one EngineConfig for every model, a
    {model_id: EngineConfig} dict, or a callable model_id -> config;
    same for ``params`` (None = random init per engine seed)."""

    def __init__(
        self,
        spec: FleetSpec,
        engine_config: Any = None,
        params: Any = None,
        seed: int = 0,
        thresholds: Any = None,
    ):
        from ray_tpu.fleet.weights import FleetWeightPlane

        self.spec = spec
        self.seed = seed
        self._engine_config = engine_config
        self._params = params
        self.qos = TenantQoSController(spec)
        self._lock = threading.RLock()
        self._replicas: Dict[str, List[FleetReplica]] = {}
        self._replica_seq = itertools.count()
        # lifetime routed-request counts: an epsilon tiebreak so equal
        # instantaneous load round-robins instead of pinning the first
        # replica (a sequential submit-collect client would otherwise
        # never exercise replica N — including the canary)
        self._routed: Dict[str, int] = {}
        self.weights = FleetWeightPlane(self, thresholds=thresholds)
        self._closed = False
        for m in spec.models:
            for _ in range(m.replicas):
                self._spawn_replica(m.model_id)

    # -- replica lifecycle ----------------------------------------------------

    def _config_for(self, model_id: str) -> EngineConfig:
        ec = self._engine_config
        if callable(ec):
            cfg = ec(model_id)
        elif isinstance(ec, dict):
            cfg = ec.get(model_id) or EngineConfig()
        else:
            cfg = ec or EngineConfig()
        # replicas must not share a mutable config object (the serving
        # layer historically writes eos_token_id into it)
        return dataclasses.replace(cfg)

    def _params_for(self, model_id: str) -> Any:
        p = self._params
        if callable(p):
            return p(model_id)
        if isinstance(p, dict):
            return p.get(model_id)
        return p

    def _spawn_replica(self, model_id: str) -> FleetReplica:
        cfg = self._config_for(model_id)
        params = self._params_for(model_id)
        tag = f"{model_id}@r{next(self._replica_seq)}"
        weights = self.weights

        def _build() -> LLMEngine:
            eng = LLMEngine(cfg, params=params, seed=self.seed)
            eng.model_tag = tag
            # a rebuilt engine lost its adapter slots: reload what the
            # registry holds so in-flight lora requests can recompute
            for aid, payload in weights.resident_payloads(model_id):
                try:
                    eng.add_lora(aid, payload)
                except Exception:  # noqa: BLE001 — slot budget may differ
                    logger.exception("adapter %r reload failed", aid)
            return eng

        engine = LLMEngine(cfg, params=params, seed=self.seed)
        engine.model_tag = tag
        runner = _EngineRunner(engine, engine_factory=_build)
        replica = FleetReplica(model_id, tag, runner)
        with self._lock:
            self._replicas.setdefault(model_id, []).append(replica)
        # late joiner: stream the fleet's current base weights at the
        # current version (the r20 cold-start path, reused per model)
        self.weights.attach_replica(replica)
        logger.info("spawned replica %s", tag)
        return replica

    def replicas(self, model_id: str) -> List[FleetReplica]:
        with self._lock:
            reps = self._replicas.get(model_id)
            if not reps:
                raise UnknownModelError(
                    f"no replicas for model {model_id!r}"
                )
            return list(reps)

    # -- per-model pool targets (the autoscale surface) -----------------------

    def pool_state(self) -> Dict[str, dict]:
        """The PoolActuator surface: pools are base models."""
        with self._lock:
            return {
                mid: {
                    "replicas_running": len(reps),
                    "replicas_target": len(reps),
                }
                for mid, reps in self._replicas.items()
            }

    def set_pool_target(self, model_id: str, target: int,
                        drain_timeout_s: float = 5.0) -> int:
        """Converge one model's pool to ``target`` replicas. Scale-up
        spawns (weights stream from the plane's latest publish);
        scale-down retires only replicas that drain idle within the
        timeout — a busy replica is left serving (the same
        never-hard-kill invariant the autoscale actuators keep).
        Returns the resulting replica count."""
        self.spec.model(model_id)  # raises UnknownModelError
        target = max(1, int(target))
        while True:
            with self._lock:
                have = len(self._replicas.get(model_id, ()))
            if have >= target:
                break
            self._spawn_replica(model_id)
        while True:
            with self._lock:
                reps = self._replicas.get(model_id, [])
                if len(reps) <= target:
                    break
                victim = reps[-1]
            deadline = time.monotonic() + drain_timeout_s
            while (victim.engine.has_unfinished()
                   and time.monotonic() < deadline):
                time.sleep(0.02)
            if victim.engine.has_unfinished():
                logger.warning(
                    "pool %s: replica %s still busy; not retiring",
                    model_id, victim.tag,
                )
                break
            with self._lock:
                reps = self._replicas.get(model_id, [])
                if victim in reps:
                    reps.remove(victim)
            self.weights.detach_replica(victim)
            victim.shutdown()
            logger.info("retired replica %s", victim.tag)
        with self._lock:
            return len(self._replicas.get(model_id, ()))

    def autoscaler_pool_targets(self, slo_report: Optional[dict] = None
                                ) -> Dict[str, int]:
        """Per-model pool targets from the r11 grade machinery: any
        replica of a model graded red asks for one more replica; a model
        whose replicas all grade green may give one back (never below
        its spec floor). Pure advice — callers (a FleetPoolActuator or
        an operator) apply it via set_pool_target."""
        if slo_report is None:
            from ray_tpu.fleet.weights import local_slo_histograms
            from ray_tpu.obs.telemetry import evaluate_slo

            slo_report = evaluate_slo(local_slo_histograms(),
                                      self.weights.thresholds)
        tags = slo_report.get("model_tags", {})
        targets: Dict[str, int] = {}
        with self._lock:
            pools = {mid: list(reps) for mid, reps in self._replicas.items()}
        for mid, reps in pools.items():
            floor = self.spec.model(mid).replicas
            grades = [
                tags[r.tag]["grade"] for r in reps if r.tag in tags
            ]
            n = len(reps)
            if any(g == "red" for g in grades):
                targets[mid] = n + 1
            elif grades and all(g == "green" for g in grades) and n > floor:
                targets[mid] = n - 1
            else:
                targets[mid] = n
        return targets

    # -- adapter residency ----------------------------------------------------

    def register_adapter(self, model_id: str, adapter_id: str,
                         payload: dict) -> int:
        """Register (or version-bump) an adapter's weights with the
        fleet; replicas load it on demand at routing time. Returns the
        new version."""
        self.spec.model(model_id)
        return self.weights.publish_adapter(model_id, adapter_id, payload)

    def ensure_adapter(self, replica: FleetReplica, adapter_id: str) -> None:
        """Make ``adapter_id`` resident on ``replica``, LRU-evicting an
        idle adapter if the slot budget is full. Raises
        AdapterSlotsExhausted when every slot is pinned by in-flight
        requests (route() falls back to another replica)."""
        payload = self.weights.adapter_payload(replica.model_id, adapter_id)
        with replica.runner.lock:
            eng = replica.engine
            if adapter_id in eng._lora_slots:
                return
            try:
                eng.add_lora(adapter_id, payload)
            except AdapterSlotsExhausted:
                if eng.evict_lru_lora() is None:
                    raise
                fleet_metrics.adapter_evict_counter().inc(
                    1, tags={"model": replica.model_id}
                )
                eng.add_lora(adapter_id, payload)
        fleet_metrics.adapter_load_counter().inc(
            1, tags={"model": replica.model_id}
        )

    # -- routing --------------------------------------------------------------

    def route(self, model_id: str, adapter_id: Optional[str],
              prompt_ids: list) -> FleetReplica:
        """Model-aware least-loaded pick, prefix- and residency-aware:
        score = W_PREFIX * discounted_resident_prefix_tokens
              + RESIDENT_BONUS (adapter already in a slot)
              - W_LOAD * (waiting + running)."""
        reps = self.replicas(model_id)
        best, best_score = None, None
        for r in reps:
            score = -W_LOAD * r.load()
            score += W_PREFIX * r.prefix_score(prompt_ids, adapter_id)
            if adapter_id is not None and (
                    adapter_id in r.engine._lora_slots):
                score += RESIDENT_BONUS
            score -= 1e-4 * self._routed.get(r.tag, 0)
            if best_score is None or score > best_score:
                best, best_score = r, score
        with self._lock:
            self._routed[best.tag] = self._routed.get(best.tag, 0) + 1
        return best

    # -- request path ---------------------------------------------------------

    def submit(
        self,
        tenant_id: str,
        model_ref: str,
        prompt_ids: list,
        sampling_params: Optional[SamplingParams] = None,
        request_id: Optional[str] = None,
        trace: Any = None,
    ) -> FleetTicket:
        """Admit (per-tenant QoS), route, and start one request.
        Raises FleetAdmissionRejected (shed), UnknownTenantError /
        UnknownModelError (bad identity), AdapterSlotsExhausted (every
        replica's slots pinned)."""
        tenant = self.spec.tenant(tenant_id)
        model_id, adapter_id = FleetSpec.parse_model_ref(model_ref)
        mspec = self.spec.model(model_id)
        if adapter_id is not None and mspec.adapter(adapter_id) is None:
            # not declared up front: still servable if registered at
            # runtime — only a never-registered adapter is a 404
            self.weights.adapter_payload(model_id, adapter_id)
        running = sum(
            len(r.engine.running) for r in self.replicas(model_id)
        )
        rejection = self.qos.admit(tenant, num_running=running)
        if rejection is not None:
            raise FleetAdmissionRejected(rejection)
        try:
            reps_tried: List[str] = []
            replica = self.route(model_id, adapter_id, prompt_ids)
            if adapter_id is not None:
                # slot-budget fallback: a replica whose every slot is
                # pinned by in-flight work yields to the next-best
                for candidate in sorted(
                    self.replicas(model_id),
                    key=lambda r: r is not replica,
                ):
                    try:
                        self.ensure_adapter(candidate, adapter_id)
                        replica = candidate
                        break
                    except AdapterSlotsExhausted:
                        reps_tried.append(candidate.tag)
                else:
                    raise AdapterSlotsExhausted(
                        f"adapter {adapter_id!r}: all slots in use on "
                        f"every replica ({reps_tried})"
                    )
            rid, q = replica.runner.submit(
                prompt_ids,
                sampling_params or SamplingParams(),
                request_id=request_id,
                trace=trace,
                lora_id=adapter_id,
                priority=tenant.priority,
                tenant=tenant_id,
                slo_tag=tenant.slo_tag,
            )
        except BaseException:
            self.qos.release(tenant_id)
            raise
        fleet_metrics.tenant_requests_counter().inc(
            1, tags={"tenant": tenant_id, "model": model_id}
        )
        return FleetTicket(rid, q, replica, tenant_id, model_id, adapter_id)

    def _release(self, ticket: FleetTicket) -> None:
        if not ticket._released:
            ticket._released = True
            self.qos.release(ticket.tenant_id)

    def collect(self, ticket: FleetTicket,
                timeout_s: float = 60.0) -> Any:
        """Drain a ticket to completion; returns the final RequestOutput.
        Raises on engine failure or timeout. Always settles QoS state."""
        deadline = time.monotonic() + timeout_s
        try:
            while True:
                remain = deadline - time.monotonic()
                if remain <= 0:
                    raise TimeoutError(
                        f"request {ticket.request_id} did not finish in "
                        f"{timeout_s}s"
                    )
                try:
                    out = ticket.queue.get(timeout=min(remain, 1.0))
                except queue_mod.Empty:
                    continue
                if out is None:
                    raise FleetError(
                        f"request {ticket.request_id} aborted"
                    )
                if isinstance(out, BaseException):
                    raise out
                if out.finished:
                    return out
        finally:
            self._release(ticket)

    def abort(self, ticket: FleetTicket) -> None:
        try:
            ticket.replica.runner.abort(ticket.request_id)
        finally:
            self._release(ticket)

    # -- observability / lifecycle --------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            pools = {mid: list(reps) for mid, reps in self._replicas.items()}
        models: dict = {}
        for mid, reps in pools.items():
            rows = []
            n_adapters = 0
            for r in reps:
                eng = r.engine
                resident = list(eng._lora_slots)
                n_adapters += len(resident)
                rows.append({
                    "tag": r.tag,
                    "waiting": len(eng.waiting),
                    "running": len(eng.running),
                    "resident_adapters": resident,
                    "weight_version": eng.weight_version,
                    "num_recoveries": r.runner.num_recoveries,
                })
            try:
                fleet_metrics.resident_adapters_gauge().set(
                    n_adapters, tags={"model": mid}
                )
            except Exception:  # noqa: BLE001
                pass
            models[mid] = {"replicas": rows}
        return {
            "models": models,
            "qos": self.qos.stats(),
            "weights": self.weights.stats(),
        }

    def drain(self) -> None:
        self.qos.start_drain()

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            pools, self._replicas = self._replicas, {}
        for reps in pools.values():
            for r in reps:
                r.shutdown()
        self.weights.close()
