"""Fleet topology declaration: {base models x LoRA adapters x tenants}.

A ``FleetSpec`` is the operator-facing description of a multi-tenant
serving fleet (the "Fine-Tuning and Serving Gemma on Cloud TPU" shape):
which base models exist, which LoRA adapters hang off each, and which
tenants may call them with what QoS. The FleetManager (manager.py) maps
it onto replica pools; the QoS plane (qos.py) prices admission from the
tenant specs; the weight plane (weights.py) versions per-(model,
adapter) payloads against it.

Everything here is plain data + validation — no engine imports, so the
spec can be built (and round-tripped through JSON for a control plane)
without touching jax.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple


class FleetError(Exception):
    """Base class for fleet-plane failures."""


class UnknownTenantError(FleetError):
    """Request carried a tenant id the FleetSpec does not declare."""


class UnknownModelError(FleetError):
    """Request named a model (or model:adapter) the fleet does not serve."""


class CanaryStateError(FleetError):
    """Canary ladder misuse: begin while one is active, promote/rollback
    while none is."""


@dataclasses.dataclass
class AdapterSpec:
    """One LoRA adapter of a base model. ``adapter_id`` is what requests
    select (``model = "base:adapter"``); the payload itself rides the
    FleetWeightPlane, not the spec."""

    adapter_id: str
    # rank must match the host engine's EngineConfig.lora_rank
    rank: int = 8

    def __post_init__(self):
        if not self.adapter_id or ":" in self.adapter_id:
            raise ValueError(
                f"adapter_id {self.adapter_id!r} must be non-empty and "
                "':'-free (':' separates model from adapter in routing)"
            )


@dataclasses.dataclass
class ModelSpec:
    """One base model and its adapter catalog. ``replicas`` is the pool
    target the manager converges to (the autoscale plane may move it)."""

    model_id: str
    replicas: int = 1
    # adapters declared up front; more can be attached at runtime via
    # FleetManager.register_adapter (the catalog is advisory — routing
    # only requires the adapter to be RESIDENT or loadable on a replica)
    adapters: Tuple[AdapterSpec, ...] = ()

    def __post_init__(self):
        if not self.model_id or ":" in self.model_id:
            raise ValueError(
                f"model_id {self.model_id!r} must be non-empty and ':'-free"
            )
        if self.replicas < 1:
            raise ValueError("replicas must be >= 1")
        seen = set()
        for a in self.adapters:
            if a.adapter_id in seen:
                raise ValueError(f"duplicate adapter {a.adapter_id!r}")
            seen.add(a.adapter_id)

    def adapter(self, adapter_id: str) -> Optional[AdapterSpec]:
        for a in self.adapters:
            if a.adapter_id == adapter_id:
                return a
        return None


@dataclasses.dataclass
class TenantSpec:
    """One tenant's QoS contract.

    ``priority`` orders admission and preemption (higher wins; a paying
    tenant at 10 preempts a batch tenant at 0). ``weight`` is the
    weighted-fair share of queue capacity. ``max_queue_depth`` caps this
    tenant's waiting requests per replica (-1 = fleet default), and
    ``target_queue_wait_s`` arms SLO-priced shedding for this tenant's
    own traffic (0 = depth-only)."""

    tenant_id: str
    priority: int = 0
    weight: float = 1.0
    max_queue_depth: int = -1
    target_queue_wait_s: float = 0.0

    def __post_init__(self):
        if not self.tenant_id:
            raise ValueError("tenant_id must be non-empty")
        if self.weight <= 0:
            raise ValueError("weight must be > 0")

    @property
    def slo_tag(self) -> str:
        """The SLO-histogram tag this tenant's observations record under
        (beyond the engine's model tag) — what evaluate_slo grades."""
        return f"tenant:{self.tenant_id}"


@dataclasses.dataclass
class FleetSpec:
    """The whole fleet: models, tenants, and shared QoS defaults."""

    models: Tuple[ModelSpec, ...] = ()
    tenants: Tuple[TenantSpec, ...] = ()
    # per-tenant queue-depth default when TenantSpec.max_queue_depth < 0:
    # ceil(weight_share * total_queue_budget) per replica
    total_queue_budget: int = 32
    # admit unknown tenants as an anonymous priority-0 tenant instead of
    # rejecting them (off = strict: UnknownTenantError -> 403 at ingress)
    allow_unknown_tenants: bool = False

    def __post_init__(self):
        seen = set()
        for m in self.models:
            if m.model_id in seen:
                raise ValueError(f"duplicate model {m.model_id!r}")
            seen.add(m.model_id)
        seen = set()
        for t in self.tenants:
            if t.tenant_id in seen:
                raise ValueError(f"duplicate tenant {t.tenant_id!r}")
            seen.add(t.tenant_id)

    # -- lookups --------------------------------------------------------------

    def model(self, model_id: str) -> ModelSpec:
        for m in self.models:
            if m.model_id == model_id:
                return m
        raise UnknownModelError(f"fleet does not serve model {model_id!r}")

    def tenant(self, tenant_id: str) -> TenantSpec:
        for t in self.tenants:
            if t.tenant_id == tenant_id:
                return t
        if self.allow_unknown_tenants:
            # anonymous traffic (no header, no user field) pools under one
            # id — TenantSpec forbids empty ids
            return TenantSpec(tenant_id=tenant_id or "anon",
                              priority=0, weight=1.0)
        raise UnknownTenantError(
            f"unknown tenant {tenant_id!r} (declare it in FleetSpec.tenants "
            "or set allow_unknown_tenants)"
        )

    def queue_depth_for(self, tenant: TenantSpec) -> int:
        """Weighted-fair share of the queue budget for one tenant."""
        if tenant.max_queue_depth >= 0:
            return tenant.max_queue_depth
        total_w = sum(t.weight for t in self.tenants) or tenant.weight
        share = tenant.weight / total_w
        return max(1, int(round(share * self.total_queue_budget)))

    @staticmethod
    def parse_model_ref(ref: str) -> Tuple[str, Optional[str]]:
        """Split a request's model field: ``"base"`` or ``"base:adapter"``."""
        if ":" in ref:
            base, adapter = ref.split(":", 1)
            return base, (adapter or None)
        return ref, None

    def to_dict(self) -> Dict:
        return dataclasses.asdict(self)
