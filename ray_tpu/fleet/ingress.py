"""FleetServer: the multi-tenant OpenAI-compatible ingress.

One HTTP surface in front of a FleetManager. Differences from the
single-model ``llm.openai_api.LLMServer``:

 * **model refs** — ``"model"`` selects ``base`` or ``base:adapter``
   (the multiplex convention); the adapter loads on the routed replica
   on demand, LRU-evicting an idle one when the slot budget is full;
 * **tenant identity** — the ``x-tenant-id`` header (or the OpenAI
   ``user`` field as the fallback) binds the request to a TenantSpec;
   unknown tenants are refused up front unless the spec opts into a
   default tenant;
 * **admission** — per-tenant weighted-fair QoS (fleet.qos) replaces the
   single engine-wide controller: a batch tenant flooding its own queue
   share never prices a paying tenant's admission, and the tenant's
   priority rides into the engine to arm priority preemption.

Handlers are async (serve deployment callables), but the engine path is
the runner's thread + queue machinery — blocking drains run in the
default executor, mirroring LLMServer.
"""

from __future__ import annotations

import asyncio
import time
import uuid
from typing import Any, Optional

from ray_tpu.fleet.config import (
    FleetError,
    FleetSpec,
    UnknownModelError,
    UnknownTenantError,
)
from ray_tpu.fleet.manager import FleetAdmissionRejected, FleetManager
from ray_tpu.llm.engine import AdapterSlotsExhausted, SamplingParams
from ray_tpu.llm.openai_api import (
    ByteTokenizer,
    _sse_transcript,
    default_chat_template,
)
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.fleet.ingress")

TENANT_HEADER = "x-tenant-id"


def _error(message: str, code: int, type_: str = "invalid_request_error",
           **extra) -> dict:
    return {"error": {"message": message, "type": type_, "code": code,
                      **extra}}


class FleetServer:
    """The fleet's OpenAI surface (serve ingress callable)."""

    def __init__(self, spec: FleetSpec, engine_config: Any = None,
                 params: Any = None, tokenizer: Any = None, seed: int = 0,
                 thresholds: Any = None):
        self.spec = spec
        self.manager = FleetManager(
            spec, engine_config=engine_config, params=params, seed=seed,
            thresholds=thresholds,
        )
        first = self.manager.replicas(spec.models[0].model_id)[0]
        self.tokenizer = tokenizer or ByteTokenizer(
            first.engine.config.model.vocab_size
        )
        eos = getattr(self.tokenizer, "eos_token_id", 2)
        for m in spec.models:
            for r in self.manager.replicas(m.model_id):
                r.engine.config.eos_token_id = eos

    # -- identity -------------------------------------------------------------

    def _tenant_id(self, body: dict, headers: Optional[dict]) -> str:
        for k, v in (headers or {}).items():
            if k.lower() == TENANT_HEADER:
                return str(v)
        return str(body.get("user", "") or "")

    def _sampling_from_body(self, body: dict) -> SamplingParams:
        return SamplingParams(
            max_tokens=int(body.get("max_tokens", 64)),
            temperature=float(body.get("temperature", 1.0)),
            top_k=int(body.get("top_k", 0)),
            top_p=float(body.get("top_p", 1.0)),
            seed=body.get("seed"),
            logprobs=bool(body.get("logprobs", False)),
        )

    # -- request path ---------------------------------------------------------

    async def _generate(self, tenant_id: str, model_ref: str,
                        prompt_ids: list, sp: SamplingParams,
                        request_id: Optional[str] = None,
                        timeout_s: float = 120.0):
        """Submit + collect one request through the fleet (QoS admission,
        routing, adapter residency all inside manager.submit). Returns
        (text_tokens, finish_reason)."""
        loop = asyncio.get_running_loop()
        ticket = self.manager.submit(
            tenant_id, model_ref, prompt_ids, sampling_params=sp,
            request_id=request_id,
        )
        try:
            out = await loop.run_in_executor(
                None, lambda: self.manager.collect(ticket, timeout_s)
            )
        except BaseException:
            self.manager.abort(ticket)
            raise
        toks = list(out.output_token_ids)
        eos = ticket.replica.engine.config.eos_token_id
        if toks and toks[-1] == eos:
            toks = toks[:-1]
        return toks, out.finish_reason

    async def completions(self, body: dict,
                          headers: Optional[dict] = None) -> Any:
        tenant_id = self._tenant_id(body, headers)
        model_ref = str(body.get("model") or self.spec.models[0].model_id)
        try:
            sp = self._sampling_from_body(body)
        except (ValueError, TypeError) as e:
            return _error(str(e), 400)
        prompts = body.get("prompt", "")
        if not isinstance(prompts, list):
            prompts = [prompts]
        rid = f"cmpl-{uuid.uuid4().hex[:24]}"
        try:
            id_lists = [self.tokenizer.encode(str(p)) for p in prompts]
            results = await asyncio.gather(*[
                self._generate(
                    tenant_id, model_ref, ids, sp,
                    request_id=rid if len(id_lists) == 1 else f"{rid}-{i}",
                )
                for i, ids in enumerate(id_lists)
            ])
        except FleetAdmissionRejected as e:
            return e.payload
        except UnknownTenantError as e:
            return _error(str(e), 401, type_="invalid_tenant")
        except (UnknownModelError, FleetError) as e:
            return _error(str(e), 404, type_="model_not_found")
        except AdapterSlotsExhausted as e:
            return _error(str(e), 503, type_="overloaded", retry_after=1)
        n_prompt = sum(len(ids) for ids in id_lists)
        n_out = sum(len(toks) for toks, _ in results)
        payload = {
            "id": rid,
            "object": "text_completion",
            "created": int(time.time()),
            "model": model_ref,
            "choices": [
                {
                    "index": i,
                    "text": self.tokenizer.decode(toks),
                    "finish_reason": reason,
                    "logprobs": None,
                }
                for i, (toks, reason) in enumerate(results)
            ],
            "usage": {
                "prompt_tokens": n_prompt,
                "completion_tokens": n_out,
                "total_tokens": n_prompt + n_out,
            },
        }
        if body.get("stream"):
            return _sse_transcript(payload, "text_completion")
        return payload

    async def chat_completions(self, body: dict,
                               headers: Optional[dict] = None) -> Any:
        chat_body = dict(body)
        chat_body["prompt"] = default_chat_template(
            body.get("messages", [])
        )
        out = await self.completions(chat_body, headers=headers)
        if isinstance(out, str) or "error" in out:
            return out
        choice = out["choices"][0]
        payload = dict(out)
        payload["id"] = f"chatcmpl-{uuid.uuid4().hex[:24]}"
        payload["object"] = "chat.completion"
        payload["choices"] = [{
            "index": 0,
            "message": {"role": "assistant",
                        "content": choice["text"]},
            "finish_reason": choice["finish_reason"],
        }]
        if body.get("stream"):
            return _sse_transcript(payload, "chat.completion.chunk")
        return payload

    # -- operator surface -----------------------------------------------------

    def models(self) -> dict:
        data = []
        for m in self.spec.models:
            data.append({"id": m.model_id, "object": "model",
                         "owned_by": "ray_tpu"})
            for a in m.adapters:
                data.append({
                    "id": f"{m.model_id}:{a.adapter_id}", "object": "model",
                    "owned_by": "ray_tpu", "parent": m.model_id,
                })
        return {"object": "list", "data": data}

    def stats(self) -> dict:
        return self.manager.stats()

    def drain(self, timeout_s: float = 30.0) -> dict:
        self.manager.drain()
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            busy = any(
                r.engine.has_unfinished()
                for m in self.spec.models
                for r in self.manager.replicas(m.model_id)
            )
            if not busy:
                break
            time.sleep(0.05)
        inflight = sum(
            r.load()
            for m in self.spec.models
            for r in self.manager.replicas(m.model_id)
        )
        return {"drained": inflight == 0, "inflight": inflight}

    async def __call__(self, request) -> Any:
        path, method = request.path, request.method
        headers = dict(getattr(request, "headers", {}) or {})
        if path.rstrip("/") == "/v1/models" and method == "GET":
            return self.models()
        if path.rstrip("/") == "/v1/stats" and method == "GET":
            return self.stats()
        if path.rstrip("/") == "/v1/completions" and method == "POST":
            return await self.completions(request.json(), headers=headers)
        if path.rstrip("/") == "/v1/chat/completions" and method == "POST":
            return await self.chat_completions(request.json(),
                                               headers=headers)
        if path.rstrip("/") == "/v1/drain" and method == "POST":
            body = request.json() or {}
            timeout_s = float(body.get("timeout_s", 30.0))
            return await asyncio.get_running_loop().run_in_executor(
                None, lambda: self.drain(timeout_s=timeout_s)
            )
        return _error(f"no route {method} {path}", 404,
                      type_="not_found_error")

    def shutdown(self) -> None:
        self.manager.close()

    def __del__(self):
        try:
            self.manager.close()
        except Exception:  # noqa: BLE001
            pass
