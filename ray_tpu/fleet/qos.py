"""Per-tenant QoS: weighted-fair admission on top of the r09 controller.

One ``AdmissionController`` per tenant, constructed so its histogram-
priced SLO shedding reads the TENANT's own queue-wait series (requests
record under ``tenant:<id>`` via the engine's per-request ``slo_tag``)
instead of the engine-wide one. On top of that, the fleet adds what the
single-tenant controller cannot express:

 * weighted-fair depth caps — each tenant's waiting requests are capped
   at its weight share of ``FleetSpec.total_queue_budget``, so a batch
   tenant flooding the queue exhausts ITS OWN cap while the paying
   tenant's share stays admittable;
 * priority pass-through — the tenant's priority rides every request to
   the engine, where it orders admission and arms priority preemption.

Shed decisions are counted per tenant in
``llm_admission_rejected_total{model,code,tenant}``.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ray_tpu.fleet.config import FleetSpec, TenantSpec
from ray_tpu.llm.admission import AdmissionConfig, AdmissionController


class TenantQoSController:
    """Fleet-wide admission state: per-tenant waiting counts and the
    per-tenant AdmissionController ladder. Thread-safe — the ingress
    admits from request threads while replicas retire from their engine
    loops."""

    def __init__(self, spec: FleetSpec):
        self.spec = spec
        self._lock = threading.Lock()
        self._waiting: Dict[str, int] = {}   # tenant -> in-queue count
        self._ctls: Dict[str, AdmissionController] = {}

    def controller(self, tenant: TenantSpec) -> AdmissionController:
        with self._lock:
            ctl = self._ctls.get(tenant.tenant_id)
            if ctl is None:
                ctl = AdmissionController(
                    AdmissionConfig(
                        max_queue_depth=self.spec.queue_depth_for(tenant),
                        target_queue_wait_s=tenant.target_queue_wait_s,
                    ),
                    # the controller's histogram pricing filters by this
                    # tag: point it at the tenant's own SLO series
                    model_tag=tenant.slo_tag,
                    tenant=tenant.tenant_id,
                )
                self._ctls[tenant.tenant_id] = ctl
        return ctl

    # -- admission ------------------------------------------------------------

    def admit(self, tenant: TenantSpec,
              num_running: int = 0) -> Optional[dict]:
        """None = admitted (the caller MUST pair with release());
        otherwise the 429/503 payload to return. The depth the r09
        ladder sees is THIS TENANT's waiting count, so one tenant's
        flood never prices another's admission."""
        ctl = self.controller(tenant)
        with self._lock:
            waiting = self._waiting.get(tenant.tenant_id, 0)
        rejection = ctl.check(num_waiting=waiting, num_running=num_running)
        if rejection is not None:
            return rejection
        with self._lock:
            self._waiting[tenant.tenant_id] = (
                self._waiting.get(tenant.tenant_id, 0) + 1
            )
        return None

    def release(self, tenant_id: str) -> None:
        """The admitted request left the waiting queue (prefilled,
        finished, failed, or was shed downstream)."""
        with self._lock:
            n = self._waiting.get(tenant_id, 0) - 1
            if n > 0:
                self._waiting[tenant_id] = n
            else:
                self._waiting.pop(tenant_id, None)

    def start_drain(self) -> None:
        with self._lock:
            ctls = list(self._ctls.values())
        for ctl in ctls:
            ctl.start_drain()

    def waiting_by_tenant(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._waiting)

    def stats(self) -> dict:
        with self._lock:
            ctls = dict(self._ctls)
            waiting = dict(self._waiting)
        return {
            "waiting_by_tenant": waiting,
            "tenants": {tid: ctl.stats() for tid, ctl in ctls.items()},
        }
