"""Global config/flag registry.

TPU-native analog of the reference's RAY_CONFIG macro registry
(reference: src/ray/common/ray_config_def.h:20-23 — typed flags with
defaults, overridable by RAY_<name> env vars). Here flags are declared
once in _DEFS, resolved lazily from the environment (``RAY_TPU_<name>``),
and overridable programmatically for tests via `override`.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
from typing import Any

_DEFS: dict[str, tuple[type, Any, str]] = {}
_OVERRIDES: dict[str, Any] = {}
_LOCK = threading.Lock()


def define(name: str, typ: type, default: Any, doc: str = "") -> None:
    _DEFS[name] = (typ, default, doc)


def get(name: str) -> Any:
    if name not in _DEFS:
        raise KeyError(f"unknown config flag: {name}")
    with _LOCK:
        if name in _OVERRIDES:
            return _OVERRIDES[name]
    typ, default, _ = _DEFS[name]
    env = os.environ.get(f"RAY_TPU_{name}")
    if env is None:
        return default
    if typ is bool:
        return env.lower() in ("1", "true", "yes")
    if typ in (dict, list):
        return json.loads(env)
    return typ(env)


def set_override(name: str, value: Any) -> None:
    if name not in _DEFS:
        raise KeyError(f"unknown config flag: {name}")
    with _LOCK:
        _OVERRIDES[name] = value


@contextlib.contextmanager
def override(**kwargs):
    """Temporarily override flags (test helper)."""
    for name in kwargs:
        if name not in _DEFS:
            raise KeyError(f"unknown config flag: {name}")
    with _LOCK:
        saved = dict(_OVERRIDES)
        _OVERRIDES.update(kwargs)
    try:
        yield
    finally:
        with _LOCK:
            _OVERRIDES.clear()
            _OVERRIDES.update(saved)


def all_flags() -> dict[str, Any]:
    return {name: get(name) for name in _DEFS}


# ---------------------------------------------------------------------------
# Flag definitions (grow as subsystems land).
# ---------------------------------------------------------------------------

define("object_store_memory_mb", int, 2048, "Host shared-memory object store capacity.")
define("inline_object_max_bytes", int, 100 * 1024, "Objects smaller than this stay in the in-process memory store.")
define("worker_pool_size", int, 4, "Default number of task-execution workers per node.")
define("worker_mode", str, "thread", "Task execution mode: 'thread' (shares the host JAX process, TPU-friendly) or 'process'.")
define("task_max_retries", int, 3, "Default retries for tasks that die with the worker.")
define("actor_max_restarts", int, 0, "Default actor restarts on failure.")
define("health_check_period_s", float, 1.0, "Control-plane node health check interval.")
define("health_check_timeout_s", float, 5.0, "Node declared dead after this long without heartbeat.")
define("scheduler_spread_threshold", float, 0.5, "Utilization above which hybrid policy prefers spreading.")
define("scheduler_top_k_fraction", float, 0.2, "Hybrid policy: random pick among best k = frac * num_nodes.")
define("gcs_port", int, 0, "Control-plane service port (0 = pick free).")
define("metrics_export_interval_s", float, 5.0, "Metrics push interval.")
define("log_level", str, "INFO", "Framework log level.")
