"""Unique identifiers for framework entities.

TPU-native analog of the reference's ID types (reference:
src/ray/common/id.h — TaskID/ObjectID/ActorID/NodeID/JobID). We keep the
same conceptual split but use flat random 128-bit ids with a type tag;
object ids embed the owner task id + return index so ownership can be
derived without a lookup (mirroring the reference's scheme where object
ids are task-id + index, src/ray/common/id.h ObjectID::FromIndex).
"""

from __future__ import annotations

import os
import threading

_ID_NBYTES = 16


class BaseID:
    """Immutable random identifier. Subclasses carry the entity type."""

    __slots__ = ("_bytes", "_hash", "_repr")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != _ID_NBYTES:
            raise ValueError(f"expected {_ID_NBYTES} bytes, got {len(id_bytes)}")
        self._bytes = id_bytes
        self._hash = None
        self._repr = None

    @classmethod
    def from_random(cls):
        return cls(os.urandom(_ID_NBYTES))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _ID_NBYTES)

    @classmethod
    def from_hex(cls, s: str):
        return cls(bytes.fromhex(s))

    def is_nil(self) -> bool:
        return self._bytes == b"\x00" * _ID_NBYTES

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        # cached: ids key every hot-path dict (object store, event table)
        h = self._hash
        if h is None:
            h = self._hash = hash((type(self).__name__, self._bytes))
        return h

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        r = self._repr
        if r is None:
            r = self._repr = f"{type(self).__name__}({self.hex()[:12]})"
        return r


class JobID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class TaskID(BaseID):
    pass


class ActorID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class ObjectID(BaseID):
    """Object ids embed owner task id (first 12 bytes) + return index."""

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        return cls(task_id.binary()[:12] + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Put objects use the high bit of the index to avoid collision
        # with task returns.
        return cls(task_id.binary()[:12] + (put_index | 0x80000000).to_bytes(4, "little"))

    def task_prefix(self) -> bytes:
        return self._bytes[:12]

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[12:], "little") & 0x7FFFFFFF


class _Counter:
    """Thread-safe monotonically increasing counter."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
