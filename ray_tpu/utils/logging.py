"""Framework logging (analog of reference RAY_LOG, src/ray/util/logging.h)."""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False


def get_logger(name: str = "ray_tpu") -> logging.Logger:
    global _CONFIGURED
    logger = logging.getLogger(name)
    if not _CONFIGURED:
        root = logging.getLogger("ray_tpu")
        if not root.handlers:
            handler = logging.StreamHandler(sys.stderr)
            handler.setFormatter(
                logging.Formatter(
                    "%(asctime)s %(levelname)s %(name)s [pid=%(process)d] %(message)s"
                )
            )
            root.addHandler(handler)
        root.setLevel(os.environ.get("RAY_TPU_log_level", "INFO"))
        root.propagate = False
        _CONFIGURED = True
    return logger
