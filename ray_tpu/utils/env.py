"""Process-environment helpers shared by every process-spawning site."""

from __future__ import annotations

import os


def inject_framework_pythonpath(env: dict) -> dict:
    """Prepend the framework root to env's PYTHONPATH (in place).

    Every spawned process (workers, job drivers, dashboards) must import
    ray_tpu regardless of its cwd — a runtime_env working_dir or an
    arbitrary entrypoint directory drops the implicit cwd-based import.
    """
    import ray_tpu

    fw_root = os.path.dirname(os.path.dirname(os.path.abspath(ray_tpu.__file__)))
    env["PYTHONPATH"] = (
        fw_root + os.pathsep + env["PYTHONPATH"]
        if env.get("PYTHONPATH") else fw_root
    )
    return env
