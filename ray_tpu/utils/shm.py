"""Shared-memory directory resolution.

One helper so every shm participant (node daemon, LocalCluster node
procs, DAG channels) derives the SAME backing directory — divergent
copies would make cross-process readers spin on a path the writer never
creates (hosts without /dev/shm, e.g. macOS, fall back to TMPDIR).
"""

from __future__ import annotations

import os


def shm_dir() -> str:
    return ("/dev/shm" if os.path.isdir("/dev/shm")
            else os.environ.get("TMPDIR", "/tmp"))
