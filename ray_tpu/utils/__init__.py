from ray_tpu.utils.ids import (
    ActorID,
    JobID,
    NodeID,
    ObjectID,
    PlacementGroupID,
    TaskID,
    WorkerID,
)
from ray_tpu.utils.logging import get_logger

__all__ = [
    "ActorID",
    "JobID",
    "NodeID",
    "ObjectID",
    "PlacementGroupID",
    "TaskID",
    "WorkerID",
    "get_logger",
]
