"""DeviceKVConnector: the third KV-transfer backend (ROADMAP item 1).

Implements the ``llm/disagg/connector.KVConnector`` contract the r10
interface was deliberately shaped for: ``register_target`` binds a
decode engine to a **device endpoint** (the device its paged KV cache
lives on), and ``send`` moves ``k_pages``/``v_pages`` as device arrays
through the generic ``fabric.transport.DeviceTransport`` —
``jax.device_put`` between mesh endpoints, i.e. ICI DMA on a real TPU
slice and a device-to-device memcpy between
``--xla_force_host_platform_device_count`` CPU devices on CI. The
multi-MB pages are never pickled, never framed, and never staged
through host RAM; only the small host-side header (token ids, sampler
key, SLO timestamps) rides the bundle's ``meta``.

Failure modes mirror the host-path connectors exactly: a dropped
transfer raises ``KVTransferError`` at the sender (chaos:
``DROP_DEVICE_TRANSFER``), a corrupt one arrives with a failing
device-side checksum and is caught by ``KVHandoff.verify()`` at import
(chaos: ``CORRUPT_DEVICE_TRANSFER``) — the orchestrator's answer to
both is its existing budgeted re-prefill, now with the faulted edge
degraded to its RPC fallback (fabric/topology.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

from ray_tpu.fabric.transport import (
    ArrayBundle,
    DeviceTransport,
    FabricTransferError,
)
from ray_tpu.llm.disagg.connector import KVConnector, KVTransferError
from ray_tpu.llm.disagg.handoff import KVHandoff
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.fabric.device_connector")

# KVHandoff fields that ride the bundle meta (everything except the
# device-array pages and the checksum the bundle carries itself)
_META_FIELDS = tuple(
    f.name for f in dataclasses.fields(KVHandoff)
    if f.name not in ("k_pages", "v_pages")
)


class DeviceKVConnector(KVConnector):
    """KV handoffs as device-array bundles over the fabric transport."""

    name = "device"

    def __init__(self, namespace: str = "default",
                 transport: Optional[DeviceTransport] = None):
        super().__init__()
        self.transport = transport or DeviceTransport(namespace=namespace)
        self.namespace = self.transport.namespace

    # -- interface ------------------------------------------------------------

    def register_target(self, target_id: str, device: Any = None) -> tuple:
        """Bind ``target_id`` to a device endpoint. Pass the decode
        engine's KV-cache device so the transfer lands where the cache
        scatter will read it (a same-device import is then zero-copy)."""
        return self.transport.register_endpoint(target_id, device=device)

    def send(self, target: tuple, handoff: KVHandoff,
             timeout_s: float = 30.0) -> None:
        """Ship one handoff: pages as device arrays, header as meta.
        The handoff must be device-sealed (``seal(device=True)``) so the
        receiver's verify reduces on device too; a host-sealed handoff
        is re-sealed device-side here (one extra pair of reductions)."""
        if handoff.checksum_kind != "device_u32":
            handoff = dataclasses.replace(handoff).seal(device=True)
        meta = {f: getattr(handoff, f) for f in _META_FIELDS}
        try:
            # seal=False: the handoff's own device checksum (in meta,
            # verified at import) IS the integrity gate — a second
            # bundle seal would re-reduce both page arrays per transfer
            # for a checksum nothing on this path reads
            self.transport.send_arrays(
                target,
                {"k_pages": handoff.k_pages, "v_pages": handoff.v_pages},
                meta=meta, timeout_s=timeout_s,
                bundle_id=handoff.request_id, seal=False,
            )
        except FabricTransferError as e:
            self.num_dropped += 1
            raise KVTransferError(
                f"device transfer of {handoff.request_id!r} failed: {e}"
            ) from e
        self.num_sent += 1
        self.bytes_sent += handoff.nbytes

    def recv(self, target_id: str, timeout_s: float = 0.1) -> Optional[KVHandoff]:
        b = self.transport.recv_arrays(target_id, timeout_s=timeout_s)
        if b is None:
            return None
        self.num_received += 1
        return self._to_handoff(b)

    @staticmethod
    def _to_handoff(bundle: ArrayBundle) -> KVHandoff:
        """Reassemble the KVHandoff; the bundle checksum is carried into
        the handoff's device checksum so ``verify()`` at import checks
        the same device-reduced sum the sender sealed. Token-id
        integrity is covered by the meta'd ``checksum`` field itself
        (sealed over pages + tokens on the send side)."""
        kw = dict(bundle.meta)
        kw["k_pages"] = bundle.arrays["k_pages"]
        kw["v_pages"] = bundle.arrays["v_pages"]
        return KVHandoff(**kw)

    def close(self) -> None:
        self.transport.close()

    def stats(self) -> dict:
        s = super().stats()
        s["transport"] = self.transport.stats()
        return s
