"""ray_tpu.fabric — device-direct array transfer plane + multi-slice
pool fabric.

Three pieces (ROADMAP item 1, shaped for item 5's weight sync too):

 * **transport** — the generic ``send_arrays``/``recv_arrays`` API:
   named device arrays move between registered device endpoints by
   ``jax.device_put`` (ICI DMA on TPU slices, device-to-device memcpy
   on CPU CI devices), sealed with a device-computed checksum so
   multi-MB payloads never cross to the host for integrity.
 * **device_connector** — ``DeviceKVConnector``, the third
   ``KVConnector`` backend: prefill→decode KV handoffs as device-array
   bundles (zero host staging), same checksum/timeout failure modes as
   the host-path connectors.
 * **topology / pool** — role-tagged pools pinned to ICI slices via
   placement groups, a topology map recording which pool-pairs share a
   device mesh, and stateful per-edge transport selection (device where
   meshes are shared, RPC elsewhere, fault ⇒ degrade the edge to its
   RPC fallback).

Clients: the ``DisaggOrchestrator`` (per-edge ICI-vs-RPC KV transfer)
and ``train.weight_sync`` (learner→rollout weight publishes) — both go
through ``send_arrays``.
"""

from ray_tpu.fabric.device_connector import DeviceKVConnector
from ray_tpu.fabric.pool import (
    FabricPlan,
    SlicePoolSpec,
    build_fabric,
    build_topology,
    slice_resource,
)
from ray_tpu.fabric.topology import FabricTopology
from ray_tpu.fabric.transport import (
    ArrayBundle,
    DeviceTransport,
    FabricTransferError,
    device_checksum,
)

__all__ = [
    "ArrayBundle",
    "DeviceKVConnector",
    "DeviceTransport",
    "FabricPlan",
    "FabricTopology",
    "FabricTransferError",
    "SlicePoolSpec",
    "build_fabric",
    "build_topology",
    "device_checksum",
    "slice_resource",
]
