"""Fabric topology: which pools sit on which ICI slice, and which
pool-pairs can move device arrays directly.

The paper's north star maps placement groups onto ICI slices; the
topology map is the serving-plane half of that contract: every
role-tagged pool (prefill / decode / draft / learner / rollout) is
pinned to a **slice**, slices are grouped into **meshes** (a slice
always shares a mesh with itself; ``link`` declares two slices
device-reachable — one multislice ICI domain), and an **edge** between
two pools carries a transport backend:

 * ``"device"`` when the pools share a mesh — arrays move by
   ``jax.device_put`` / collective permute (ray_tpu/fabric/transport.py),
   never through host RAM;
 * ``"rpc"`` otherwise — the cluster frame protocol
   (llm/disagg/connector.RpcKVConnector), chunked for large payloads.

Edges are *stateful*: a device edge that faults is degraded to its RPC
fallback (``mark_fallback``) so the next transfer on that edge rides
the wire instead of retrying a broken DMA path forever; fallbacks are
counted and exported (``fabric_transfer_fallbacks_total``).

The map serializes to a plain dict (``to_dict``/``from_dict``) so a
DisaggConfig can carry it through serve deployment configs and the
`ray_tpu status` fabric block can render it.
"""

from __future__ import annotations

import threading
from typing import Optional

VALID_BACKENDS = ("device", "rpc", "inproc")


class FabricTopology:
    """Pool → slice → mesh map with per-edge transport state."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pools: dict[str, dict] = {}      # name -> {role, slice, size}
        self._mesh_of: dict[str, str] = {}     # slice -> mesh-group root
        self._fallbacks: dict[tuple, str] = {} # (src, dst) -> reason
        self._overrides: dict[tuple, str] = {} # (src, dst) -> forced backend

    # -- declaration ----------------------------------------------------------

    def add_pool(self, name: str, role: str, slice_id: str,
                 size: int = 1) -> "FabricTopology":
        with self._lock:
            self._pools[name] = {
                "role": role, "slice": slice_id, "size": int(size),
            }
            self._mesh_of.setdefault(slice_id, slice_id)
        return self

    def link(self, slice_a: str, slice_b: str) -> "FabricTopology":
        """Declare two slices device-reachable (one ICI/multislice mesh
        domain): union their mesh groups."""
        with self._lock:
            ra = self._root_locked(slice_a)
            rb = self._root_locked(slice_b)
            if ra != rb:
                self._mesh_of[rb] = ra
        return self

    def _root_locked(self, slice_id: str) -> str:
        self._mesh_of.setdefault(slice_id, slice_id)
        s = slice_id
        while self._mesh_of[s] != s:
            s = self._mesh_of[s]
        self._mesh_of[slice_id] = s  # path compression
        return s

    # -- queries --------------------------------------------------------------

    def pools(self) -> dict:
        with self._lock:
            return {k: dict(v) for k, v in self._pools.items()}

    def pool_of_role(self, role: str) -> Optional[str]:
        with self._lock:
            for name, p in self._pools.items():
                if p["role"] == role:
                    return name
        return None

    def shares_mesh(self, pool_a: str, pool_b: str) -> bool:
        with self._lock:
            pa = self._pools.get(pool_a)
            pb = self._pools.get(pool_b)
            if pa is None or pb is None:
                return False
            return self._root_locked(pa["slice"]) == self._root_locked(pb["slice"])

    def edge_backend(self, src_pool: str, dst_pool: str) -> str:
        """Transport for the (src → dst) edge: a forced override wins,
        a recorded fallback degrades to rpc, else device iff the pools
        share a mesh."""
        key = (src_pool, dst_pool)
        with self._lock:
            if key in self._overrides:
                return self._overrides[key]
            if key in self._fallbacks:
                return "rpc"
        return "device" if self.shares_mesh(src_pool, dst_pool) else "rpc"

    def set_edge_backend(self, src_pool: str, dst_pool: str,
                         backend: str) -> None:
        if backend not in VALID_BACKENDS:
            raise ValueError(
                f"unknown fabric backend {backend!r}; one of {VALID_BACKENDS}"
            )
        with self._lock:
            self._overrides[(src_pool, dst_pool)] = backend

    def mark_fallback(self, src_pool: str, dst_pool: str,
                      reason: str = "") -> bool:
        """Degrade one edge to its RPC fallback after a device-transfer
        fault; returns True the first time (so the caller counts each
        degradation once)."""
        key = (src_pool, dst_pool)
        with self._lock:
            if key in self._fallbacks:
                return False
            self._fallbacks[key] = reason or "device_transfer_fault"
            return True

    def fallbacks(self) -> dict:
        with self._lock:
            return {f"{s}->{d}": r for (s, d), r in self._fallbacks.items()}

    def edges(self) -> list:
        """Every directed pool-pair with its current backend (the
        transport matrix the README documents and `ray_tpu status`
        renders)."""
        names = sorted(self.pools())
        return [
            {"src": s, "dst": d, "backend": self.edge_backend(s, d)}
            for s in names for d in names if s != d
        ]

    # -- wire form ------------------------------------------------------------

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "pools": {k: dict(v) for k, v in self._pools.items()},
                "mesh_of": dict(self._mesh_of),
                "overrides": {
                    f"{s}->{d}": b for (s, d), b in self._overrides.items()
                },
            }

    @classmethod
    def from_dict(cls, doc: dict) -> "FabricTopology":
        topo = cls()
        for name, p in (doc.get("pools") or {}).items():
            topo.add_pool(name, p["role"], p["slice"], p.get("size", 1))
        for slice_id, root in (doc.get("mesh_of") or {}).items():
            topo.link(root, slice_id)
        for edge, backend in (doc.get("overrides") or {}).items():
            src, _, dst = edge.partition("->")
            topo.set_edge_backend(src, dst, backend)
        return topo

    def __repr__(self):
        return (f"FabricTopology(pools={sorted(self.pools())}, "
                f"edges={[(e['src'], e['dst'], e['backend']) for e in self.edges()]})")
