"""Fabric observability: the transfer-plane metrics `ray_tpu status`
renders in its ``== fabric ==`` block.

Construct-per-call like obs/slo.py (same-name re-registration shares
storage in util/metrics, so a test's ``clear_registry()`` can never
strand a stale cached instance). Both metrics are telemetry-plane
(``ray_tpu_fabric_`` is in ``obs.telemetry.AGGREGATED_PREFIXES``) and
declare their aggregation kinds, so ``check_metrics`` /
``check_aggregations`` hold them to the same contract as every other
cluster-rolled metric.
"""

from __future__ import annotations


def edges_active_gauge():
    """Directed pool-pair edges this orchestrator currently serves, per
    transport backend. SUM across reporters: the fleet value is the
    total edge count, and the per-backend series are the backend mix."""
    from ray_tpu.obs.telemetry import cluster_gauge

    return cluster_gauge(
        "fabric_edges_active",
        description="active fabric transfer edges (directed pool pairs) "
        "by transport backend (device/rpc/inproc)",
        tag_keys=("model", "backend"),
    )


def transfer_fallbacks_counter():
    """Device edges degraded to their RPC fallback after a
    device-transfer fault (counters default to SUM aggregation)."""
    from ray_tpu.obs.telemetry import cluster_counter

    return cluster_counter(
        "fabric_transfer_fallbacks_total",
        description="fabric edges degraded from device-direct transfer "
        "to the RPC fallback after a device-transfer fault",
        tag_keys=("model", "edge"),
    )


def register_metrics() -> None:
    """scripts/check_metrics.py hook: force lazy metrics to register."""
    edges_active_gauge()
    transfer_fallbacks_counter()
