"""Slice pools: role-tagged engine pools pinned to ICI slices through
placement groups.

PAPER.md's north star maps placement groups onto ICI slices; this
module is that mapping made concrete for the serving/training fabric.
A ``SlicePoolSpec`` names a pool (role + slice + size + per-engine
resources); ``build_fabric`` reserves **one placement group per pool**
whose bundles all carry the pool's slice resource (``slice:<id>`` — a
custom resource each node advertises for the slice its hosts belong
to), STRICT_PACK so the whole pool lands inside one slice's host group
and its engines share one device mesh. The returned ``FabricPlan``
couples the reservations with the ``FabricTopology`` the transfer
plane consults: pools whose slices were declared ``link``\\ ed (one
multislice ICI domain) get device edges, everything else RPC.

On CPU CI the "slices" are just resource labels on LocalCluster nodes
(``ray_tpu.init(resources={"slice:s0": ...})``) and the device mesh is
``--xla_force_host_platform_device_count`` — identical placement and
topology code paths, ICI only at the bottom.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from ray_tpu.fabric.topology import FabricTopology
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.fabric.pool")


def slice_resource(slice_id: str) -> str:
    """The custom resource name a node advertises for its slice."""
    return f"slice:{slice_id}"


@dataclasses.dataclass
class SlicePoolSpec:
    """One role-tagged pool pinned to one slice.

    ``resources`` are per-engine bundle resources beyond the slice pin
    (e.g. ``{"TPU": 4}`` for a 4-chip engine); every bundle additionally
    reserves one unit of the pool's ``slice:<id>`` resource."""

    name: str
    role: str                       # prefill | decode | draft | learner | rollout
    slice_id: str
    size: int = 1
    resources: dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.size < 1:
            raise ValueError(f"pool {self.name!r}: size must be >= 1")

    def bundles(self) -> list[dict]:
        return [
            {**self.resources, slice_resource(self.slice_id): 1.0}
            for _ in range(self.size)
        ]


@dataclasses.dataclass
class FabricPlan:
    """Reserved pools + the topology the transfer plane consults."""

    topology: FabricTopology
    specs: list
    groups: dict = dataclasses.field(default_factory=dict)  # pool -> pg

    def describe(self) -> dict:
        return {
            "pools": self.topology.pools(),
            "edges": self.topology.edges(),
            "placement_groups": {
                name: getattr(pg, "name", str(pg)) for name, pg in self.groups.items()
            },
        }

    def remove(self) -> None:
        import ray_tpu

        for pg in self.groups.values():
            try:
                ray_tpu.remove_placement_group(pg)
            except Exception:  # noqa: BLE001 — best-effort teardown
                logger.exception("failed to remove fabric placement group")
        self.groups.clear()


def build_topology(specs: list, links: Optional[list] = None) -> FabricTopology:
    """Topology alone (no reservations): what the in-process
    orchestrator consumes when pools are engine lists, not actors."""
    topo = FabricTopology()
    for spec in specs:
        topo.add_pool(spec.name, spec.role, spec.slice_id, spec.size)
    for a, b in links or ():
        topo.link(a, b)
    return topo


def build_fabric(specs: list, links: Optional[list] = None,
                 ready_timeout_s: float = 30.0) -> FabricPlan:
    """Reserve one STRICT_PACK placement group per pool (bundles pinned
    to the pool's slice resource) and return the plan. Raises
    ``PlacementGroupUnavailableError`` when a pool's slice can't hold it
    — a fabric that silently half-places would hand the transfer plane
    a topology map describing pools that don't exist."""
    import ray_tpu

    from ray_tpu.core.errors import PlacementGroupUnavailableError

    topo = build_topology(specs, links)
    plan = FabricPlan(topology=topo, specs=list(specs))
    try:
        for spec in specs:
            pg = ray_tpu.placement_group(
                spec.bundles(), strategy="STRICT_PACK",
                name=f"fabric-{spec.name}",
            )
            plan.groups[spec.name] = pg
            # ready() RAISES only for INFEASIBLE/REMOVED and returns
            # False for still-PENDING-at-deadline (core/placement.py) —
            # a transiently-full slice must fail the fabric too, not
            # hand back a topology describing unreserved pools
            if not pg.ready(timeout=ready_timeout_s):
                raise PlacementGroupUnavailableError(
                    f"fabric pool {spec.name!r} still PENDING on slice "
                    f"{spec.slice_id!r} after {ready_timeout_s}s"
                )
    except BaseException:
        plan.remove()  # all-or-nothing: no half-reserved fabric
        raise
    return plan
