"""Device-direct array transfer plane: ``send_arrays``/``recv_arrays``.

The generic half of the fabric (ROADMAP item 1 *and* the weight-sync
half of item 5 share it): named device arrays move between registered
**device endpoints** without ever being copied through host RAM.
``jax.device_put`` is the transfer primitive — on one process it
compiles to a device-to-device copy (ICI DMA between chips on a real
TPU slice, a memcpy between ``--xla_force_host_platform_device_count``
CPU devices on CI); the API is identical in both worlds, which is the
whole point: tier-1 exercises the exact code path a TPU pod runs.

Two clients ship in-tree and both go through this one API:

 * ``fabric.device_connector.DeviceKVConnector`` — prefill→decode KV
   handoffs (``k_pages``/``v_pages`` as device arrays);
 * ``train.weight_sync`` — learner→rollout weight publishes (a params
   pytree's leaves as device arrays).

Integrity: a bundle is sealed with a **device-computed** checksum
(``device_checksum`` — a bitcast-to-uint32 modular sum reduced on the
array's own device, so sealing multi-MB pages costs a 4-byte
device→host read, not a full copy). ``ArrayBundle.verify()`` re-reduces
on the receive side; a transfer that bit-flips in flight is detected at
import and handled as a lost transfer.

Chaos: every send passes the ``disagg.kv_transfer`` hook site (shared
with the host-path connectors so one schedule can target the whole
transfer plane) with the device-specific kinds —
``DROP_DEVICE_TRANSFER`` raises ``FabricTransferError`` before the
move, ``CORRUPT_DEVICE_TRANSFER`` bit-flips the pages *on device*
without re-sealing (the receiver's verify catches it), ``DELAY_RPC``
injects latency.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
import zlib
from typing import Any, Optional

from ray_tpu.chaos import harness as _chaos
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.fabric.transport")


class FabricTransferError(Exception):
    """An array transfer was dropped, timed out, or arrived corrupt.
    Callers re-send / re-derive from source — never decode from it."""


# -- device-side integrity ----------------------------------------------------

_UINT_OF_ITEMSIZE = {1: "uint8", 2: "uint16", 4: "uint32", 8: "uint32"}


def device_checksum(arr) -> int:
    """Order-independent modular checksum reduced ON the array's device:
    bitcast to a same-width uint lane type, widen to uint32, sum mod
    2^32. Only the 4-byte scalar crosses to the host — sealing never
    copies the payload off-device. Deterministic: a single-device
    integer reduction has one result whatever the scheduling."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(arr)
    if x.size == 0:
        return 0
    if x.dtype.itemsize == 8:
        # split 64-bit lanes into two 32-bit halves (no uint64 without x64)
        x = jax.lax.bitcast_convert_type(x, jnp.uint32)
    else:
        uname = _UINT_OF_ITEMSIZE[x.dtype.itemsize]
        x = jax.lax.bitcast_convert_type(x, jnp.dtype(uname))
    total = jnp.sum(x.astype(jnp.uint32), dtype=jnp.uint32)
    return int(jax.device_get(total)) & 0xFFFFFFFF


def corrupt_on_device(arr):
    """Deterministic device-side bit flip (CORRUPT_DEVICE_TRANSFER): XOR
    a span of lanes in the middle of the flattened array, on the array's
    device, returning a NEW array (copy-on-corrupt — the sender's copy
    stays intact, like a real torn wire)."""
    import jax
    import jax.numpy as jnp

    x = jnp.asarray(arr)
    if x.size == 0:
        return x
    uname = _UINT_OF_ITEMSIZE.get(x.dtype.itemsize, "uint8")
    bits = jax.lax.bitcast_convert_type(x, jnp.dtype(uname))
    shape = bits.shape
    flat = bits.reshape(-1)
    mid = flat.size // 2
    span = max(1, min(16, flat.size - mid))
    flipped = flat.at[mid : mid + span].set(~flat[mid : mid + span])
    return jax.lax.bitcast_convert_type(flipped.reshape(shape), x.dtype)


@dataclasses.dataclass
class ArrayBundle:
    """One named set of arrays in flight between endpoints. ``arrays``
    values are device arrays on the device path (host ndarrays are
    accepted too — ``seal``/``verify`` reduce wherever the data lives).
    ``meta`` is a small host-side dict that rides alongside (versions,
    request ids, token lists — never bulk data)."""

    bundle_id: str
    arrays: dict
    meta: dict = dataclasses.field(default_factory=dict)
    checksum: int = 0

    def _sum(self) -> int:
        # CHAINED CRC over (name, per-array device sum) pairs — chaining
        # (not commutative addition) binds each sum to its name and
        # position, so delivering two same-shape arrays with their
        # contents SWAPPED changes the result; only the 4-byte per-array
        # scalars ever cross to the host
        crc = 0
        for name in sorted(self.arrays):
            crc = zlib.crc32(name.encode(), crc)
            crc = zlib.crc32(
                device_checksum(self.arrays[name]).to_bytes(4, "big"), crc
            )
        return crc & 0xFFFFFFFF

    def seal(self) -> "ArrayBundle":
        self.checksum = self._sum()
        return self

    def verify(self) -> bool:
        return self.checksum == self._sum()

    @property
    def nbytes(self) -> int:
        return int(sum(getattr(a, "nbytes", 0) for a in self.arrays.values()))


# process-global endpoint queues + device map, namespaced like the
# in-process KV connector's: two fabrics in one process never
# cross-deliver, and serve replicas (in-process async actors) share one
# plane with a same-process orchestrator — a SENDER-side transport
# instance resolves a receiver-registered endpoint's device through the
# shared map (the device pin travels with the endpoint, not the
# instance)
_ENDPOINT_LOCK = threading.Lock()
_ENDPOINT_QUEUES: dict[tuple, "queue.Queue[ArrayBundle]"] = {}
_ENDPOINT_DEVICES: dict[tuple, Any] = {}


class DeviceTransport:
    """``send_arrays``/``recv_arrays`` over device-to-device placement.

    ``register_endpoint`` binds an endpoint id to a jax device (callers
    pass the device their consumer computes on — e.g. the decode
    engine's KV-cache device — or let the transport round-robin the
    local devices). ``send_arrays`` moves every array onto the target's
    device with ``jax.device_put`` — the ICI hop on real hardware —
    and enqueues only *references*; nothing is serialized and no host
    staging buffer exists on this path. On a multi-host pod the
    endpoint map would name remote meshes and the put becomes a
    collective permute; the contract here (opaque target token in,
    checksum/timeout failure modes out) is written so that backend
    slots in without touching any caller.
    """

    name = "device"

    def __init__(self, namespace: str = "default", devices: Optional[list] = None,
                 endpoint_capacity: int = 64):
        import jax

        self.namespace = namespace
        self._devices = list(devices) if devices is not None else list(jax.devices())
        if not self._devices:
            raise FabricTransferError("no jax devices visible to the transport")
        # bounded endpoints: every queued bundle pins device memory, so a
        # receiver that stopped draining must fail the SENDER with the
        # documented timeout failure mode — never grow until the device
        # OOMs (the RPC plane's equivalent is its torn-chunk GC)
        self.endpoint_capacity = int(endpoint_capacity)
        self._lock = threading.Lock()
        self._endpoints: dict[str, Any] = {}  # endpoint_id -> device
        self._rr = 0
        self.num_sent = 0
        self.num_received = 0
        self.num_dropped = 0
        self.bytes_sent = 0

    # -- endpoints ------------------------------------------------------------

    def register_endpoint(self, endpoint_id: str, device: Any = None) -> tuple:
        """Create the receive side for ``endpoint_id`` pinned to
        ``device`` (round-robin over local devices when omitted);
        returns the opaque target token ``send_arrays`` addresses."""
        with self._lock:
            if device is None:
                device = self._devices[self._rr % len(self._devices)]
                self._rr += 1
            self._endpoints[endpoint_id] = device
        with _ENDPOINT_LOCK:
            _ENDPOINT_QUEUES.setdefault(
                (self.namespace, endpoint_id),
                queue.Queue(maxsize=self.endpoint_capacity),
            )
            _ENDPOINT_DEVICES[(self.namespace, endpoint_id)] = device
        return (self.namespace, endpoint_id)

    def endpoint_device(self, endpoint_id: str):
        with self._lock:
            dev = self._endpoints.get(endpoint_id)
        if dev is not None:
            return dev
        with _ENDPOINT_LOCK:
            return _ENDPOINT_DEVICES.get((self.namespace, endpoint_id))

    def _queue(self, endpoint_id: str) -> "queue.Queue[ArrayBundle]":
        with _ENDPOINT_LOCK:
            q = _ENDPOINT_QUEUES.get((self.namespace, endpoint_id))
        if q is None:
            raise FabricTransferError(
                f"unknown fabric endpoint {endpoint_id!r} in namespace "
                f"{self.namespace!r} (register_endpoint first)"
            )
        return q

    # -- transfer -------------------------------------------------------------

    def send_arrays(self, target: tuple, arrays: dict, meta: Optional[dict] = None,
                    timeout_s: float = 30.0, bundle_id: str = "",
                    seal: bool = True) -> None:
        """Move ``arrays`` (name -> array) onto the target endpoint's
        device and deliver them as one ``ArrayBundle``. Raises
        ``FabricTransferError`` on a dropped transfer (chaos, unknown
        endpoint, or an endpoint whose backlog stayed full past
        ``timeout_s`` — a consumer that stopped draining fails the
        sender instead of pinning device memory without bound). Pass
        ``seal=False`` when the payload carries its OWN verified
        integrity (the KV connector's device-sealed handoff) — skipping
        the bundle seal saves two synchronizing device reductions per
        transfer on that hot path; ``recv_arrays`` consumers must then
        verify the payload, not the bundle."""
        import jax
        import time as _time

        # the token names the endpoint's own namespace (normally this
        # instance's, but an opaque token from another same-process
        # transport addresses fine — the plane is the process-global map)
        ns, endpoint_id = target
        bundle = ArrayBundle(
            bundle_id=bundle_id or f"{endpoint_id}-{self.num_sent}",
            arrays=dict(arrays), meta=dict(meta or {}),
        )
        if seal:
            bundle.seal()
        if _chaos.ACTIVE is not None:
            for _f in _chaos.fire(
                "disagg.kv_transfer",
                kinds=(_chaos.DROP_DEVICE_TRANSFER,
                       _chaos.CORRUPT_DEVICE_TRANSFER, _chaos.DELAY_RPC),
                bundle_id=bundle.bundle_id, connector=self.name,
                target=endpoint_id,
            ):
                if _f.kind == _chaos.DROP_DEVICE_TRANSFER:
                    self.num_dropped += 1
                    raise FabricTransferError(
                        f"chaos: dropped device transfer of "
                        f"{bundle.bundle_id!r} to {endpoint_id}"
                    )
                if _f.kind == _chaos.DELAY_RPC:
                    _time.sleep(_f.delay_s)
                if _f.kind == _chaos.CORRUPT_DEVICE_TRANSFER:
                    # checksum is NOT re-sealed: the receiver catches it
                    bundle = dataclasses.replace(bundle, arrays={
                        name: (corrupt_on_device(a)
                               if name == min(bundle.arrays) else a)
                        for name, a in bundle.arrays.items()
                    })
        with _ENDPOINT_LOCK:
            q = _ENDPOINT_QUEUES.get((ns, endpoint_id))
            device = _ENDPOINT_DEVICES.get((ns, endpoint_id))
        if q is None:
            raise FabricTransferError(
                f"unknown fabric endpoint {endpoint_id!r} in namespace "
                f"{ns!r} (register_endpoint first)"
            )
        if device is not None:
            bundle.arrays = {
                name: jax.device_put(a, device)
                for name, a in bundle.arrays.items()
            }
        try:
            q.put(bundle, timeout=timeout_s)
        except queue.Full:
            self.num_dropped += 1
            raise FabricTransferError(
                f"endpoint {endpoint_id!r} backlog full "
                f"({self.endpoint_capacity} bundles) for {timeout_s}s — "
                "consumer stopped draining"
            ) from None
        self.num_sent += 1
        self.bytes_sent += bundle.nbytes

    def recv_arrays(self, endpoint_id: str,
                    timeout_s: float = 0.1) -> Optional[ArrayBundle]:
        """Bounded receive; None when nothing arrived within the timeout
        (callers poll — the transfer plane never parks a consumer loop
        forever)."""
        try:
            b = self._queue(endpoint_id).get(timeout=timeout_s)
        except queue.Empty:
            return None
        self.num_received += 1
        return b

    def close(self) -> None:
        with self._lock:
            eids = list(self._endpoints)
            self._endpoints.clear()
        with _ENDPOINT_LOCK:
            for eid in eids:
                _ENDPOINT_QUEUES.pop((self.namespace, eid), None)
                _ENDPOINT_DEVICES.pop((self.namespace, eid), None)

    def stats(self) -> dict:
        return {
            "transport": self.name,
            "namespace": self.namespace,
            "num_sent": self.num_sent,
            "num_received": self.num_received,
            "num_dropped": self.num_dropped,
            "bytes_sent": self.bytes_sent,
        }
