"""In-process fake cluster for multi-node tests.

Reference analog: python/ray/cluster_utils.py:135 (Cluster — N raylets
sharing one GCS, used by scheduling/FT/placement tests). Here a "node"
is a capacity domain registered in the GCS: placement groups spread/
pack across them exactly as across real hosts, while execution remains
in-process threads (the TPU host model — see core/scheduler.py).
"""

from __future__ import annotations

import threading
from typing import Optional

from ray_tpu.core.gcs import NodeInfo
from ray_tpu.core.resources import NodeResources, ResourceSet
from ray_tpu.utils.ids import NodeID


class Cluster:
    def __init__(self, initialize_head: bool = True, head_node_args: Optional[dict] = None):
        import ray_tpu
        from ray_tpu.core import runtime as rt

        self._lock = threading.Lock()
        self._nodes: list[NodeInfo] = []
        if not rt.is_initialized():
            ray_tpu.init(**(head_node_args or {}))
        self._runtime = rt.get_runtime()
        if initialize_head:
            # the runtime's own node is the head
            self.head_node = self._runtime.gcs.get_node(self._runtime.node_id)

    def add_node(
        self,
        num_cpus: float = 1.0,
        num_tpus: float = 0.0,
        resources: Optional[dict] = None,
    ) -> NodeInfo:
        total = dict(resources or {})
        total["CPU"] = num_cpus
        if num_tpus:
            total["TPU"] = num_tpus
        info = NodeInfo(NodeID.from_random(), NodeResources(ResourceSet(total)))
        self._runtime.gcs.register_node(info)
        with self._lock:
            self._nodes.append(info)
        self._retry_pending_pgs()
        return info

    def remove_node(self, node: NodeInfo) -> None:
        self._runtime.gcs.remove_node(node.node_id)
        with self._lock:
            if node in self._nodes:
                self._nodes.remove(node)

    def _retry_pending_pgs(self) -> None:
        from ray_tpu.core.placement import retry_pending_placement_groups

        retry_pending_placement_groups(self._runtime)

    @property
    def nodes(self) -> list[NodeInfo]:
        with self._lock:
            return list(self._nodes)

    def shutdown(self) -> None:
        for n in self.nodes:
            self.remove_node(n)
