"""Workflow storage: durable per-step results + workflow metadata.

Reference analog: python/ray/workflow/workflow_storage.py:229
(WorkflowStorage over a filesystem/S3 store). Exactly-once comes from
atomic write-then-rename of step results: a step whose result file
exists is never re-executed on resume.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Any, Optional

# cloudpickle: DAGs close over locally-defined task functions (same choice
# as the reference's vendored cloudpickle for task serialization)
import cloudpickle as pickle


class WorkflowStorage:
    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    # -- paths ---------------------------------------------------------------

    def _wf_dir(self, workflow_id: str) -> str:
        return os.path.join(self.root, workflow_id)

    def _step_path(self, workflow_id: str, step_key: str) -> str:
        return os.path.join(self._wf_dir(workflow_id), "steps", f"{step_key}.pkl")

    def _meta_path(self, workflow_id: str) -> str:
        return os.path.join(self._wf_dir(workflow_id), "meta.json")

    # -- atomic writes ---------------------------------------------------------

    @staticmethod
    def _atomic_write(path: str, data: bytes) -> None:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, path)  # atomic on POSIX
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    # -- step results -----------------------------------------------------------

    def has_step(self, workflow_id: str, step_key: str) -> bool:
        return os.path.exists(self._step_path(workflow_id, step_key))

    def save_step(self, workflow_id: str, step_key: str, result: Any) -> None:
        self._atomic_write(
            self._step_path(workflow_id, step_key), pickle.dumps(result)
        )

    def load_step(self, workflow_id: str, step_key: str) -> Any:
        with open(self._step_path(workflow_id, step_key), "rb") as f:
            return pickle.load(f)

    # -- workflow metadata -------------------------------------------------------

    def save_meta(self, workflow_id: str, meta: dict) -> None:
        meta = dict(meta, updated_at=time.time())
        self._atomic_write(
            self._meta_path(workflow_id), json.dumps(meta).encode()
        )

    def load_meta(self, workflow_id: str) -> Optional[dict]:
        p = self._meta_path(workflow_id)
        if not os.path.exists(p):
            return None
        with open(p) as f:
            return json.load(f)

    def save_dag(self, workflow_id: str, dag) -> None:
        self._atomic_write(
            os.path.join(self._wf_dir(workflow_id), "dag.pkl"), pickle.dumps(dag)
        )

    def load_dag(self, workflow_id: str):
        with open(os.path.join(self._wf_dir(workflow_id), "dag.pkl"), "rb") as f:
            return pickle.load(f)

    def list_workflows(self) -> list:
        if not os.path.isdir(self.root):
            return []
        out = []
        for wid in sorted(os.listdir(self.root)):
            meta = self.load_meta(wid)
            if meta is not None:
                out.append((wid, meta))
        return out

    def delete(self, workflow_id: str) -> None:
        import shutil

        shutil.rmtree(self._wf_dir(workflow_id), ignore_errors=True)
