"""ray_tpu.workflow: durable task DAGs with exactly-once steps.

Reference analog: python/ray/workflow/ (api.py:123 run, workflow
executor + storage). Steps checkpoint to storage atomically; resume
skips completed steps; a step returning a DAG continues into it.
"""

from ray_tpu.workflow.api import (
    delete,
    get_output,
    get_status,
    init,
    list_all,
    resume,
    run,
    run_async,
)
from ray_tpu.workflow.execution import WorkflowStatus

__all__ = [
    "WorkflowStatus",
    "delete",
    "get_output",
    "get_status",
    "init",
    "list_all",
    "resume",
    "run",
    "run_async",
]
