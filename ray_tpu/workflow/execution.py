"""Workflow executor: run a task DAG with durable, exactly-once steps.

Reference analog: python/ray/workflow/workflow_executor.py:32 +
workflow_context.py. Steps whose results exist in storage are skipped
on resume; a step returning another DAG is a continuation
(reference: workflow.continuation) executed in its place.
"""

from __future__ import annotations

import traceback
from typing import Any, Optional

from ray_tpu.dag.nodes import DAGNode, FunctionNode, InputNode, MultiOutputNode
from ray_tpu.utils.logging import get_logger
from ray_tpu.workflow.storage import WorkflowStorage

logger = get_logger("ray_tpu.workflow")


class WorkflowStatus:
    RUNNING = "RUNNING"
    SUCCESSFUL = "SUCCESSFUL"
    FAILED = "FAILED"
    RESUMABLE = "RESUMABLE"


def _step_key(node: FunctionNode) -> str:
    """Stable key: node id (creation-ordered, preserved by DAG pickling) +
    task name — one key per NODE, so a diamond-shared upstream step runs
    once, not once per consuming path."""
    return f"n{node.id}-{node.task_name}"


class WorkflowExecutor:
    def __init__(self, storage: WorkflowStorage, workflow_id: str):
        self.storage = storage
        self.workflow_id = workflow_id
        self._memo: dict[int, Any] = {}  # node.id -> result (this run)

    def run(self, dag: DAGNode) -> Any:
        meta = self.storage.load_meta(self.workflow_id) or {}
        meta.update(status=WorkflowStatus.RUNNING)
        self.storage.save_meta(self.workflow_id, meta)
        try:
            result = self._exec_node(dag, "root")
        except BaseException as e:
            self.storage.save_meta(
                self.workflow_id,
                {
                    "status": WorkflowStatus.RESUMABLE,
                    "error": f"{type(e).__name__}: {e}",
                    "traceback": traceback.format_exc(),
                },
            )
            raise
        self.storage.save_step(self.workflow_id, "__output__", result)
        self.storage.save_meta(self.workflow_id, {"status": WorkflowStatus.SUCCESSFUL})
        return result

    def _exec_node(self, node: Any, path: str) -> Any:
        if isinstance(node, MultiOutputNode):
            return [
                self._exec_node(o, f"{path}.{i}") for i, o in enumerate(node.outputs)
            ]
        if isinstance(node, FunctionNode):
            return self._exec_step(node, path)
        if isinstance(node, InputNode):
            raise ValueError("workflows take no InputNode; bind concrete args")
        if isinstance(node, DAGNode):
            raise TypeError(f"workflows support task nodes only, got {type(node)}")
        return node  # plain value

    def _exec_step(self, node: FunctionNode, path: str) -> Any:
        if node.id in self._memo:
            return self._memo[node.id]
        key = _step_key(node)
        if self.storage.has_step(self.workflow_id, key):
            result = self.storage.load_step(self.workflow_id, key)
            self._memo[node.id] = result
            return result

        args = [
            self._exec_node(a, f"{path}.a{i}") for i, a in enumerate(node.args)
        ]
        kwargs = {
            k: self._exec_node(v, f"{path}.k{k}") for k, v in node.kwargs.items()
        }
        import ray_tpu

        result = ray_tpu.get(node.remote_fn.remote(*args, **kwargs))
        if isinstance(result, DAGNode):
            # continuation: the step expanded into a sub-DAG
            result = self._exec_node(result, f"{path}.c")
        self.storage.save_step(self.workflow_id, key, result)
        self._memo[node.id] = result
        return result
