"""Workflow public API (reference: python/ray/workflow/api.py —
run:123, run_async:177, resume, get_status, list_all, get_output)."""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

from ray_tpu.dag.nodes import DAGNode
from ray_tpu.workflow.execution import WorkflowExecutor, WorkflowStatus
from ray_tpu.workflow.storage import WorkflowStorage

_storage: Optional[WorkflowStorage] = None
_lock = threading.Lock()
_counter = [0]


def init(storage_dir: Optional[str] = None) -> None:
    """Configure workflow storage (default: RAY_TPU_WORKFLOW_DIR or
    ~/.ray_tpu/workflows)."""
    global _storage
    root = (
        storage_dir
        or os.environ.get("RAY_TPU_WORKFLOW_DIR")
        or os.path.expanduser("~/.ray_tpu/workflows")
    )
    _storage = WorkflowStorage(root)


def _get_storage() -> WorkflowStorage:
    with _lock:
        if _storage is None:
            init()
        return _storage


def _new_id() -> str:
    import time

    with _lock:
        _counter[0] += 1
        return f"workflow-{int(time.time())}-{_counter[0]}"


def run(dag: DAGNode, *, workflow_id: Optional[str] = None) -> Any:
    """Execute a task DAG durably; blocks until done. Re-running an
    interrupted workflow_id resumes the STORED dag (step identity is
    node-based, so a freshly rebuilt graph would re-execute everything)."""
    storage = _get_storage()
    wid = workflow_id or _new_id()
    meta = storage.load_meta(wid)
    if meta is not None and meta.get("status") != "SUCCESSFUL":
        dag = storage.load_dag(wid)
    else:
        storage.save_dag(wid, dag)
    return WorkflowExecutor(storage, wid).run(dag)


def run_async(dag: DAGNode, *, workflow_id: Optional[str] = None):
    """Submit a workflow; returns an ObjectRef for its output. Like run(),
    re-submitting an interrupted workflow_id drives the STORED dag — step
    identity is node-based, so saving a freshly built graph would orphan
    every completed step and re-execute them all."""
    import ray_tpu

    storage = _get_storage()
    wid = workflow_id or _new_id()
    meta = storage.load_meta(wid)
    if meta is None or meta.get("status") == "SUCCESSFUL":
        storage.save_dag(wid, dag)

    @ray_tpu.remote
    def _drive(workflow_id: str):
        return WorkflowExecutor(_get_storage(), workflow_id).run(
            _get_storage().load_dag(workflow_id)
        )

    return _drive.options(name=f"workflow:{wid}").remote(wid)


def resume(workflow_id: str) -> Any:
    """Re-run a failed/interrupted workflow; completed steps are skipped."""
    storage = _get_storage()
    dag = storage.load_dag(workflow_id)
    return WorkflowExecutor(storage, workflow_id).run(dag)


def get_status(workflow_id: str) -> Optional[str]:
    meta = _get_storage().load_meta(workflow_id)
    return meta["status"] if meta else None


def get_output(workflow_id: str) -> Any:
    storage = _get_storage()
    if not storage.has_step(workflow_id, "__output__"):
        raise ValueError(f"workflow {workflow_id!r} has no stored output")
    return storage.load_step(workflow_id, "__output__")


def list_all(status_filter: Optional[str] = None) -> list:
    out = []
    for wid, meta in _get_storage().list_workflows():
        if status_filter is None or meta.get("status") == status_filter:
            out.append((wid, meta.get("status")))
    return out


def delete(workflow_id: str) -> None:
    _get_storage().delete(workflow_id)
