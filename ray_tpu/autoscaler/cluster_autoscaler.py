"""Autoscaler driving the DISTRIBUTED cluster plane.

Reference analog: the autoscaler monitor reading resource-demand
reports the raylets ship to the GCS and asking a NodeProvider for
more/fewer nodes (python/ray/autoscaler/_private/monitor.py,
autoscaler.py StandardAutoscaler.update). Here:

  * demand: every node daemon ships its server-side lease queue's
    resource specs in its heartbeat; `cluster_demand` on the GCS
    aggregates them (gcs_service.rpc_cluster_demand);
  * supply: a NodeProvider that launches/terminates REAL node-daemon
    processes — `LocalClusterNodeProvider` drives a LocalCluster the
    way the reference's fake multinode provider drives sub-raylets.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

from ray_tpu.autoscale.demand import plan_launches
from ray_tpu.autoscaler.autoscaler import AutoscalerConfig, NodeTypeConfig
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.autoscaler.cluster")


class LocalClusterNodeProvider(NodeProvider):
    """Launch/terminate real node-daemon processes on a LocalCluster."""

    def __init__(self, cluster):
        self._cluster = cluster
        self._counter = 0
        self._mine: set[str] = set()

    def create_node(self, node_type: str, resources: dict) -> str:
        self._counter += 1
        node_id = f"auto-{node_type}-{self._counter}"
        self._cluster.add_node(dict(resources), node_id=node_id)
        self._mine.add(node_id)
        return node_id

    def terminate_node(self, node_id: str) -> None:
        self._mine.discard(node_id)
        self._cluster.kill_node(node_id)

    def non_terminated_nodes(self) -> list[str]:
        alive = {n["node_id"] for n in self._cluster.client().nodes() if n["alive"]}
        return sorted(self._mine & alive)

    def node_resources(self, node_id: str) -> dict:
        for n in self._cluster.client().nodes():
            if n["node_id"] == node_id:
                return dict(n["resources"])
        return {}

    def is_idle(self, node_id: str) -> bool:
        """Idle = no resources in use AND no live leases AND no stored
        objects. Resource counters alone are not enough: zero-resource
        actors consume nothing (the node reads available==total), and a
        resource-idle node can hold the only copy of task-return objects
        — terminating it would destroy both without drain (reference:
        the autoscaler counts object-store usage and active workers
        toward idleness, autoscaler/_private/autoscaler.py)."""
        client = self._cluster.client()
        for n in client.nodes():
            if n["node_id"] != node_id:
                continue
            if n.get("available") != n.get("resources"):
                return False
            try:
                # direct short-timeout client, NOT client.pool (the pool
                # dials with timeout=120s x retries — a hung daemon would
                # freeze the whole reconcile thread for minutes)
                from ray_tpu.cluster.rpc import RpcClient

                host, port = tuple(n["addr"])
                c = RpcClient(host, int(port), timeout=5.0).connect(retries=0)
                try:
                    stats = c.call("stats", None, timeout=5)
                finally:
                    c.close()
            except Exception:
                return False  # unreachable ≠ provably idle; don't kill
            if stats.get("num_leases", 0) > 0:
                return False
            if stats.get("objects", {}).get("num_objects", 0) > 0:
                return False
            return True
        return True


class ClusterAutoscaler:
    """Reconcile cluster-plane demand against a NodeProvider.

    Same binpack policy as the in-process StandardAutoscaler, but demand
    and idleness come from the GCS's aggregated heartbeat view instead
    of the local scheduler queue.
    """

    def __init__(self, config: AutoscalerConfig, provider: NodeProvider, gcs):
        self.config = config
        self.provider = provider
        self._gcs = gcs  # RpcClient (or any .call("cluster_demand", None))
        self._idle_since: dict[str, float] = {}
        self._node_type: dict[str, str] = {}
        # in-flight launches: a freshly-spawned daemon takes seconds to
        # register and absorb the queued lease that justified it, during
        # which the demand spec is STILL in the heartbeat feed — without
        # netting launches against demand every tick would launch again
        # (reference: the autoscaler's pending-launch accounting)
        self._launching: dict[str, tuple[dict, float]] = {}
        self._launch_grace_s = 30.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        for tname, tcfg in config.node_types.items():
            for _ in range(tcfg.min_workers):
                self._launch(tname, tcfg)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="ray_tpu-cluster-autoscaler", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.reconcile()
            except Exception:
                logger.exception("cluster autoscaler tick failed")

    # -- demand ---------------------------------------------------------------

    def pending_demand(self) -> list[dict]:
        """Queued lease specs that no alive node could EVER host — plus
        queued specs that fit somewhere but are waiting on capacity (the
        scale-up signal the reference acts on)."""
        view = self._gcs.call("cluster_demand", None)
        return [dict(s) for s in view["pending"] if s]

    def reconcile(self) -> None:
        self._scale_up()
        self._scale_down()

    def _count(self, tname: str) -> int:
        return sum(1 for t in self._node_type.values() if t == tname)

    def _launch(self, tname: str, tcfg: NodeTypeConfig) -> Optional[str]:
        if self._count(tname) >= tcfg.max_workers:
            return None
        nid = self.provider.create_node(tname, dict(tcfg.resources))
        self._node_type[nid] = tname
        self._launching[nid] = (dict(tcfg.resources), time.time())
        logger.info("cluster scale-up: %s (%s)", nid, tcfg.resources)
        return nid

    def _scale_up(self) -> None:
        demand = self.pending_demand()
        if not demand:
            self._launching = {
                k: v for k, v in self._launching.items()
                if time.time() - v[1] <= self._launch_grace_s
            }
            return
        # seed the plan with capacity already launched but not yet
        # absorbed, so repeat ticks don't re-buy the same demand
        now = time.time()
        self._launching = {
            k: v for k, v in self._launching.items()
            if now - v[1] <= self._launch_grace_s
        }
        planned_types, unplaced = plan_launches(
            demand, self.config.node_types, self._count,
            seed_capacity=[res for res, _ in self._launching.values()],
        )
        for req in unplaced:
            logger.warning("demand %s fits no configured node type", req)
        for tname in planned_types:
            self._launch(tname, self.config.node_types[tname])

    def _node_idle(self, nid: str) -> bool:
        """Idleness from the CLUSTER view first, the provider second: a
        cloud provider (TPUPodProvider) cannot see occupancy, so a busy
        slice would read idle from is_idle alone. Contract: daemons on
        provider-launched nodes register with node_id == the provider's
        node id (the LocalClusterNodeProvider and the TPU startup script
        both do), so the GCS resource view keys by it."""
        try:
            nodes = {n["node_id"]: n for n in self._gcs.call("list_nodes", None)}
        except Exception:  # noqa: BLE001 — GCS unreachable: don't cull
            return False
        rec = nodes.get(nid)
        if rec is not None and rec.get("alive"):
            if rec.get("available") != rec.get("resources"):
                return False  # resources in use on the slice
        return self.provider.is_idle(nid)

    def _scale_down(self) -> None:
        now = time.time()
        # reap bookkeeping for nodes that died on their own (daemon crash):
        # leaving them in _node_type would count them against max_workers
        # forever and starve replacement launches
        live = set(self.provider.non_terminated_nodes())
        for nid in list(self._node_type):
            if nid in live:
                continue
            launching = self._launching.get(nid)
            if launching is not None and now - launching[1] <= self._launch_grace_s:
                continue  # still booting; not registered yet
            self._node_type.pop(nid, None)
            self._idle_since.pop(nid, None)
            self._launching.pop(nid, None)
        for nid in list(live):
            tname = self._node_type.get(nid)
            if tname is None:
                continue
            launching = self._launching.get(nid)
            if launching is not None and now - launching[1] <= self._launch_grace_s:
                # a slice still provisioning (cloud create can take
                # minutes) reads idle — culling it here would thrash
                # create/delete against the provider
                continue
            tcfg = self.config.node_types[tname]
            if not self._node_idle(nid):
                self._idle_since.pop(nid, None)
                continue
            first_idle = self._idle_since.setdefault(nid, now)
            if (
                now - first_idle >= self.config.idle_timeout_s
                and self._count(tname) > tcfg.min_workers
            ):
                self.provider.terminate_node(nid)
                self._node_type.pop(nid, None)
                self._idle_since.pop(nid, None)
                logger.info("cluster scale-down: idle node %s", nid)

    def status(self) -> dict:
        return {
            "nodes": {
                nid: self._node_type.get(nid)
                for nid in self.provider.non_terminated_nodes()
            },
            "pending_demand": self.pending_demand(),
        }
