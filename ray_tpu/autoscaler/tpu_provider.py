"""GCE TPU pod-slice NodeProvider over the queued-resources API.

Reference analog: python/ray/autoscaler/_private/gcp/node_provider.py
(GCPNodeProvider) + gcp/node.py GCPTPU resource (tpu.googleapis.com
v2alpha1) + gcp/tpu_command_runner.py. Redesigned around QUEUED
RESOURCES — the modern way to obtain pod slices (create returns a
queued-resource whose state machine walks CREATING -> ACCEPTED ->
PROVISIONING -> ACTIVE; deletion walks DELETING -> gone) — instead of
the reference's direct node create.

All cloud I/O goes through an injectable `Transport` (`request(method,
path, body) -> dict`): production wires an authorized HTTP session;
tests (and this zero-egress environment) wire recorded fixtures, so the
provider's full lifecycle logic is exercised without credentials
(tests/test_tpu_provider.py drives scale-up/down through the
ClusterAutoscaler against it).
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Any, Callable, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.core.accelerators import parse_pod_type
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.autoscaler.tpu")

# queued-resource states (tpu.googleapis.com v2alpha1 QueuedResourceState)
_PENDING = ("CREATING", "ACCEPTED", "PROVISIONING", "WAITING_FOR_RESOURCES")
_LIVE = ("ACTIVE",)
_DEAD = ("FAILED", "SUSPENDED", "SUSPENDING", "DELETING")


class Transport:
    """Cloud HTTP seam. `path` is relative to the TPU API base
    (projects/{p}/locations/{z}/...); returns the decoded JSON body."""

    def request(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        raise NotImplementedError


class HttpTransport(Transport):  # pragma: no cover - needs GCP egress
    """Production transport: authorized requests against
    https://tpu.googleapis.com/v2alpha1/. Requires
    google-auth/credentials, absent in this image — constructed lazily
    so importing the provider never needs the dependency."""

    BASE = "https://tpu.googleapis.com/v2alpha1/"

    def __init__(self, credentials=None):
        import importlib

        auth = importlib.import_module("google.auth")
        self._session_mod = importlib.import_module(
            "google.auth.transport.requests"
        )
        if credentials is None:
            credentials, _ = auth.default(
                scopes=["https://www.googleapis.com/auth/cloud-platform"]
            )
        self._session = self._session_mod.AuthorizedSession(credentials)

    def request(self, method: str, path: str, body: Optional[dict] = None) -> dict:
        r = self._session.request(method, self.BASE + path, json=body)
        r.raise_for_status()
        return r.json() if r.content else {}


class TPUPodProvider(NodeProvider):
    """Pod-slice lifecycle through queued resources.

    One provider node == one queued resource == one TPU pod slice (all
    its hosts). `resources` passed to create_node may carry a
    "tpu_pod_type" override; otherwise the provider default applies.
    """

    def __init__(
        self,
        project: str,
        zone: str,
        transport: Transport,
        accelerator_type: str = "v5litepod-8",
        runtime_version: str = "v2-alpha-tpuv5-lite",
        startup_script: str = "",
        poll_interval_s: float = 5.0,
        cluster_name: str = "ray-tpu",
    ):
        self.project = project
        self.zone = zone
        self.transport = transport
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.startup_script = startup_script
        self.poll_interval_s = poll_interval_s
        self.cluster_name = cluster_name
        self._lock = threading.Lock()
        self._nodes: dict[str, dict] = {}  # qr_id -> last known record
        self._parent = f"projects/{project}/locations/{zone}"

    # -- raw API calls --------------------------------------------------------

    def _qr_path(self, qr_id: str) -> str:
        return f"{self._parent}/queuedResources/{qr_id}"

    def _list_qrs(self) -> list[dict]:
        out: list[dict] = []
        page: Optional[str] = None
        while True:
            path = f"{self._parent}/queuedResources"
            if page:
                path += f"?pageToken={page}"
            r = self.transport.request("GET", path)
            out.extend(r.get("queuedResources", ()))
            page = r.get("nextPageToken")
            if not page:
                return out

    @staticmethod
    def _state(rec: dict) -> str:
        return (rec.get("state") or {}).get("state", "CREATING")

    def _is_ours(self, rec: dict) -> bool:
        specs = rec.get("tpu", {}).get("nodeSpec") or [{}]
        labels = specs[0].get("node", {}).get("labels", {})
        return labels.get("ray-cluster-name") == self.cluster_name

    # -- NodeProvider ---------------------------------------------------------

    def create_node(self, node_type: str, resources: dict) -> str:
        pod_type = resources.get("tpu_pod_type", self.accelerator_type)
        topo = parse_pod_type(pod_type)  # validates before spending quota
        qr_id = f"ray-{node_type}-{uuid.uuid4().hex[:8]}"
        body = {
            "tpu": {
                "nodeSpec": [
                    {
                        "parent": self._parent,
                        "nodeId": qr_id,
                        "node": {
                            "acceleratorType": pod_type,
                            "runtimeVersion": self.runtime_version,
                            "labels": {
                                "ray-cluster-name": self.cluster_name,
                                "ray-node-type": node_type,
                            },
                            "metadata": {
                                "startup-script": self.startup_script
                            },
                        },
                    }
                ]
            },
        }
        rec = self.transport.request(
            "POST",
            f"{self._parent}/queuedResources?queuedResourceId={qr_id}",
            body,
        )
        with self._lock:
            self._nodes[qr_id] = rec if rec.get("name") else {
                "name": self._qr_path(qr_id), "state": {"state": "CREATING"},
            }
        logger.info(
            "queued TPU slice %s (%s: %d chips / %d hosts)",
            qr_id, pod_type, topo.num_chips, topo.num_hosts,
        )
        return qr_id

    def terminate_node(self, node_id: str) -> None:
        try:
            self.transport.request(
                "DELETE", f"{self._qr_path(node_id)}?force=true"
            )
        except Exception as e:  # noqa: BLE001 — already gone counts as done
            logger.warning("delete of %s failed: %s", node_id, e)
        with self._lock:
            rec = self._nodes.get(node_id)
            if rec is not None:
                rec.setdefault("state", {})["state"] = "DELETING"

    def non_terminated_nodes(self) -> list[str]:
        self.refresh()
        with self._lock:
            return sorted(
                qr for qr, rec in self._nodes.items()
                if self._state(rec) in _PENDING + _LIVE
            )

    def node_resources(self, node_id: str) -> dict:
        with self._lock:
            rec = self._nodes.get(node_id)
        if rec is None:
            return {}
        spec = (
            rec.get("tpu", {}).get("nodeSpec", [{}])[0].get("node", {})
        )
        pod_type = spec.get("acceleratorType", self.accelerator_type)
        topo = parse_pod_type(pod_type)
        return {
            "TPU": float(topo.num_chips),
            topo.slice_resource_name: float(topo.num_hosts),
        }

    def is_idle(self, node_id: str) -> bool:
        """The cloud cannot see cluster occupancy. The ClusterAutoscaler
        checks the GCS resource view FIRST (_node_idle: a slice whose
        daemon reports resources in use is never culled; daemons on
        provider-launched slices register with node_id == this provider
        id) — the provider-level True only confirms there is no
        cloud-side reason to keep the slice."""
        return True

    # -- state machine --------------------------------------------------------

    def refresh(self) -> None:
        """Reconcile the local table against the API: adopt externally
        visible queued resources with our cluster label, drop records
        the API no longer returns (deletion completed)."""
        try:
            listed = {r["name"].rsplit("/", 1)[-1]: r for r in self._list_qrs()}
        except Exception as e:  # noqa: BLE001 — transient API failure
            logger.warning("queuedResources list failed: %s", e)
            return
        with self._lock:
            for qr_id, rec in listed.items():
                if qr_id in self._nodes or self._is_ours(rec):
                    self._nodes[qr_id] = rec
            for qr_id in list(self._nodes):
                if qr_id not in listed:
                    del self._nodes[qr_id]  # deletion finished

    def node_state(self, node_id: str) -> Optional[str]:
        with self._lock:
            rec = self._nodes.get(node_id)
        return None if rec is None else self._state(rec)

    def active_nodes(self) -> list[str]:
        self.refresh()
        with self._lock:
            return sorted(
                qr for qr, rec in self._nodes.items()
                if self._state(rec) in _LIVE
            )

    def wait_active(self, node_id: str, timeout: float = 1800.0,
                    sleep: Optional[Callable[[float], Any]] = None) -> bool:
        """Poll the queued resource until ACTIVE / dead / timeout."""
        sleep = sleep or time.sleep
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            self.refresh()
            st = self.node_state(node_id)
            if st in _LIVE:
                return True
            if st is None or st in _DEAD:
                return False
            sleep(self.poll_interval_s)
        return False
