"""NodeProvider: pluggable node lifecycle backend for the autoscaler.

Reference analog: python/ray/autoscaler/node_provider.py:13 (ABC with
aws/gcp/kuberay/fake_multi_node implementations). Two built-ins here:
FakeNodeProvider (in-process capacity domains, the fake_multi_node
analog used by tests) and a GCE/TPU-pod provider stub documenting the
production surface (zero-egress image: no cloud calls possible).
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class NodeProvider:
    """Subclass per infrastructure backend."""

    def create_node(self, node_type: str, resources: dict) -> str:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[str]:
        raise NotImplementedError

    def node_resources(self, node_id: str) -> dict:
        raise NotImplementedError

    def is_idle(self, node_id: str) -> bool:
        """All capacity available (no reservations)."""
        raise NotImplementedError


class FakeNodeProvider(NodeProvider):
    """Registers capacity-domain nodes in the local GCS (the reference's
    fake_multi_node docker provider, minus docker)."""

    def __init__(self):
        from ray_tpu.core import runtime as rt

        self._runtime = rt.get_runtime()
        self._nodes: dict[str, object] = {}  # provider id -> NodeInfo
        self._lock = threading.Lock()
        self._counter = 0

    def create_node(self, node_type: str, resources: dict) -> str:
        from ray_tpu.core.gcs import NodeInfo
        from ray_tpu.core.resources import NodeResources, ResourceSet
        from ray_tpu.utils.ids import NodeID

        info = NodeInfo(NodeID.from_random(), NodeResources(ResourceSet(resources)))
        self._runtime.gcs.register_node(info)
        with self._lock:
            self._counter += 1
            pid = f"{node_type}-{self._counter}"
            self._nodes[pid] = info
        return pid

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            info = self._nodes.pop(node_id, None)
        if info is not None:
            self._runtime.gcs.remove_node(info.node_id)

    def non_terminated_nodes(self) -> list[str]:
        with self._lock:
            return list(self._nodes)

    def node_resources(self, node_id: str) -> dict:
        with self._lock:
            info = self._nodes.get(node_id)
        return dict(info.resources.total) if info else {}

    def is_idle(self, node_id: str) -> bool:
        with self._lock:
            info = self._nodes.get(node_id)
        if info is None:
            return False
        return dict(info.resources._available) == dict(info.resources.total)


def __getattr__(name):
    # TPUPodProvider moved to its own module once it became a real
    # component (queued-resources state machine behind an injectable
    # transport, tpu_provider.py); keep the historical import path
    if name == "TPUPodProvider":
        from ray_tpu.autoscaler.tpu_provider import TPUPodProvider

        return TPUPodProvider
    raise AttributeError(name)
