"""Autoscaler: demand-driven node reconciliation.

Reference analog: autoscaler v2 (python/ray/autoscaler/v2/autoscaler.py
+ scheduler.py — reconcile desired instances from resource demand) with
v1's bin-packing demand scheduler (resource_demand_scheduler.py).
Demand sources: queued tasks whose requests fit no node, and
PENDING/INFEASIBLE placement groups. Scale-down: idle nodes past the
timeout, respecting min_workers.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu.autoscale.demand import fits as _shared_fits, plan_launches
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.autoscaler")


@dataclass
class NodeTypeConfig:
    resources: dict
    min_workers: int = 0
    max_workers: int = 10


@dataclass
class AutoscalerConfig:
    node_types: dict  # name -> NodeTypeConfig
    idle_timeout_s: float = 60.0
    interval_s: float = 1.0


# bin-pack core lives in ray_tpu.autoscale.demand (r20: one brain);
# re-exported under the historical name for existing importers
_fits = _shared_fits


class StandardAutoscaler:
    def __init__(self, config: AutoscalerConfig, provider: NodeProvider):
        from ray_tpu.core import runtime as rt

        self.config = config
        self.provider = provider
        self._runtime = rt.get_runtime()
        self._idle_since: dict[str, float] = {}
        self._node_type: dict[str, str] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # satisfy min_workers up front (reference: initial nodes)
        for tname, tcfg in config.node_types.items():
            for _ in range(tcfg.min_workers):
                self._launch(tname, tcfg)

    # -- lifecycle ------------------------------------------------------------

    def start(self) -> None:
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, name="ray_tpu-autoscaler", daemon=True
            )
            self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.config.interval_s):
            try:
                self.reconcile()
            except Exception:
                logger.exception("autoscaler tick failed")

    # -- demand ---------------------------------------------------------------

    def pending_demand(self) -> list[dict]:
        """Resource requests with no node that can host them."""
        demand: list[dict] = []
        nodes = self._runtime.gcs.alive_nodes()
        caps = [dict(n.resources.total) for n in nodes]
        # queued tasks
        sched = self._runtime.scheduler
        with sched._cv:
            queued = [s.options.resource_set() for s in sched._queue]
        for req in queued:
            r = dict(req)
            if r and not any(_fits(r, c) for c in caps):
                demand.append(r)
        # pending / infeasible placement groups
        for pg in self._runtime.gcs.list_placement_groups():
            if getattr(pg, "_state", None) in ("PENDING", "INFEASIBLE"):
                demand.extend(dict(b.resources) for b in pg.bundles)
        return demand

    # -- reconcile -------------------------------------------------------------

    def reconcile(self) -> None:
        self._scale_up()
        self._retry_pending_pgs()
        self._scale_down()

    def _count(self, tname: str) -> int:
        return sum(1 for t in self._node_type.values() if t == tname)

    def _launch(self, tname: str, tcfg: NodeTypeConfig) -> Optional[str]:
        if self._count(tname) >= tcfg.max_workers:
            return None
        pid = self.provider.create_node(tname, dict(tcfg.resources))
        self._node_type[pid] = tname
        logger.info("scaled up: %s (%s)", pid, tcfg.resources)
        return pid

    def _scale_up(self) -> None:
        demand = self.pending_demand()
        if not demand:
            return
        planned_types, unplaced = plan_launches(
            demand, self.config.node_types, self._count
        )
        for req in unplaced:
            logger.warning("demand %s fits no configured node type", req)
        for tname in planned_types:
            self._launch(tname, self.config.node_types[tname])

    def _retry_pending_pgs(self) -> None:
        from ray_tpu.core.placement import retry_pending_placement_groups

        retry_pending_placement_groups(self._runtime)

    def _scale_down(self) -> None:
        now = time.time()
        for pid in list(self.provider.non_terminated_nodes()):
            tname = self._node_type.get(pid)
            if tname is None:
                continue
            tcfg = self.config.node_types[tname]
            if not self.provider.is_idle(pid):
                self._idle_since.pop(pid, None)
                continue
            first_idle = self._idle_since.setdefault(pid, now)
            if (
                now - first_idle >= self.config.idle_timeout_s
                and self._count(tname) > tcfg.min_workers
            ):
                self.provider.terminate_node(pid)
                self._node_type.pop(pid, None)
                self._idle_since.pop(pid, None)
                logger.info("scaled down idle node %s", pid)

    def status(self) -> dict:
        return {
            "nodes": {
                pid: self._node_type.get(pid)
                for pid in self.provider.non_terminated_nodes()
            },
            "pending_demand": self.pending_demand(),
        }
