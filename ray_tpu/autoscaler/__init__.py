"""ray_tpu.autoscaler: demand-driven cluster scaling.

Reference analog: python/ray/autoscaler/ (v1 StandardAutoscaler +
NodeProvider plugins; v2 reconciler). See autoscaler.py/node_provider.py.
"""

from ray_tpu.autoscaler.autoscaler import (
    AutoscalerConfig,
    NodeTypeConfig,
    StandardAutoscaler,
)
from ray_tpu.autoscaler.cluster_autoscaler import (
    ClusterAutoscaler,
    LocalClusterNodeProvider,
)
from ray_tpu.autoscaler.node_provider import (
    FakeNodeProvider,
    NodeProvider,
    TPUPodProvider,
)

__all__ = [
    "AutoscalerConfig",
    "ClusterAutoscaler",
    "FakeNodeProvider",
    "LocalClusterNodeProvider",
    "NodeProvider",
    "NodeTypeConfig",
    "StandardAutoscaler",
    "TPUPodProvider",
]
