"""Multi-agent RL: env protocol, policy mapping, and a multi-agent env
runner producing per-policy batches.

Reference analog: rllib/env/multi_agent_env.py (dict-keyed spaces) +
MultiAgentEnvRunner (env/multi_agent_env_runner.py:65) + the
policy_mapping_fn contract. Redesigned lean: agents appear/disappear per
step via dict keys; each policy is a functional RLModule whose params
the caller passes per sample() (so independent learners — one per
policy — plug straight into the existing single-agent algorithms).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import numpy as np

from ray_tpu.rl.module import RLModuleSpec
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.rl.multi_agent")


class MultiAgentEnv:
    """Protocol: dict-keyed multi-agent episodes.

    reset() -> ({agent_id: obs}, info)
    step({agent_id: action}) -> (obs_d, rew_d, term_d, trunc_d, info);
    term_d/trunc_d may carry "__all__" to end the episode for everyone.
    `agents` lists possible agent ids; `observation_space(agent)` /
    `action_space(agent)` give per-agent gym spaces.
    """

    agents: list = []

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: dict):
        raise NotImplementedError

    def observation_space(self, agent_id):
        raise NotImplementedError

    def action_space(self, agent_id):
        raise NotImplementedError


def spec_for_agent(env: MultiAgentEnv, agent_id) -> RLModuleSpec:
    obs_space = env.observation_space(agent_id)
    act_space = env.action_space(agent_id)
    obs_dim = int(np.prod(obs_space.shape))
    if hasattr(act_space, "n"):
        return RLModuleSpec(obs_dim=obs_dim, action_dim=int(act_space.n))
    return RLModuleSpec(
        obs_dim=obs_dim,
        action_dim=int(np.prod(act_space.shape)),
        continuous=True,
        action_high=float(np.max(np.abs(act_space.high))),
    )


class MultiAgentEnvRunner:
    """Steps ONE multi-agent env, routing each agent through its policy.

    policies: {policy_id: RLModuleSpec} — built once here.
    policy_mapping_fn(agent_id) -> policy_id.
    sample(params_by_policy, num_steps) -> {policy_id: batch} where batch
    has flat columns obs/actions/logp/rewards/terminateds/next_obs —
    ready for the single-agent learners (independent learning)."""

    def __init__(
        self,
        env_factory: Callable[[], MultiAgentEnv],
        policies: dict[str, RLModuleSpec],
        policy_mapping_fn: Callable[[Any], str],
        seed: int = 0,
    ):
        self.env = env_factory()
        self.policy_mapping_fn = policy_mapping_fn
        self.modules = {pid: spec.build() for pid, spec in policies.items()}
        self._explore = {
            pid: jax.jit(m.explore) for pid, m in self.modules.items()
        }
        self.key = jax.random.key(seed)
        self._obs, _ = self.env.reset(seed=seed)
        self._ep_ret: dict = {}
        self._done_returns: list[float] = []
        self._episodes = 0

    def sample(self, params_by_policy: dict, num_steps: int) -> dict:
        """Collect num_steps env steps; returns per-POLICY transition
        batches (concatenated over the agents mapped to that policy)."""
        rows: dict[str, list] = {pid: [] for pid in self.modules}
        pending: dict = {}  # agent_id -> (policy_id, obs, act, logp)
        for _ in range(num_steps):
            actions: dict = {}
            for aid, obs in self._obs.items():
                pid = self.policy_mapping_fn(aid)
                self.key, k = jax.random.split(self.key)
                act, logp, _ = self._explore[pid](
                    params_by_policy[pid], np.asarray(obs, np.float32)[None], k
                )
                act = np.asarray(act)[0]
                actions[aid] = (
                    int(act) if not self.modules[pid].spec.continuous else act
                )
                pending[aid] = (pid, np.asarray(obs, np.float32),
                                actions[aid], float(np.asarray(logp)[0]))
            obs_d, rew_d, term_d, trunc_d, _ = self.env.step(actions)
            all_done = bool(term_d.get("__all__", False) or
                            trunc_d.get("__all__", False))
            for aid, (pid, obs, act, logp) in pending.items():
                done = bool(term_d.get(aid, False) or all_done)
                nxt = obs_d.get(aid, obs)
                rows[pid].append({
                    "obs": obs,
                    "actions": act,
                    "logp": logp,
                    "rewards": float(rew_d.get(aid, 0.0)),
                    "terminateds": float(done),
                    "next_obs": np.asarray(nxt, np.float32),
                })
                self._ep_ret[aid] = self._ep_ret.get(aid, 0.0) + rew_d.get(aid, 0.0)
            pending.clear()
            if all_done or not obs_d:
                self._done_returns.append(sum(self._ep_ret.values()))
                self._episodes += 1
                self._ep_ret.clear()
                obs_d, _ = self.env.reset()
            self._obs = obs_d
        out = {}
        for pid, rs in rows.items():
            if not rs:
                continue
            out[pid] = {
                k: np.stack([np.asarray(r[k]) for r in rs]) for k in rs[0]
            }
        return out

    def metrics(self) -> dict:
        recent = self._done_returns[-20:]
        return {
            "episodes": self._episodes,
            "episode_return_mean": float(np.mean(recent)) if recent else float("nan"),
        }
