"""ConnectorV2-style data pipelines between env, module, and learner.

Reference analog: rllib/connectors/ (env-to-module, module-to-env,
learner pipelines of ConnectorV2 pieces). Same composition idea, but a
connector here is a plain callable `batch -> batch` over numpy/jax
pytrees, and anything numeric enough to matter runs *inside* the
learner's jitted update instead (e.g. GAE lives in algorithms/, not in
a Python pipeline) — Python-side connectors only do what must stay
dynamic: casting, flattening, normalization bookkeeping.
"""

from __future__ import annotations

from typing import Callable, Iterable, List

import numpy as np


class Connector:
    """One pipeline piece. Override __call__; state (if any) is instance attrs."""

    def __call__(self, batch: dict) -> dict:
        raise NotImplementedError

    def state(self) -> dict:
        return {}

    def set_state(self, state: dict) -> None:
        pass


class ConnectorPipeline(Connector):
    def __init__(self, pieces: Iterable[Connector] = ()):
        self.pieces: List[Connector] = list(pieces)

    def __call__(self, batch: dict) -> dict:
        for p in self.pieces:
            batch = p(batch)
        return batch

    def append(self, piece: Connector) -> "ConnectorPipeline":
        self.pieces.append(piece)
        return self

    def state(self) -> dict:
        return {i: p.state() for i, p in enumerate(self.pieces)}

    def set_state(self, state: dict) -> None:
        for i, p in enumerate(self.pieces):
            if i in state:
                p.set_state(state[i])


class FlattenObs(Connector):
    """Flatten [..., *obs_shape] observations to [..., obs_dim] float32."""

    def __call__(self, batch: dict) -> dict:
        obs = np.asarray(batch["obs"], np.float32)
        batch["obs"] = obs.reshape(*obs.shape[:1], -1) if obs.ndim > 2 else obs
        return batch


class NormalizeObs(Connector):
    """Running mean/std observation filter (reference: MeanStdFilter
    connector, rllib/connectors/env_to_module/mean_std_filter.py)."""

    def __init__(self, eps: float = 1e-8, clip: float = 10.0):
        self.count = eps
        self.mean = 0.0
        self.m2 = 0.0
        self.eps = eps
        self.clip = clip

    def __call__(self, batch: dict) -> dict:
        obs = np.asarray(batch["obs"], np.float32)
        flat = obs.reshape(-1, obs.shape[-1])
        # Chan et al. parallel update of running moments.
        n, mean = flat.shape[0], flat.mean(0)
        delta = mean - self.mean
        tot = self.count + n
        self.m2 = self.m2 + flat.var(0) * n + delta**2 * self.count * n / tot
        self.mean = self.mean + delta * n / tot
        self.count = tot
        std = np.sqrt(self.m2 / self.count) + self.eps
        batch["obs"] = np.clip((obs - self.mean) / std, -self.clip, self.clip)
        return batch

    def state(self) -> dict:
        return {"count": self.count, "mean": self.mean, "m2": self.m2}

    def set_state(self, state: dict) -> None:
        self.count, self.mean, self.m2 = state["count"], state["mean"], state["m2"]


def default_env_to_module() -> ConnectorPipeline:
    return ConnectorPipeline([FlattenObs()])
