"""Action distributions as pure-JAX functions.

Reference analog: rllib/models/distributions.py + torch distribution
wrappers (rllib/models/torch/torch_distributions.py). Here every
distribution is a stateless namespace of jittable functions over the
module's raw outputs (logits / mean+logstd) so the whole sample/logp/
entropy path stays inside one XLA program on TPU — no framework
objects cross the jit boundary.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


class Categorical:
    """Distribution over discrete actions, parameterized by logits [..., A]."""

    @staticmethod
    def sample(key: jax.Array, logits: jax.Array) -> jax.Array:
        return jax.random.categorical(key, logits, axis=-1)

    @staticmethod
    def mode(logits: jax.Array) -> jax.Array:
        return jnp.argmax(logits, axis=-1)

    @staticmethod
    def logp(logits: jax.Array, actions: jax.Array) -> jax.Array:
        logp_all = jax.nn.log_softmax(logits, axis=-1)
        return jnp.take_along_axis(logp_all, actions[..., None], axis=-1)[..., 0]

    @staticmethod
    def entropy(logits: jax.Array) -> jax.Array:
        logp = jax.nn.log_softmax(logits, axis=-1)
        return -jnp.sum(jnp.exp(logp) * logp, axis=-1)

    @staticmethod
    def kl(logits_p: jax.Array, logits_q: jax.Array) -> jax.Array:
        logp = jax.nn.log_softmax(logits_p, axis=-1)
        logq = jax.nn.log_softmax(logits_q, axis=-1)
        return jnp.sum(jnp.exp(logp) * (logp - logq), axis=-1)


class DiagGaussian:
    """Factored normal over continuous actions; params [..., 2*D] = mean|logstd."""

    @staticmethod
    def _split(params: jax.Array):
        mean, log_std = jnp.split(params, 2, axis=-1)
        return mean, jnp.clip(log_std, -20.0, 2.0)

    @staticmethod
    def sample(key: jax.Array, params: jax.Array) -> jax.Array:
        mean, log_std = DiagGaussian._split(params)
        return mean + jnp.exp(log_std) * jax.random.normal(key, mean.shape)

    @staticmethod
    def mode(params: jax.Array) -> jax.Array:
        return DiagGaussian._split(params)[0]

    @staticmethod
    def logp(params: jax.Array, actions: jax.Array) -> jax.Array:
        mean, log_std = DiagGaussian._split(params)
        var = jnp.exp(2 * log_std)
        ll = -0.5 * ((actions - mean) ** 2 / var + 2 * log_std + jnp.log(2 * jnp.pi))
        return jnp.sum(ll, axis=-1)

    @staticmethod
    def entropy(params: jax.Array) -> jax.Array:
        _, log_std = DiagGaussian._split(params)
        return jnp.sum(log_std + 0.5 * jnp.log(2 * jnp.pi * jnp.e), axis=-1)

    @staticmethod
    def kl(params_p: jax.Array, params_q: jax.Array) -> jax.Array:
        mp, lp = DiagGaussian._split(params_p)
        mq, lq = DiagGaussian._split(params_q)
        vp, vq = jnp.exp(2 * lp), jnp.exp(2 * lq)
        return jnp.sum(lq - lp + (vp + (mp - mq) ** 2) / (2 * vq) - 0.5, axis=-1)


def get_distribution(name: str):
    return {"categorical": Categorical, "diag_gaussian": DiagGaussian}[name]
