"""The trajectory plane: rollout -> learner, bounded and staleness-stamped.

Every trajectory carries the **weight version** and **sampler key** that
generated it — the learner's staleness filter and any replay/debugging
of a rollout both need to know exactly which policy and which PRNG
stream produced a continuation.

``TrajectoryQueue`` rides the ``rl/replay.py`` ring-buffer discipline
(drop-oldest, never grow) extended to variable-length entries: it is
bounded by **entries AND bytes**, and overflow evicts the oldest
trajectory with a counted ``ray_tpu_rl_post_trajectories_dropped_total``
instead of growing host memory without bound under a stalled learner.
A dropped rollout is cheap (the actor regenerates at the current
version); an OOM'd learner is not.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional

from ray_tpu.rl.post_train import metrics as _metrics


@dataclasses.dataclass
class Trajectory:
    """One scored continuation. ``weight_version`` is the subscriber
    version the generating engine served; ``sampler_key`` is the
    ``(sampling_seed, request_id)`` pair the engine folds into its PRNG
    key — together they name the exact (policy, randomness) that
    produced ``output_token_ids``."""

    request_id: str
    prompt_token_ids: list
    output_token_ids: list
    reward: float
    weight_version: int
    sampler_key: tuple
    actor_id: str = ""
    created_at: float = dataclasses.field(default_factory=time.time)
    # stamped by the feeder at consume time (reward - batch baseline,
    # staleness down-weighting applied); never crosses the queue
    advantage: float = 0.0

    @property
    def nbytes(self) -> int:
        """Approximate host bytes this entry pins (token ids dominate;
        8 bytes per int plus a flat per-entry overhead for the strings
        and dataclass itself — the bound needs honesty, not precision)."""
        return 8 * (len(self.prompt_token_ids) + len(self.output_token_ids)) + 200


class TrajectoryQueue:
    """Bounded FIFO between the tiers. ``put`` never blocks (drop-oldest
    on either bound); ``take`` parks bounded and drains up to a batch.

    Thread-safe: rollout actors push from their own threads while the
    learner's feeder drains from gang ranks.
    """

    def __init__(self, max_entries: int = 4096, max_bytes: int = 64 << 20,
                 model_tag: str = "rl-post"):
        if max_entries < 1 or max_bytes < 1:
            raise ValueError("max_entries/max_bytes must be >= 1")
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self.model_tag = model_tag
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._items: list[Trajectory] = []
        self._bytes = 0
        self.num_put = 0
        self.num_taken = 0
        self.num_dropped = 0
        # gauge-publication ordering: snapshots are stamped with _seq
        # inside the queue's critical section; _pub_lock/_pub_seq let
        # _update_gauges reject an older snapshot that lost the race to
        # the metric store without holding the queue lock across the set
        self._seq = 0
        self._pub_lock = threading.Lock()
        self._pub_seq = 0

    def put(self, traj: Trajectory) -> None:
        """Append; evict oldest-first while either bound is exceeded.
        A single trajectory larger than ``max_bytes`` is itself dropped
        (counted) WITHOUT being admitted — running the eviction loop on
        it would flush every good trajectory first and still end up
        dropping it."""
        dropped = 0
        with self._cond:
            self.num_put += 1
            if traj.nbytes > self.max_bytes:
                self.num_dropped += 1
                dropped = 1
            else:
                self._items.append(traj)
                self._bytes += traj.nbytes
                while self._items and (
                    len(self._items) > self.max_entries
                    or self._bytes > self.max_bytes
                ):
                    old = self._items.pop(0)
                    self._bytes -= old.nbytes
                    self.num_dropped += 1
                    dropped += 1
            self._seq += 1
            seq, depth, nbytes = self._seq, len(self._items), self._bytes
            self._cond.notify_all()
        if dropped:
            try:
                _metrics.trajectories_dropped_counter().inc(
                    float(dropped), tags={"model": self.model_tag})
            except Exception:  # noqa: BLE001 — observability never blocks the plane
                pass
        self._update_gauges(seq, depth, nbytes)

    def take(self, max_n: int, timeout_s: float = 0.1) -> list[Trajectory]:
        """Drain up to ``max_n`` oldest trajectories; parks at most
        ``timeout_s`` for the first one (bounded — the learner's feeder
        loops in slices so a starved queue can never hang a gang rank
        past its own starvation bound)."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        with self._cond:
            while not self._items:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return []
                self._cond.wait(timeout=remaining)
            n = min(int(max_n), len(self._items))
            out = self._items[:n]
            del self._items[:n]
            self._bytes -= sum(t.nbytes for t in out)
            self.num_taken += len(out)
            self._seq += 1
            seq, depth, nbytes = self._seq, len(self._items), self._bytes
        self._update_gauges(seq, depth, nbytes)
        return out

    def depth(self) -> int:
        with self._lock:
            return len(self._items)

    def total_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": len(self._items),
                "bytes": self._bytes,
                "max_entries": self.max_entries,
                "max_bytes": self.max_bytes,
                "num_put": self.num_put,
                "num_taken": self.num_taken,
                "num_dropped": self.num_dropped,
            }

    def _update_gauges(self, seq: int, depth: int, nbytes: int) -> None:
        """Callers pass the (seq, depth, bytes) they observed INSIDE
        their own critical section; a snapshot that lost the race here
        to a newer one is discarded — two threads leaving put/take out
        of order can never park an older depth over the current one.
        The metric set itself stays off the queue lock (put/take must
        never contend on the metric store)."""
        with self._pub_lock:
            if seq <= self._pub_seq:
                return  # a newer snapshot already published
            self._pub_seq = seq
            try:
                tags = {"model": self.model_tag}
                _metrics.queue_depth_gauge().set(float(depth), tags=tags)
                _metrics.queue_bytes_gauge().set(float(nbytes), tags=tags)
            except Exception:  # noqa: BLE001 — observability never blocks the plane
                pass
