"""The post-training loop: two tiers, two one-way planes, one driver.

``PostTrainLoop`` assembles the sebulba shape:

 * N rollout actors (``LLMEngine``-backed, rollout.py) generate on a
   background thread, paced only by queue backpressure — never by the
   learner's step clock;
 * the r12 ``TrainerSupervisor`` gang trains on the feeder's cached
   batches (feeder.py) on the calling thread — ``KILL_RANK`` /
   partition / stall recoveries are ITS problem and invisible to the
   rollout tier;
 * publishes ride a background ``_PublishWorker`` that coalesces to the
   newest snapshot (a learner that outruns the fabric ships the latest
   version, not a backlog of dead ones) — wired into the supervisor via
   the ``on_round`` hook, the exact missing link ROADMAP item 5 named.

Fault isolation contract (chaos-gated):

 * learner gang recovery: rollout actors keep serving the last good
   version (a publish torn by the dying gang is dropped by the
   subscriber's verify/version gates, never half-applied), and resumed
   training is bitwise loss-identical at the same world size;
 * rollout preemption: the queue starves, the feeder reuses/waits
   bounded, the gang does not fault; the recovered engine resubscribes
   at the next round boundary and catches up to the newest version.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Any, Callable, Optional

import numpy as np

from ray_tpu.llm.engine import LLMEngine
from ray_tpu.rl.post_train import metrics as _metrics
from ray_tpu.rl.post_train.config import PostTrainConfig, PostTrainError
from ray_tpu.rl.post_train.feeder import TrajectoryFeeder
from ray_tpu.rl.post_train.learner import make_batch_fn, make_pg_fns
from ray_tpu.rl.post_train.rollout import RolloutActor
from ray_tpu.rl.post_train.trajectory import TrajectoryQueue
from ray_tpu.train.elastic import ElasticConfig, TrainerSupervisor
from ray_tpu.train.weight_sync import WeightPublisher, WeightSubscriber
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.rl.post_train.loop")


# base type lives in config.py (FeederError subclasses it there without
# a loop->feeder->loop import cycle); re-exported here for callers
__all__ = ["PostTrainError", "PostTrainLoop", "PostTrainResult"]


class _PublishWorker:
    """Async, coalescing weight publisher: the learner thread hands off
    ``(version, state)`` and keeps training — the fabric send happens
    here, hidden behind the next round's device work (the Podracer
    recovery-cost bar). Superseded snapshots are dropped (counted): the
    rollout tier wants the NEWEST version, not a faithful replay of
    every intermediate one. Failures are counted, never raised into the
    training loop — the next publish supersedes."""

    def __init__(self, publisher: WeightPublisher, targets: list,
                 timeout_s: float = 30.0, model_tag: str = "rl-post",
                 on_published: Optional[Callable[[int], None]] = None):
        self._publisher = publisher
        self._targets = list(targets)
        self._timeout_s = float(timeout_s)
        self.model_tag = model_tag
        # success hook: the loop advances its staleness clock HERE, not
        # at submit — a down fabric must not let the feeder judge fresh
        # trajectories against a version no rollout engine ever received
        self._on_published = on_published
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._pending: Optional[tuple[int, Any]] = None
        self._stop = False
        self._inflight = False
        self.num_published = 0
        self.num_coalesced = 0
        self.num_failures = 0
        self.last_published_version = 0
        self._thread = threading.Thread(
            target=self._run, name="rl-post-publish", daemon=True
        )
        self._thread.start()

    def submit(self, version: int, state: Any) -> None:
        with self._cond:
            if self._pending is not None:
                self.num_coalesced += 1
            self._pending = (int(version), state)
            self._cond.notify_all()

    def _run(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait(timeout=0.2)
                if self._pending is None and self._stop:
                    return
                version, state = self._pending
                self._pending = None
                self._inflight = True
            try:
                self._publisher.publish(
                    state, self._targets, version=version,
                    timeout_s=self._timeout_s,
                )
                self.num_published += 1
                self.last_published_version = max(
                    self.last_published_version, version
                )
                if self._on_published is not None:
                    try:
                        self._on_published(version)
                    except Exception:  # noqa: BLE001
                        pass
                try:
                    _metrics.publishes_counter().inc(
                        tags={"model": self.model_tag})
                except Exception:  # noqa: BLE001
                    pass
            except Exception as e:  # noqa: BLE001 — publish faults never fault training
                self.num_failures += 1
                logger.warning("weight publish v%d failed: %r", version, e)
            finally:
                with self._cond:
                    self._inflight = False
                    self._cond.notify_all()

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Park (bounded) until nothing is pending or in flight."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while self._pending is not None or self._inflight:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(timeout=min(0.2, remaining))
        return True

    def close(self, timeout_s: float = 10.0) -> None:
        self.drain(timeout_s=timeout_s)
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout_s)

    def stats(self) -> dict:
        return {
            "published": self.num_published,
            "coalesced": self.num_coalesced,
            "failures": self.num_failures,
            "last_version": self.last_published_version,
        }


@dataclasses.dataclass
class PostTrainResult:
    completed: bool
    losses: list                 # per-learner-step mean loss
    rounds: list                 # rollout round records (the reward curve)
    recoveries: list             # learner-tier Recovery records (r12)
    blackouts: list
    rollout_preemptions: int
    publishes: int
    publish_failures: int
    queue_dropped: int
    stale_dropped: int
    reused_rounds: int
    max_trained_staleness: int
    final_version: int
    final_state: Any
    actor_stats: list
    error: Optional[BaseException] = None

    @property
    def reward_curve(self) -> list:
        return [r["mean_reward"] for r in self.rounds]


class PostTrainLoop:
    """Build both tiers from one config, run them decoupled, return the
    audit trail. ``engine_config`` is the rollout engines' EngineConfig
    (model must equal ``cfg.model``); ``prompts`` are the shared prompt
    token lists every round samples continuations of."""

    def __init__(
        self,
        cfg: PostTrainConfig,
        *,
        engine_config,
        prompts: list,
        reward_fn: Optional[Callable[[list, list], float]] = None,
        checkpoint_root: str,
        params: Any = None,
    ):
        import jax

        self.cfg = cfg
        self.prompts = [list(map(int, p)) for p in prompts]
        reward_fn = reward_fn or cfg.reward_fn
        if reward_fn is None:
            raise ValueError("a reward_fn is required (cfg.reward_fn or arg)")
        self.reward_fn = reward_fn
        if not self.prompts:
            raise ValueError("at least one rollout prompt is required")

        # learner state 0 == rollout params 0: both tiers start at the
        # SAME weights under version 0, so staleness accounting is exact
        # from the first trajectory on
        pad_len = max(
            len(p) for p in self.prompts
        ) + cfg.max_new_tokens
        self._init_fn, self._grad_fn, self._apply_fn = make_pg_fns(
            cfg.model,
            learning_rate=cfg.learning_rate,
            pad_rows=cfg.batch_size,
            pad_len=pad_len,
        )
        init_state = (
            self._init_fn(cfg.seed) if params is None
            else jax.tree_util.tree_map(np.asarray, params)
        )
        self._init_state = init_state

        self.queue = TrajectoryQueue(
            max_entries=cfg.queue_max_entries,
            max_bytes=cfg.queue_max_bytes,
            model_tag=cfg.model_tag,
        )
        self._published_version = 0
        self.feeder = TrajectoryFeeder(
            self.queue,
            batch_size=cfg.batch_size,
            max_staleness=cfg.max_staleness,
            version_fn=lambda: self._published_version,
            staleness_mode=cfg.staleness_mode,
            staleness_decay=cfg.staleness_decay,
            starvation_timeout_s=cfg.starvation_timeout_s,
            first_batch_timeout_s=cfg.first_batch_timeout_s,
            model_tag=cfg.model_tag,
        )

        # -- rollout tier: engines + subscribers over one fabric plane --------
        if cfg.spec is not None:
            # drafted rollouts: the spec knob rides into every rollout
            # engine (the acceptance rule is distribution-preserving,
            # so drafted trajectories sample the same policy)
            engine_config = dataclasses.replace(engine_config, spec=cfg.spec)
        self.publisher = WeightPublisher(namespace=cfg.namespace)
        self.actors: list[RolloutActor] = []
        self._targets: list = []
        for i in range(cfg.num_rollout):
            engine = LLMEngine(engine_config, params=init_state, seed=cfg.seed)
            engine.model_tag = cfg.model_tag
            endpoint = f"{cfg.model_tag}-rollout{i}"
            target = self.publisher.register_rollout(
                endpoint, device=engine.kv_cache_device()
            )
            self._targets.append(target)
            sub = WeightSubscriber(self.publisher.transport, endpoint)
            self.actors.append(RolloutActor(
                f"a{i}", engine, sub, self.queue, self.reward_fn,
                samples_per_prompt=cfg.samples_per_prompt,
                max_new_tokens=cfg.max_new_tokens,
                temperature=cfg.temperature,
                sampling_seed=cfg.sampling_seed,
                model_tag=cfg.model_tag,
            ))
        self._pub_worker: Optional[_PublishWorker] = None

        # -- learner tier: the r12 supervisor gang ----------------------------
        self.supervisor = TrainerSupervisor(
            init_fn=lambda seed: self._init_state,
            grad_fn=self._grad_fn,
            apply_fn=self._apply_fn,
            batch_fn=make_batch_fn(self.feeder),
            total_steps=cfg.total_steps,
            checkpoint_root=checkpoint_root,
            config=ElasticConfig(
                world_size=cfg.world_size,
                group_name=f"{cfg.model_tag}-learner",
                backend=cfg.learner_backend,
                seed=cfg.seed,
                step_timeout_s=cfg.step_timeout_s,
                steps_per_round=cfg.steps_per_round,
                checkpoint_every=cfg.checkpoint_every,
                max_recoveries=cfg.max_recoveries,
                sharded_checkpoints=False,
            ),
            on_round=self._on_round,
        )

        self.rounds: list[dict] = []
        self._max_round_step = 0   # publish-cadence boundary tracker
        self._stop = threading.Event()
        self._rollout_error: Optional[BaseException] = None

    # -- resync plane ----------------------------------------------------------

    def _note_published(self, version: int) -> None:
        """Publish-success hook (the staleness clock): trajectories are
        judged against the newest version that actually REACHED the
        fabric — a failing publish plane must degrade to 'rollouts look
        fresh' (they are: nothing newer was delivered), never to 'every
        fresh rollout is dropped as stale against a phantom version'."""
        self._published_version = max(self._published_version, int(version))
        try:
            _metrics.weight_version_gauge().set(
                float(self._published_version),
                tags={"model": self.cfg.model_tag, "tier": "learner",
                      "actor": "learner"},
            )
        except Exception:  # noqa: BLE001
            pass

    def _on_round(self, step: int, state_fn: Callable[[], Any]) -> None:
        """The supervisor's post-round hook: prune the feeder's replay
        cache below the checkpoint horizon, and on the publish cadence
        hand the gang's post-step state to the async publisher (version
        == step: deterministic across recoveries, so a re-published
        step after a restore carries the same version — and bitwise the
        same weights — the subscriber already holds or dropped)."""
        cfg = self.cfg
        self.feeder.prune_below(
            (step // cfg.checkpoint_every) * cfg.checkpoint_every
        )
        # boundary-crossing cadence (the checkpoint rule's form): with
        # steps_per_round > 1 the round-end step need not land ON a
        # multiple of publish_every — crossing one must still publish
        prev = self._max_round_step
        self._max_round_step = max(prev, step)
        if (
            step // cfg.publish_every > prev // cfg.publish_every
            or step >= cfg.total_steps
        ):
            state = state_fn()
            if self._pub_worker is not None:
                self._pub_worker.submit(step, state)

    # -- rollout driver --------------------------------------------------------

    def _rollout_loop(self) -> None:
        cfg = self.cfg
        backlog = cfg.backpressure_batches * cfg.batch_size
        round_idx = 0
        try:
            while not self._stop.is_set():
                if self.queue.depth() >= backlog:
                    # backpressure: generating further ahead only
                    # manufactures staleness; wait for the learner
                    self._stop.wait(0.05)
                    continue
                for actor in self.actors:
                    if self._stop.is_set():
                        return
                    actor.sync_weights()
                    rec = actor.run_round(
                        self.prompts, round_idx, stop=self._stop
                    )
                    if rec is None:  # aborted mid-round by shutdown
                        return
                    self.rounds.append(rec)
                round_idx += 1
        except BaseException as e:  # noqa: BLE001 — surfaced in the result
            self._rollout_error = e
            logger.warning("rollout loop died: %r", e)

    # -- run -------------------------------------------------------------------

    def run(self) -> PostTrainResult:
        cfg = self.cfg
        self._pub_worker = _PublishWorker(
            self.publisher, self._targets,
            timeout_s=cfg.publish_timeout_s, model_tag=cfg.model_tag,
            on_published=self._note_published,
        )
        rollout_thread = threading.Thread(
            target=self._rollout_loop, name="rl-post-rollout", daemon=True
        )
        rollout_thread.start()
        try:
            result = self.supervisor.fit()
        finally:
            self._stop.set()
            rollout_thread.join(timeout=60.0)
        # final resync: make sure version == total_steps actually reached
        # the fabric (_on_round already submitted it, but a COALESCED or
        # FAILED tail publish must not leave the tiers askew at rest —
        # and a clean tail must not be re-shipped just to be dropped as
        # stale by every subscriber), then apply on every actor so the
        # run ends converged
        if result.completed:
            self._pub_worker.drain(timeout_s=cfg.publish_timeout_s)
            if self._pub_worker.last_published_version < cfg.total_steps:
                self._pub_worker.submit(cfg.total_steps, result.state)
        self._pub_worker.close(timeout_s=cfg.publish_timeout_s)
        if rollout_thread.is_alive():
            # the cooperative stop should have ended the round; if the
            # thread is somehow still inside engine.step(), touching its
            # engines here would race a live generation — skip the final
            # sync rather than tear the batch state
            logger.warning(
                "rollout thread still alive after stop; skipping final "
                "actor resync"
            )
        else:
            for actor in self.actors:
                actor.sync_weights(timeout_s=1.0)
        error = result.error
        if error is None and self._rollout_error is not None:
            error = self._rollout_error
        return PostTrainResult(
            completed=result.completed and self._rollout_error is None,
            losses=list(result.losses),
            rounds=list(self.rounds),
            recoveries=list(result.recoveries),
            blackouts=list(result.blackouts),
            rollout_preemptions=sum(a.num_preemptions for a in self.actors),
            publishes=self._pub_worker.num_published,
            publish_failures=self._pub_worker.num_failures,
            queue_dropped=self.queue.num_dropped,
            stale_dropped=self.feeder.num_stale_dropped,
            reused_rounds=self.feeder.num_reused_rounds,
            max_trained_staleness=self.feeder.max_trained_staleness,
            final_version=self._pub_worker.last_published_version,
            final_state=result.state,
            actor_stats=[a.stats() for a in self.actors],
            error=error,
        )

    def close(self) -> None:
        """Release the fabric endpoints (queued bundles pin device
        memory) — idempotent, safe after a failed run()."""
        self._stop.set()
        if self._pub_worker is not None:
            self._pub_worker.close(timeout_s=5.0)
        for actor in self.actors:
            try:
                actor.subscriber.close()
            except Exception:  # noqa: BLE001
                pass
        self.publisher.close()
