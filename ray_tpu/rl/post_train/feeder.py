"""The learner-side feed point: queue -> per-step batch cache.

The r12 deterministic-resume contract requires ``batch_fn(seed, step,
world, rank)`` to be pure in its arguments — but post-training batches
come from a live trajectory queue. ``TrajectoryFeeder`` squares that:
the FIRST rank to ask for step ``s`` drains/filters a batch from the
queue and caches it keyed by step; every other rank (and every REPLAY
of ``s`` after a gang recovery restores the checkpoint) reads the
cached batch. Filling happens once, deterministically thereafter — so a
same-world-size resume recomputes bitwise-identical losses even though
the data plane is a race between two live tiers.

Staleness is enforced HERE, at consume time, against the learner's
latest published version: a trajectory older than ``max_staleness``
versions is dropped (counted, ``staleness_mode="drop"``) or its
advantage is exponentially down-weighted (``"down_weight"``) — and the
worst staleness ever admitted is tracked so "zero trajectories trained
past max_staleness" is auditable, not asserted.

Starvation (a preempted rollout tier) must never fault the gang: the
fill parks in bounded slices up to ``starvation_timeout_s`` and then
REUSES the previous round's batch (counted) — the gang keeps stepping
on slightly-reheated data instead of tripping the collective timeout.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ray_tpu.rl.post_train.config import PostTrainError, STALENESS_DROP
from ray_tpu.rl.post_train import metrics as _metrics
from ray_tpu.rl.post_train.trajectory import Trajectory, TrajectoryQueue
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.rl.post_train.feeder")


class FeederError(PostTrainError):
    """The feeder could not produce a first batch within its bound (the
    rollout tier never delivered) or a filler died mid-fill."""


class TrajectoryFeeder:
    def __init__(
        self,
        queue: TrajectoryQueue,
        *,
        batch_size: int,
        max_staleness: int,
        version_fn: Callable[[], int],
        staleness_mode: str = STALENESS_DROP,
        staleness_decay: float = 0.5,
        starvation_timeout_s: float = 30.0,
        first_batch_timeout_s: float = 120.0,
        poll_slice_s: float = 0.05,
        model_tag: str = "rl-post",
    ):
        self._queue = queue
        self._batch_size = int(batch_size)
        self._max_staleness = int(max_staleness)
        self._version_fn = version_fn
        self._mode = staleness_mode
        self._decay = float(staleness_decay)
        self._starve_s = float(starvation_timeout_s)
        self._first_s = float(first_batch_timeout_s)
        self._slice_s = float(poll_slice_s)
        self.model_tag = model_tag
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._batches: dict[int, list[Trajectory]] = {}
        self._filling: set[int] = set()
        self._last_batch: Optional[list[Trajectory]] = None
        self.num_stale_dropped = 0
        self.num_down_weighted = 0
        self.num_trained = 0
        self.num_reused_rounds = 0
        self.max_trained_staleness = 0

    # -- the batch_fn surface --------------------------------------------------

    def batch_for_step(self, step: int) -> list[Trajectory]:
        """The round batch for learner step ``step`` — filled once from
        the queue, then served from cache (replays after a recovery and
        the other gang ranks all see the identical batch)."""
        step = int(step)
        # waiter bound: a filler parks at most first_batch + starvation;
        # anything past that means the filler died outside the collective
        # plane (where the gang's own detector would have seen it)
        deadline = time.monotonic() + self._first_s + self._starve_s + 10.0
        while True:
            with self._cond:
                got = self._batches.get(step)
                if got is not None:
                    return got
                if step not in self._filling:
                    self._filling.add(step)
                    break  # this caller fills
                self._cond.wait(timeout=0.2)
            if time.monotonic() > deadline:
                raise FeederError(
                    f"feeder wedged: step {step} batch never materialized"
                )
        batch: Optional[list[Trajectory]] = None
        try:
            batch = self._fill(step)
            return batch
        finally:
            with self._cond:
                if batch is not None:
                    self._batches[step] = batch
                    self._last_batch = batch
                self._filling.discard(step)
                self._cond.notify_all()

    def prune_below(self, step: int) -> None:
        """Drop cached batches no recovery can ever replay (steps below
        the latest checkpoint boundary) — the cache stays bounded by the
        checkpoint cadence, not the run length."""
        with self._cond:
            for s in [s for s in self._batches if s < step]:
                del self._batches[s]

    def cached_steps(self) -> list[int]:
        with self._lock:
            return sorted(self._batches)

    def stats(self) -> dict:
        with self._lock:
            cached = len(self._batches)
        return {
            "stale_dropped": self.num_stale_dropped,
            "down_weighted": self.num_down_weighted,
            "trained": self.num_trained,
            "reused_rounds": self.num_reused_rounds,
            "max_trained_staleness": self.max_trained_staleness,
            "cached_batches": cached,
        }

    # -- filling ---------------------------------------------------------------

    def _fill(self, step: int) -> list[Trajectory]:
        """Drain the queue (bounded) into one staleness-filtered batch;
        runs OUTSIDE the feeder lock — pulling blocks, publishing the
        result doesn't."""
        first = self._last_batch is None
        deadline = time.monotonic() + (self._first_s if first else self._starve_s)
        kept: list[Trajectory] = []
        stale = 0
        while len(kept) < self._batch_size:
            got = self._queue.take(
                self._batch_size - len(kept), timeout_s=self._slice_s
            )
            current = int(self._version_fn())
            for t in got:
                lag = max(0, current - int(t.weight_version))
                if lag > self._max_staleness and self._mode == STALENESS_DROP:
                    stale += 1
                    continue
                kept.append(t)
            if kept and time.monotonic() > deadline:
                # partial batch beats a starved gang — and a slow
                # TRICKLE must not keep the fill (hence the rank) parked
                # past its bound either: the supervisor's round deadline
                # would read that as a wedged rank and replace it
                break
            if not kept and time.monotonic() > deadline:
                # stale drops drained on the way HERE still happened —
                # starving because everything was stale must reconcile
                # (generated == trained + stale + dropped), not vanish
                self._account_stale(stale)
                if self._last_batch is not None:
                    # starved: reuse the previous round (counted) — the
                    # gang must not fault because the rollout tier is
                    # mid-preemption; its recovery refills the queue
                    self.num_reused_rounds += 1
                    logger.warning(
                        "trajectory queue starved at step %d: reusing "
                        "previous round batch", step,
                    )
                    return self._last_batch
                raise FeederError(
                    f"no trajectories arrived within {self._first_s}s "
                    "for the first learner batch — is the rollout tier up?"
                )
        self._account_stale(stale)
        # finalize against the LAST version the filter used: re-reading
        # the clock here would let an async publish landing mid-fill
        # reclassify an admitted (lag <= max_staleness) trajectory as
        # past the bound — down-weighting it in drop mode and tripping
        # the max_trained_staleness audit the bench gates on
        return self._finalize(kept, current)

    def _account_stale(self, stale: int) -> None:
        if not stale:
            return
        self.num_stale_dropped += stale
        try:
            _metrics.trajectories_stale_counter().inc(
                float(stale), tags={"model": self.model_tag})
        except Exception:  # noqa: BLE001
            pass

    def _finalize(self, batch: list[Trajectory],
                  current: int) -> list[Trajectory]:
        """Advantage stamping: reward minus the round baseline, with the
        down-weight staleness mode applied past ``max_staleness``. The
        worst admitted staleness is recorded for the audit gate.
        ``current`` is the version the fill's staleness filter judged
        against (one clock read per fill)."""
        baseline = sum(t.reward for t in batch) / max(1, len(batch))
        for t in batch:
            lag = max(0, current - int(t.weight_version))
            adv = float(t.reward) - baseline
            if lag > self._max_staleness:
                # only reachable in down_weight mode (drop filtered above)
                adv *= self._decay ** (lag - self._max_staleness)
                self.num_down_weighted += 1
            t.advantage = adv
            self.max_trained_staleness = max(self.max_trained_staleness, lag)
        self.num_trained += len(batch)
        try:
            tags = {"model": self.model_tag}
            _metrics.trajectories_trained_counter().inc(
                float(len(batch)), tags=tags)
            _metrics.max_trained_staleness_gauge().set(
                float(self.max_trained_staleness), tags=tags)
        except Exception:  # noqa: BLE001
            pass
        return batch
