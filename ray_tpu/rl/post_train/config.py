"""Knobs of the decoupled actor/learner post-training loop."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

STALENESS_DROP = "drop"
STALENESS_DOWN_WEIGHT = "down_weight"


class PostTrainError(Exception):
    """Base of post-training loop failures (the feeder's starvation
    bound, a wedged plane) — callers catch ONE type for the subsystem;
    terminal learner-tier faults surface in ``PostTrainResult.error``."""


@dataclasses.dataclass
class PostTrainConfig:
    """One config for both tiers and the two planes between them.

    The model config is shared: the learner trains the SAME architecture
    the rollout engines serve (the weight-sync plane ships leaves by
    pytree order, so both sides must agree — ``train.weight_sync``
    fails loudly on a leaf-count mismatch).
    """

    model: Any                     # models/llama.LlamaConfig

    # -- rollout tier (the serving stack) -------------------------------------
    num_rollout: int = 1           # rollout engines (each its own subscriber)
    samples_per_prompt: int = 4    # sampled continuations per shared prompt
    max_new_tokens: int = 8
    temperature: float = 1.0
    sampling_seed: int = 0         # SamplingParams.seed: rollouts are seeded
    spec: Optional[Any] = None     # llm.spec SpecConfig for drafted rollouts

    # -- learner tier (the r12 TrainerSupervisor gang) ------------------------
    world_size: int = 2
    total_steps: int = 24
    steps_per_round: int = 1
    checkpoint_every: int = 4
    step_timeout_s: float = 15.0
    max_recoveries: int = 8
    learning_rate: float = 1.0     # plain SGD on the PG loss
    seed: int = 0
    learner_backend: str = "host"  # thread gang (the r12 default)

    # -- trajectory plane (rollout -> learner) --------------------------------
    queue_max_entries: int = 4096
    queue_max_bytes: int = 64 << 20   # bytes bound, not just entries
    batch_size: int = 16              # trajectories per learner step
    max_staleness: int = 4            # versions; older is dropped/down-weighted
    staleness_mode: str = STALENESS_DROP
    staleness_decay: float = 0.5      # down_weight: advantage *= decay**excess
    starvation_timeout_s: float = 30.0  # park bound when the queue runs dry
    first_batch_timeout_s: float = 120.0
    # rollout backpressure: pause generation while the queue holds this
    # many undrained batches (bounds staleness AND wasted rollout compute
    # under a slow learner; the byte bound is the hard memory backstop)
    backpressure_batches: int = 4

    # -- resync plane (learner -> rollout, train.weight_sync) -----------------
    publish_every: int = 4         # learner steps between weight publishes
    publish_timeout_s: float = 30.0
    namespace: str = "rl-post"     # fabric transport namespace
    model_tag: str = "rl-post"

    # optional hook: trajectory -> scalar reward. The loop requires one
    # (passed explicitly); kept here so serialized configs can name it.
    reward_fn: Optional[Callable] = None

    def __post_init__(self):
        if self.staleness_mode not in (STALENESS_DROP, STALENESS_DOWN_WEIGHT):
            raise ValueError(
                f"staleness_mode must be {STALENESS_DROP!r} or "
                f"{STALENESS_DOWN_WEIGHT!r}, got {self.staleness_mode!r}"
            )
        if self.max_staleness < 0:
            raise ValueError("max_staleness must be >= 0")
        if self.num_rollout < 1 or self.samples_per_prompt < 1:
            raise ValueError("num_rollout/samples_per_prompt must be >= 1")
        if self.queue_max_entries < 1 or self.queue_max_bytes < 1:
            raise ValueError("queue bounds must be >= 1")
        if self.publish_every < 1:
            raise ValueError("publish_every must be >= 1")
        if self.batch_size < 1:
            raise ValueError("batch_size must be >= 1")
        if not (0.0 < self.staleness_decay <= 1.0):
            raise ValueError("staleness_decay must be in (0, 1]")
