"""The rollout tier: the serving engine as RL actor.

A ``RolloutActor`` wraps an ``LLMEngine`` — not a bespoke generation
loop — so rollouts get the serving stack for free: shared prompts ride
the prefix cache (``samples_per_prompt`` continuations of one prompt
re-prefill nothing after the first), speculative decoding drafts cheap
tokens when the engine carries a ``SpecConfig`` (the acceptance rule is
distribution-preserving, so drafted rollouts sample the SAME policy),
and preemption is survived by the exact ``recover()`` ladder serving
uses.

Weight resync is pull-based between rounds: ``sync_weights`` drains the
actor's ``WeightSubscriber`` endpoint and applies the newest verified
version (older/corrupt bundles drop — ``train.weight_sync``), which
also invalidates the prefix cache so post-swap rollouts never splice
pre-swap KV. Within a round the version is frozen: every trajectory of
round N is stamped with the version that was serving when the round
started, so the learner's staleness accounting sees the truth even if a
publish lands mid-round.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ray_tpu.chaos.harness import EnginePreempted
from ray_tpu.llm.sampling import SamplingParams
from ray_tpu.rl.post_train import metrics as _metrics
from ray_tpu.rl.post_train.trajectory import Trajectory, TrajectoryQueue
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.rl.post_train.rollout")


class RolloutActor:
    """One rollout engine + its weight subscriber + the queue it feeds."""

    def __init__(
        self,
        actor_id: str,
        engine,
        subscriber,
        queue: TrajectoryQueue,
        reward_fn: Callable[[list, list], float],
        *,
        samples_per_prompt: int = 4,
        max_new_tokens: int = 8,
        temperature: float = 1.0,
        sampling_seed: int = 0,
        model_tag: str = "rl-post",
    ):
        self.actor_id = actor_id
        self.engine = engine
        self.subscriber = subscriber
        self.queue = queue
        self.reward_fn = reward_fn
        self.samples_per_prompt = int(samples_per_prompt)
        self.max_new_tokens = int(max_new_tokens)
        self.temperature = float(temperature)
        self.sampling_seed = int(sampling_seed)
        self.model_tag = model_tag
        self.num_rounds = 0
        self.num_preemptions = 0
        self.num_syncs = 0
        self.num_trajectories = 0

    # -- resync (learner -> rollout) ------------------------------------------

    def sync_weights(self, timeout_s: float = 0.05) -> Optional[int]:
        """Drain the subscriber endpoint; apply the newest verified
        publish (catch-up semantics: intermediate versions are skipped,
        stale/corrupt bundles counted + dropped). Returns the applied
        version or None. Called between rounds and after a recovery —
        never mid-round."""
        applied = self.subscriber.apply_to_engine(
            self.engine, timeout_s=timeout_s
        )
        if applied is not None:
            self.num_syncs += 1
            try:
                _metrics.weight_version_gauge().set(
                    float(applied),
                    tags={"model": self.model_tag, "tier": "rollout",
                          "actor": self.actor_id},
                )
            except Exception:  # noqa: BLE001
                pass
        return applied

    # -- generation (the serving stack) ---------------------------------------

    def _sampling_params(self, greedy: bool = False) -> SamplingParams:
        return SamplingParams(
            max_tokens=self.max_new_tokens,
            temperature=0.0 if greedy else self.temperature,
            seed=self.sampling_seed,
            ignore_eos=True,
        )

    def run_round(self, prompts: list, round_idx: int,
                  greedy: bool = False,
                  stop: Optional[threading.Event] = None) -> Optional[dict]:
        """Generate ``samples_per_prompt`` continuations per shared
        prompt, score them, and push staleness-stamped trajectories.
        Rides out ``PREEMPT_ENGINE`` via the engine's own recovery
        ladder — a preempted round finishes (recomputed prefixes, no
        lost/dup tokens), it does not abort. A set ``stop`` event is the
        ONE exception: the driver is shutting down and will touch the
        engine next (final sync), so the round aborts its in-flight
        requests and returns None — nothing scored, nothing pushed, no
        partial round polluting the reward curve."""
        t0 = time.perf_counter()
        version = int(getattr(self.engine, "weight_version", 0))
        sp = self._sampling_params(greedy=greedy)
        rids: dict[str, list] = {}
        for i, prompt in enumerate(prompts):
            for j in range(self.samples_per_prompt):
                rid = f"{self.actor_id}-r{round_idx}-p{i}-s{j}"
                self.engine.add_request(list(prompt), sp, request_id=rid)
                rids[rid] = list(prompt)
        outputs: dict[str, list] = {}
        while self.engine.has_unfinished():
            if stop is not None and stop.is_set():
                for rid in rids:
                    try:
                        self.engine.abort_request(rid)
                    except Exception:  # noqa: BLE001 — shutdown best-effort
                        pass
                return None
            try:
                outs = self.engine.step()
            except EnginePreempted:
                self._recover()
                continue
            for o in outs:
                if o.finished and o.request_id in rids:
                    outputs[o.request_id] = list(o.output_token_ids)
        rewards = []
        n_tokens = 0
        for rid, prompt in rids.items():
            out = outputs.get(rid, [])
            reward = float(self.reward_fn(prompt, out))
            rewards.append(reward)
            n_tokens += len(out)
            self.queue.put(Trajectory(
                request_id=rid,
                prompt_token_ids=prompt,
                output_token_ids=out,
                reward=reward,
                weight_version=version,
                sampler_key=(self.sampling_seed, rid),
                actor_id=self.actor_id,
            ))
        self.num_rounds += 1
        self.num_trajectories += len(rewards)
        wall = time.perf_counter() - t0
        try:
            tags = {"model": self.model_tag}
            _metrics.trajectories_generated_counter().inc(
                float(len(rewards)), tags=tags)
            hist = _metrics.reward_histogram()
            for r in rewards:
                hist.observe(r, tags=tags)
        except Exception:  # noqa: BLE001
            pass
        cache = self.engine.stats().get("prefix_cache", {})
        return {
            "round": round_idx,
            "actor_id": self.actor_id,
            "version": version,
            "n": len(rewards),
            "mean_reward": (sum(rewards) / len(rewards)) if rewards else 0.0,
            "tokens": n_tokens,
            "wall_s": round(wall, 4),
            "tok_s": round(n_tokens / wall, 2) if wall > 0 else 0.0,
            "cached_token_ratio": cache.get("hit_rate", 0.0),
        }

    def _recover(self) -> None:
        """The serving recovery ladder, scoped to a rollout round:
        requeue in-flight requests (generated prefixes intact); if even
        that throws, rebuild the KV cache too. The learner tier never
        hears about any of this — mutual fault isolation is the design."""
        self.num_preemptions += 1
        try:
            _metrics.rollout_preemptions_counter().inc(
                tags={"model": self.model_tag})
        except Exception:  # noqa: BLE001
            pass
        try:
            self.engine.recover()
        except Exception:  # noqa: BLE001 — torn cache: rebuild rung
            logger.warning(
                "rollout %s: recover() failed, rebuilding KV cache",
                self.actor_id,
            )
            self.engine.recover(rebuild_kv=True)

    def stats(self) -> dict:
        return {
            "actor_id": self.actor_id,
            "weight_version": int(getattr(self.engine, "weight_version", 0)),
            "rounds": self.num_rounds,
            "trajectories": self.num_trajectories,
            "preemptions": self.num_preemptions,
            "syncs": self.num_syncs,
            "subscriber": self.subscriber.stats(),
        }
