"""ray_tpu.rl.post_train — sebulba-style decoupled actor/learner RL
post-training: the serving engine as rollout actor.

"Podracer architectures for scalable RL" (PAPERS.md) decouples the two
tiers of an RL loop across a TPU pod: **actors** generate trajectories,
**learners** consume them, and the only couplings are a one-way
trajectory stream (actor -> learner) and a one-way parameter stream
(learner -> actor). This package builds exactly that shape out of parts
the repo already hardened:

 * the **rollout tier is the serving stack**: each ``RolloutActor``
   wraps an ``LLMEngine`` (rollout.py), so shared-prompt rollouts ride
   the prefix cache, speculative decoding makes sampled continuations
   cheap, and seeded ``PREEMPT_ENGINE`` chaos is survived by the same
   ``recover()`` ladder serving uses;
 * the **learner tier is the r12 gang**: a ``TrainerSupervisor`` drives
   a policy-gradient-shaped update (learner.py) whose batches come from
   the trajectory plane via a per-step batch cache (feeder.py) — so a
   ``KILL_RANK`` recovery restores the checkpoint and replays the SAME
   cached batches, keeping the same-world-size resume bitwise
   loss-identical even though the data came from a live queue;
 * the **trajectory plane** is a bounded, staleness-stamped queue
   (trajectory.py): every trajectory carries the weight version and
   sampler key that generated it, overflow drops oldest with a counted
   metric, and the learner drops (or down-weights) anything older than
   ``max_staleness`` versions;
 * the **resync plane** is the r15 fabric weight publish
   (``train.weight_sync``): the supervisor's post-step state is wired
   into ``WeightPublisher.publish`` through the ``on_round`` hook, a
   background worker coalesces publishes so resyncs hide behind device
   work, and subscribers verify + version-gate every bundle — a torn or
   corrupt publish is dropped, never half-applied.

The tiers are mutually fault-isolated: rollout engines ride out a
learner gang recovery (they keep serving the last good version) and the
learner rides out rollout preemption (the queue starves, the gang does
not fault) — both under the seeded chaos harness, gated by
``benchmarks/rlhf_post_bench.py`` -> ``benchmarks/RLHF_post_r19.json``.
"""

from ray_tpu.rl.post_train.config import PostTrainConfig, PostTrainError
from ray_tpu.rl.post_train.feeder import FeederError, TrajectoryFeeder
from ray_tpu.rl.post_train.learner import make_pg_fns
from ray_tpu.rl.post_train.loop import PostTrainLoop, PostTrainResult
from ray_tpu.rl.post_train.rollout import RolloutActor
from ray_tpu.rl.post_train.trajectory import Trajectory, TrajectoryQueue

__all__ = [
    "FeederError",
    "PostTrainConfig",
    "PostTrainError",
    "PostTrainLoop",
    "PostTrainResult",
    "RolloutActor",
    "Trajectory",
    "TrajectoryQueue",
    "TrajectoryFeeder",
    "make_pg_fns",
]
