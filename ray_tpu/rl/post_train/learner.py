"""Policy-gradient-shaped update over scored trajectories.

``make_pg_fns`` builds the four callables the r12 ``TrainerSupervisor``
drives (``init_fn`` / ``grad_fn`` / ``apply_fn`` plus a feeder-backed
``batch_fn``), closed over a fixed padded shape so the jitted
forward/backward compiles exactly once:

 * the REINFORCE loss: ``-sum(advantage * log p(output token)) / n``
   over each trajectory's generated positions only (prompt positions
   carry zero weight — the policy is trained on what it *sampled*, not
   on the prompts it was given);
 * the advantage is stamped by the feeder (reward minus the round
   baseline, staleness down-weighting applied);
 * state lives as a **numpy** pytree and ``apply_fn`` is plain SGD in
   float32 numpy — together with the r12 gang's rank-ordered float64
   allreduce this keeps a same-world-size resume bitwise loss-identical
   (no device-resident optimizer state to drift across a restore).

The learner never touches the serving stack: its only outputs are the
state pytree (checkpointed by the supervisor) and the versioned weight
publishes the loop ships over ``train.weight_sync``.
"""

from __future__ import annotations

from typing import Callable

import numpy as np

from ray_tpu.rl.post_train.trajectory import Trajectory


def pack_pg_batch(batch: list, pad_rows: int, pad_len: int):
    """Trajectories -> fixed-shape numpy arrays (tokens, targets,
    weights). Row ``i`` holds ``prompt+output`` shifted for next-token
    prediction; ``weights`` carries the advantage on positions that
    PREDICT an output token and zero elsewhere (pad rows are all-zero,
    so padding changes nothing but the compile shape)."""
    tokens = np.zeros((pad_rows, pad_len), np.int32)
    targets = np.zeros((pad_rows, pad_len), np.int32)
    weights = np.zeros((pad_rows, pad_len), np.float32)
    n_out = 0
    for i, t in enumerate(batch[:pad_rows]):
        seq = list(t.prompt_token_ids) + list(t.output_token_ids)
        seq = seq[: pad_len + 1]
        m = len(t.prompt_token_ids)
        inp, tgt = seq[:-1], seq[1:]
        L = len(inp)
        tokens[i, :L] = inp
        targets[i, :L] = tgt
        # positions m-1 .. m-1+k-1 predict the k output tokens
        lo = max(0, m - 1)
        hi = min(L, m - 1 + len(t.output_token_ids))
        weights[i, lo:hi] = t.advantage
        n_out += max(0, hi - lo)
    return tokens, targets, weights, max(1, n_out)


def make_pg_fns(
    model_cfg,
    *,
    learning_rate: float,
    pad_rows: int,
    pad_len: int,
) -> tuple[Callable, Callable, Callable]:
    """(init_fn, grad_fn, apply_fn) for ``TrainerSupervisor``. The
    returned grad_fn expects the feeder's batch (a list of advantage-
    stamped ``Trajectory``); an empty shard yields zero loss and zero
    gradients (a rank whose slice of a small round is empty still joins
    the allreduce with a neutral contribution)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import llama

    if pad_len >= model_cfg.max_seq:
        raise ValueError(
            f"pad_len {pad_len} must stay under model max_seq "
            f"{model_cfg.max_seq}"
        )

    def _pg_loss(params, tokens, targets, weights, n_out):
        logits = llama.forward(params, tokens, model_cfg).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tok_logp = jnp.take_along_axis(
            logp, targets[..., None], axis=-1
        )[..., 0]
        return -jnp.sum(weights * tok_logp) / n_out

    pg_value_and_grad = jax.jit(jax.value_and_grad(_pg_loss))

    def init_fn(seed: int):
        params = llama.init_params(model_cfg, jax.random.key(int(seed)))
        return jax.tree_util.tree_map(np.asarray, params)

    def grad_fn(state, batch):
        trajs: list[Trajectory] = batch
        if not trajs:
            return 0.0, jax.tree_util.tree_map(np.zeros_like, state)
        tokens, targets, weights, n_out = pack_pg_batch(
            trajs, pad_rows, pad_len
        )
        loss, grads = pg_value_and_grad(
            state, tokens, targets, weights, float(n_out)
        )
        return float(loss), jax.tree_util.tree_map(np.asarray, grads)

    def apply_fn(state, grads):
        lr = np.float32(learning_rate)
        return jax.tree_util.tree_map(
            lambda p, g: np.asarray(
                p - lr * g.astype(p.dtype), dtype=p.dtype
            ),
            state, grads,
        )

    return init_fn, grad_fn, apply_fn


def make_batch_fn(feeder) -> Callable:
    """The supervisor-facing ``batch_fn(seed, step, world, rank)``: the
    feeder's cached round batch, rank-strided so each rank trains a
    disjoint shard. Pure in its arguments AFTER the first fill (the
    cache is the purity mechanism — see feeder.py)."""

    def batch_fn(seed, step, world, rank):
        batch = feeder.batch_for_step(int(step))
        return batch[int(rank)::max(1, int(world))]

    return batch_fn
