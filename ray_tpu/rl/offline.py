"""Offline RL: datasets of recorded transitions + algorithms that learn
from them without touching an environment.

Reference analog: rllib/offline/ — `OfflineData` (offline_data.py:23)
wraps Ray-Data-backed readers feeding `OfflinePreLearner` batches into
learners; BC (rllib/algorithms/bc) and CQL (rllib/algorithms/cql) train
from it. TPU-native redesign: the dataset is host numpy (or a
ray_tpu.data Dataset materialized to numpy); each algorithm's update
stays one jitted program fed minibatches.
"""

from __future__ import annotations

from typing import Iterable, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.module import RLModuleSpec
from ray_tpu.utils.logging import get_logger

logger = get_logger("ray_tpu.rl.offline")

REQUIRED_COLUMNS = ("obs", "actions")


class OfflineData:
    """A table of transitions: columns obs/actions[/rewards/next_obs/
    terminateds]. Buildable from dict-of-arrays, an .npz file, or a
    ray_tpu.data Dataset of row dicts."""

    def __init__(self, columns: dict, seed: int = 0):
        for c in REQUIRED_COLUMNS:
            if c not in columns:
                raise ValueError(f"offline dataset missing column {c!r}")
        n = len(columns["obs"])
        self.columns = {k: np.asarray(v) for k, v in columns.items()}
        for k, v in self.columns.items():
            if len(v) != n:
                raise ValueError(f"column {k!r} length {len(v)} != {n}")
        self.n = n
        self._rng = np.random.RandomState(seed)

    @classmethod
    def from_npz(cls, path: str, **kw) -> "OfflineData":
        data = np.load(path)
        return cls({k: data[k] for k in data.files}, **kw)

    @classmethod
    def from_dataset(cls, ds, **kw) -> "OfflineData":
        """Materialize a ray_tpu.data Dataset of row-dicts."""
        rows = list(ds.iter_rows()) if hasattr(ds, "iter_rows") else list(ds)
        cols = {k: np.stack([r[k] for r in rows]) for k in rows[0]}
        return cls(cols, **kw)

    def save_npz(self, path: str) -> None:
        np.savez(path, **self.columns)

    def sample(self, batch_size: int) -> dict:
        idx = self._rng.randint(0, self.n, size=batch_size)
        return {k: v[idx] for k, v in self.columns.items()}

    def __len__(self) -> int:
        return self.n


class BCConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__(algo_class=BC)
        self.lr = 1e-3
        self.train_batch_size = 256
        self.updates_per_iteration = 100

    def offline_data(self, dataset) -> "BCConfig":
        self.extra["dataset"] = dataset
        return self

    def training(self, **kwargs):
        for k in ("updates_per_iteration",):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        return super().training(**kwargs)


class BC:
    """Behavior cloning: maximize logp(dataset actions | obs).

    Reference analog: rllib/algorithms/bc (MARWIL with beta=0) reading
    OfflineData. Standalone (no env needed): pass `module_spec`, or an
    env in the config to derive one for later evaluation."""

    @classmethod
    def default_config(cls) -> BCConfig:
        return BCConfig()

    def __init__(self, config: Optional[BCConfig] = None,
                 module_spec: Optional[RLModuleSpec] = None):
        self.config = config or self.default_config()
        cfg = self.config
        dataset = cfg.extra.get("dataset")
        if dataset is None:
            raise ValueError("BCConfig.offline_data(dataset) is required")
        if not isinstance(dataset, OfflineData):
            dataset = OfflineData(dataset)
        self.dataset = dataset
        if module_spec is None:
            import dataclasses

            if cfg.env is None:
                raise ValueError("pass module_spec or config.environment(env=)")
            from ray_tpu.rl.env_runner import spec_from_env

            module_spec = dataclasses.replace(
                spec_from_env(cfg.env),
                hidden=tuple(cfg.model.get("hidden", (256, 256))),
            )
        self.module_spec = module_spec
        self.module = module_spec.build()
        self.params = self.module.init(jax.random.key(cfg.seed))
        self.optimizer = optax.adam(cfg.lr)
        self.opt_state = self.optimizer.init(self.params)
        self.iteration = 0
        self._infer = jax.jit(self.module.inference)
        self._build_update()

    def _build_update(self):
        module = self.module

        @jax.jit
        def update(params, opt_state, batch):
            def loss_fn(p):
                out = module.forward(p, batch["obs"])
                logp = module.dist.logp(
                    out["action_dist_inputs"], batch["actions"]
                )
                return -logp.mean()

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update = update

    def train(self) -> dict:
        cfg = self.config
        loss = None
        for _ in range(cfg.updates_per_iteration):
            batch = self.dataset.sample(cfg.train_batch_size)
            dev = {k: jnp.asarray(v) for k, v in batch.items()}
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, dev
            )
        self.iteration += 1
        return {"loss": float(loss), "iteration": self.iteration,
                "dataset_size": len(self.dataset)}

    def compute_actions(self, obs) -> np.ndarray:
        return np.asarray(self._infer(self.params, jnp.asarray(obs)))

    def get_state(self) -> dict:
        return {"params": jax.device_get(self.params),
                "iteration": self.iteration}

    def set_state(self, state: dict) -> None:
        self.params = jax.device_put(state["params"])
        self.iteration = state["iteration"]


class CQL:
    """Conservative Q-Learning: SAC's jitted update with cql_alpha > 0,
    driven purely by offline minibatches (no env interaction).

    Reference analog: rllib/algorithms/cql (SAC-based offline RL).
    Build a SACConfig (cql_alpha defaults to 1.0 here if unset), pass the
    dataset, train() consumes minibatches only."""

    def __init__(self, sac_config, dataset, updates_per_iteration: int = 100):
        from ray_tpu.rl.algorithms.sac import SAC

        if not isinstance(dataset, OfflineData):
            dataset = OfflineData(dataset)
        self.dataset = dataset
        if sac_config.cql_alpha <= 0:
            sac_config.cql_alpha = 1.0
        self.sac = SAC(sac_config)
        self.updates_per_iteration = updates_per_iteration
        self.iteration = 0
        self._infer = jax.jit(self.sac.module.inference)

    def train(self) -> dict:
        m: dict = {}
        for _ in range(self.updates_per_iteration):
            batch = self.dataset.sample(self.sac.config.train_batch_size)
            m = self.sac.offline_update(batch)
        self.iteration += 1
        m["iteration"] = self.iteration
        return m

    @property
    def params(self):
        return self.sac.params

    def compute_actions(self, obs) -> np.ndarray:
        return np.asarray(self._infer(self.sac.params, jnp.asarray(obs)))


class MARWILConfig(BCConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = MARWIL
        self.beta = 1.0        # 0 = plain BC
        self.vf_coeff = 1.0
        self.gamma = 0.99
        self.adv_clip = 20.0   # cap on exp-advantage weights

    def training(self, **kwargs):
        for k in ("beta", "vf_coeff", "adv_clip"):
            if k in kwargs:
                setattr(self, k, kwargs.pop(k))
        return super().training(**kwargs)


class MARWIL(BC):
    """Monotonic Advantage Re-Weighted Imitation Learning.

    Reference analog: rllib/algorithms/marwil (BC is its beta=0 case):
    imitation weighted by exp(beta * normalized advantage), advantage =
    monte-carlo return-to-go minus a learned value baseline — cloning
    leans toward the dataset's BETTER-than-average actions instead of
    imitating everything uniformly.

    Dataset needs obs/actions plus either a precomputed "returns"
    column or rewards (+ terminateds/dones episode boundaries, rows in
    trajectory order) from which discounted return-to-go is derived.
    """

    @classmethod
    def default_config(cls) -> MARWILConfig:
        return MARWILConfig()

    def __init__(self, config: Optional["MARWILConfig"] = None,
                 module_spec: Optional[RLModuleSpec] = None):
        super().__init__(config, module_spec)
        cols = self.dataset.columns
        if "returns" not in cols:
            if "rewards" not in cols:
                raise ValueError(
                    "MARWIL needs a 'returns' column, or 'rewards' "
                    "(+ 'terminateds'/'dones') to derive return-to-go"
                )
            dones = cols.get("terminateds", cols.get("dones"))
            if dones is None:
                raise ValueError("MARWIL needs 'terminateds'/'dones' with rewards")
            r = np.asarray(cols["rewards"], np.float32)
            d = np.asarray(dones, np.float32)
            g = np.zeros_like(r)
            acc = 0.0
            for i in range(len(r) - 1, -1, -1):
                acc = r[i] + self.config.gamma * acc * (1.0 - d[i])
                g[i] = acc
            # an algorithm-OWNED dataset view: the derived column is
            # gamma-specific, and the caller's object must not mutate
            # (a second MARWIL at another gamma would silently reuse it)
            self.dataset = OfflineData({**cols, "returns": g})

    def _build_update(self):
        module = self.module
        cfg = self.config
        beta, vf_coeff, clip = cfg.beta, cfg.vf_coeff, cfg.adv_clip

        @jax.jit
        def update(params, opt_state, batch):
            def loss_fn(p):
                out = module.forward(p, batch["obs"])
                logp = module.dist.logp(
                    out["action_dist_inputs"], batch["actions"]
                )
                v = out["vf"]
                returns = batch["returns"].astype(jnp.float32)
                vf_loss = jnp.square(v - returns).mean()
                adv = returns - jax.lax.stop_gradient(v)
                # batch-normalized advantage inside the exp (reference
                # normalizes by a running estimate of E[adv^2])
                scale = jnp.sqrt(jnp.mean(jnp.square(adv)) + 1e-8)
                w = jnp.clip(jnp.exp(beta * adv / scale), 0.0, clip)
                bc_loss = -(jax.lax.stop_gradient(w) * logp).mean()
                return bc_loss + vf_coeff * vf_loss

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self.optimizer.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        self._update = update
