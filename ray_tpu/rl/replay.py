"""Replay buffers for off-policy algorithms.

Reference analog: rllib/utils/replay_buffers/ (EpisodeReplayBuffer,
PrioritizedEpisodeReplayBuffer). Flat numpy ring buffers here — the
buffer lives on host RAM (HBM is for the learner), and sampling
produces contiguous batches ready to ship to the device in one
transfer.
"""

from __future__ import annotations

from typing import Optional

import numpy as np


class ReplayBuffer:
    """Uniform transition ring buffer."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = capacity
        self.rng = np.random.default_rng(seed)
        self._store: Optional[dict] = None
        self.size = 0
        self._next = 0

    def add_batch(self, batch: dict) -> None:
        """Add flat [N, ...] transitions (obs/actions/rewards/next_obs/terminateds)."""
        n = len(batch["obs"])
        if self._store is None:
            self._store = {
                k: np.empty((self.capacity,) + v.shape[1:], v.dtype)
                for k, v in batch.items()
            }
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._store[k][idx] = v
        self._next = (self._next + n) % self.capacity
        self.size = min(self.size + n, self.capacity)

    def sample(self, batch_size: int) -> dict:
        idx = self.rng.integers(0, self.size, batch_size)
        return {k: v[idx] for k, v in self._store.items()}

    def __len__(self) -> int:
        return self.size


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (Schaul et al. 2015) with a flat
    priority array; O(n) sampling via cumsum — fine for host-side buffers
    at DQN scales, no sum-tree needed."""

    def __init__(self, capacity: int, alpha: float = 0.6, seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self._prio = np.zeros(capacity, np.float64)
        self._max_prio = 1.0

    def add_batch(self, batch: dict) -> None:
        n = len(batch["obs"])
        idx = (self._next + np.arange(n)) % self.capacity
        super().add_batch(batch)
        self._prio[idx] = self._max_prio**self.alpha

    def sample(self, batch_size: int, beta: float = 0.4) -> dict:
        p = self._prio[: self.size]
        probs = p / p.sum()
        idx = self.rng.choice(self.size, batch_size, p=probs)
        weights = (self.size * probs[idx]) ** (-beta)
        out = {k: v[idx] for k, v in self._store.items()}
        out["weights"] = (weights / weights.max()).astype(np.float32)
        out["idx"] = idx
        return out

    def update_priorities(self, idx: np.ndarray, td_errors: np.ndarray) -> None:
        prio = np.abs(td_errors) + 1e-6
        self._prio[idx] = prio**self.alpha
        self._max_prio = max(self._max_prio, float(prio.max()))
