"""AlgorithmConfig: fluent builder for RL algorithms.

Reference analog: rllib/algorithms/algorithm_config.py (the
.environment().env_runners().training().build_algo() chain). Kept the
same surface so reference users can port configs 1:1; fields not
meaningful on TPU (framework selection, torch compile flags) are gone —
there is one framework here.
"""

from __future__ import annotations

import copy
from typing import Callable, Optional


class AlgorithmConfig:
    def __init__(self, algo_class: Optional[type] = None):
        self.algo_class = algo_class
        # environment
        self.env: "str | Callable | None" = None
        self.env_config: dict = {}
        # env runners
        self.num_env_runners = 0
        self.num_envs_per_env_runner = 8
        self.rollout_fragment_length = 64
        self.explore = True
        # training (common)
        self.lr = 3e-4
        self.gamma = 0.99
        self.train_batch_size = 512
        self.minibatch_size = 128
        self.num_epochs = 4
        self.grad_clip = 0.5
        self.model: dict = {"hidden": (256, 256)}
        # learners
        self.num_learners = 0
        # algo-specific knobs land here via .training(**kwargs)
        self.extra: dict = {}
        self.seed = 0

    # -- fluent sections (each returns self, reference-style) ---------------

    def environment(self, env=None, *, env_config: Optional[dict] = None):
        if env is not None:
            self.env = env
        if env_config is not None:
            self.env_config = env_config
        return self

    def env_runners(
        self,
        *,
        num_env_runners: Optional[int] = None,
        num_envs_per_env_runner: Optional[int] = None,
        rollout_fragment_length: Optional[int] = None,
        explore: Optional[bool] = None,
    ):
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        if explore is not None:
            self.explore = explore
        return self

    def training(self, **kwargs):
        for k, v in kwargs.items():
            if hasattr(self, k) and k != "extra":
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def learners(self, *, num_learners: Optional[int] = None):
        if num_learners is not None:
            self.num_learners = num_learners
        return self

    def debugging(self, *, seed: Optional[int] = None):
        if seed is not None:
            self.seed = seed
        return self

    # -- build --------------------------------------------------------------

    def copy(self) -> "AlgorithmConfig":
        return copy.deepcopy(self)

    def to_dict(self) -> dict:
        d = {k: v for k, v in self.__dict__.items() if k != "algo_class"}
        return copy.deepcopy(d)

    def update_from_dict(self, d: dict) -> "AlgorithmConfig":
        for k, v in d.items():
            if k == "extra":
                self.extra.update(v)  # round-trips to_dict() output
            elif hasattr(self, k):
                setattr(self, k, v)
            else:
                self.extra[k] = v
        return self

    def build_algo(self):
        if self.algo_class is None:
            raise ValueError("config has no algo_class; use PPOConfig() etc.")
        return self.algo_class(config=self)

    # legacy alias (reference keeps both)
    build = build_algo
