"""Off-policy evaluation: IS / WIS / DM / DR estimators + FQE.

Reference analog: rllib/offline/estimators/ — importance_sampling.py,
weighted_importance_sampling.py, direct_method.py, doubly_robust.py,
with fqe_torch_model.py providing the Q-model DM/DR need. Redesigned
functional: estimators are pure numpy over EPISODE dicts, FQE is one
jitted fitted-Q iteration loop (discrete actions).

An episode dict: {"obs" [T, obs_dim], "actions" [T] int, "rewards" [T],
"action_prob" [T] (behavior policy's probability of the logged
action)}. `policy` is anything with `action_probs(obs) -> [T, A]`
(TargetPolicy wraps an RLModule + params).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class TargetPolicy:
    """RLModule adapter exposing action probabilities (discrete)."""

    def __init__(self, module, params):
        self.module = module
        self.params = params
        self._probs = jax.jit(
            lambda p, obs: jax.nn.softmax(
                module.forward(p, obs)["action_dist_inputs"], axis=-1
            )
        )

    def action_probs(self, obs) -> np.ndarray:  # [T, A]
        return np.asarray(self._probs(self.params, jnp.asarray(obs)))


def _ratios(policy, ep) -> np.ndarray:
    """Per-step rho_t = pi(a_t|s_t) / b(a_t|s_t)."""
    probs = policy.action_probs(ep["obs"])
    pi = probs[np.arange(len(ep["actions"])), np.asarray(ep["actions"], int)]
    b = np.clip(np.asarray(ep["action_prob"], np.float64), 1e-8, None)
    return pi / b


def _behavior_return(ep, gamma: float) -> float:
    r = np.asarray(ep["rewards"], np.float64)
    return float((r * gamma ** np.arange(len(r))).sum())


class OffPolicyEstimator:
    def __init__(self, policy, gamma: float = 0.99):
        self.policy = policy
        self.gamma = gamma

    def estimate(self, episodes: Sequence[dict]) -> dict:
        vals = [self.estimate_on_single_episode(ep) for ep in episodes]
        return self._summarize(vals, episodes)

    def _summarize(self, vals: Sequence[float],
                   episodes: Sequence[dict]) -> dict:
        behav = [_behavior_return(ep, self.gamma) for ep in episodes]
        v_t = float(np.mean(vals))
        v_b = float(np.mean(behav))
        # v_gain is only meaningful for positive behavior value: dividing
        # by a NEGATIVE v_behavior sign-flips the ratio (a better target
        # policy reads as gain < 1), and by ~0 it explodes — report NaN
        # and let callers compare v_target - v_behavior instead
        return {
            "v_target": v_t,
            "v_behavior": v_b,
            "v_gain": v_t / v_b if v_b > 0 else float("nan"),
            "v_std": float(np.std(vals) / max(1, len(vals)) ** 0.5),
        }

    def estimate_on_single_episode(self, ep: dict) -> float:
        raise NotImplementedError


class ImportanceSampling(OffPolicyEstimator):
    """Per-decision IS (reference: estimators/importance_sampling.py):
    V = sum_t gamma^t (prod_{k<=t} rho_k) r_t."""

    def estimate_on_single_episode(self, ep: dict) -> float:
        rho = np.cumprod(_ratios(self.policy, ep))
        r = np.asarray(ep["rewards"], np.float64)
        return float((self.gamma ** np.arange(len(r)) * rho * r).sum())


class WeightedImportanceSampling(OffPolicyEstimator):
    """WIS: cumulative weights normalized per TIMESTEP across the
    dataset (reference: weighted_importance_sampling.py) — biased but
    far lower variance than plain IS.

    Caveat (inherent to the estimator, reference included): on
    CONSTANT-reward domains (e.g. CartPole's +1/step) the per-timestep
    normalization cancels exactly and v_target == v_behavior for any
    policy — use IS or DR there."""

    def estimate(self, episodes: Sequence[dict]) -> dict:
        cum = [np.cumprod(_ratios(self.policy, ep)) for ep in episodes]
        T = max(len(c) for c in cum)
        # mean cumulative weight at each t over episodes still running
        sums = np.zeros(T)
        counts = np.zeros(T)
        for c in cum:
            sums[: len(c)] += c
            counts[: len(c)] += 1
        w_mean = sums / np.maximum(counts, 1)
        vals = []
        for ep, c in zip(episodes, cum):
            r = np.asarray(ep["rewards"], np.float64)
            t = np.arange(len(r))
            w = c / np.clip(w_mean[: len(c)], 1e-12, None)
            vals.append(float((self.gamma**t * w * r).sum()))
        return self._summarize(vals, episodes)


class DirectMethod(OffPolicyEstimator):
    """DM (reference: direct_method.py): V = E_{a ~ pi}[Q(s_0, a)] from
    a fitted Q-model (FQE)."""

    def __init__(self, policy, q_model: "FQE", gamma: float = 0.99):
        super().__init__(policy, gamma)
        self.q_model = q_model

    def estimate_on_single_episode(self, ep: dict) -> float:
        q0 = self.q_model.q_values(ep["obs"][:1])[0]        # [A]
        pi0 = self.policy.action_probs(ep["obs"][:1])[0]    # [A]
        return float((pi0 * q0).sum())


class DoublyRobust(OffPolicyEstimator):
    """Per-decision DR (reference: doubly_robust.py, Jiang & Li 2016):
    V_DR^t = Vhat(s_t) + rho_t (r_t + gamma V_DR^{t+1} - Qhat(s_t, a_t)),
    unbiased when either the model or the behavior probs are right."""

    def __init__(self, policy, q_model: "FQE", gamma: float = 0.99):
        super().__init__(policy, gamma)
        self.q_model = q_model

    def estimate_on_single_episode(self, ep: dict) -> float:
        rho = _ratios(self.policy, ep)
        q = self.q_model.q_values(ep["obs"])               # [T, A]
        pi = self.policy.action_probs(ep["obs"])           # [T, A]
        v_hat = (pi * q).sum(-1)                           # [T]
        q_a = q[np.arange(len(rho)), np.asarray(ep["actions"], int)]
        r = np.asarray(ep["rewards"], np.float64)
        v_dr = 0.0
        for t in range(len(r) - 1, -1, -1):
            v_dr = v_hat[t] + rho[t] * (r[t] + self.gamma * v_dr - q_a[t])
        return float(v_dr)


class FQE:
    """Fitted Q Evaluation for a FIXED target policy (discrete actions).

    Reference analog: offline/estimators/fqe_torch_model.py — an MLP
    Q(s, .) trained by iterated Bellman regression
        Q <- r + gamma * (1 - done) * sum_a pi(a|s') Q_tgt(s', a)
    with a periodically synced target net; one jitted update."""

    def __init__(self, policy, obs_dim: int, num_actions: int,
                 hidden: tuple = (64, 64), lr: float = 1e-2,
                 gamma: float = 0.99, target_sync: int = 25, seed: int = 0):
        from ray_tpu.rl.module import _mlp_apply, _mlp_init

        self.policy = policy
        self.gamma = gamma
        self.target_sync = target_sync
        key = jax.random.key(seed)
        dims = [obs_dim, *hidden, num_actions]
        self.params = _mlp_init(key, dims)
        self.tgt = jax.tree.map(jnp.copy, self.params)
        self._apply = _mlp_apply
        import optax

        self.opt = optax.adam(lr)
        self.opt_state = self.opt.init(self.params)

        def update(params, opt_state, tgt, batch):
            def loss_fn(p):
                q = _mlp_apply(p, batch["obs"])  # [N, A]
                q_a = jnp.take_along_axis(
                    q, batch["actions"][:, None], axis=-1
                )[:, 0]
                qn = _mlp_apply(tgt, batch["next_obs"])
                v_next = (batch["pi_next"] * qn).sum(-1)
                target = batch["rewards"] + gamma * (1 - batch["dones"]) * v_next
                return jnp.square(q_a - jax.lax.stop_gradient(target)).mean()

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            import optax as _optax

            return _optax.apply_updates(params, updates), opt_state, loss

        self._update = jax.jit(update)
        self._q = jax.jit(_mlp_apply)

    def train(self, episodes: Sequence[dict], iters: int = 200,
              batch_size: int = 256, seed: int = 0) -> float:
        obs, actions, rewards, next_obs, dones = [], [], [], [], []
        for ep in episodes:
            T = len(ep["rewards"])
            terminated = ep.get("terminated", True)
            # a TRUNCATED episode's last transition has no observed
            # successor state — bootstrapping it from obs[-1] itself
            # would chase the self-referential fixed point r/(1-gamma);
            # drop it from the regression set instead
            keep = T if terminated else T - 1
            if keep <= 0:
                continue
            o = np.asarray(ep["obs"], np.float32)
            obs.append(o[:keep])
            actions.append(np.asarray(ep["actions"][:keep], np.int32))
            rewards.append(np.asarray(ep["rewards"][:keep], np.float32))
            nxt = np.concatenate([o[1:], o[-1:]], 0)[:keep]
            next_obs.append(nxt)
            d = np.zeros(keep, np.float32)
            if terminated:
                d[-1] = 1.0
            dones.append(d)
        if not obs:
            raise ValueError(
                "FQE has no usable transitions: every episode was empty or "
                "a 1-step truncation (truncated finals are excluded from "
                "the Bellman regression)"
            )
        obs = np.concatenate(obs)
        actions = np.concatenate(actions)
        rewards = np.concatenate(rewards)
        next_obs = np.concatenate(next_obs)
        dones = np.concatenate(dones)
        pi_next = self.policy.action_probs(next_obs)
        rng = np.random.default_rng(seed)
        loss = 0.0
        for i in range(iters):
            idx = rng.integers(0, len(obs), size=min(batch_size, len(obs)))
            batch = {
                "obs": jnp.asarray(obs[idx]),
                "actions": jnp.asarray(actions[idx]),
                "rewards": jnp.asarray(rewards[idx]),
                "next_obs": jnp.asarray(next_obs[idx]),
                "dones": jnp.asarray(dones[idx]),
                "pi_next": jnp.asarray(pi_next[idx]),
            }
            self.params, self.opt_state, loss = self._update(
                self.params, self.opt_state, self.tgt, batch
            )
            if (i + 1) % self.target_sync == 0:
                self.tgt = jax.tree.map(jnp.copy, self.params)
        return float(loss)

    def q_values(self, obs) -> np.ndarray:
        return np.asarray(self._q(self.params, jnp.asarray(obs, jnp.float32)))
