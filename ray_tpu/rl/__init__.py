"""ray_tpu.rl: reinforcement learning (the RLlib-equivalent layer).

Reference analog: rllib/ (188k LoC; Algorithm/EnvRunnerGroup/RLModule/
LearnerGroup architecture — see SURVEY.md §2.5). TPU-first redesign:
modules are functional JAX pytrees, learners are single pjit programs
over the device mesh (no DDP actor tier), and trajectory math (GAE,
V-trace) compiles into the update as lax.scan.
"""

from ray_tpu.rl.config import AlgorithmConfig
from ray_tpu.rl.algorithm import Algorithm
from ray_tpu.rl.module import MLPModule, RLModule, RLModuleSpec
from ray_tpu.rl.env_runner import EnvRunnerGroup, SingleAgentEnvRunner
from ray_tpu.rl.learner import Learner, LearnerGroup
from ray_tpu.rl.replay import PrioritizedReplayBuffer, ReplayBuffer
from ray_tpu.rl.algorithms import (APPO, APPOConfig, DQN, DQNConfig, IMPALA,
                                   IMPALAConfig, PPO, PPOConfig)

__all__ = [
    "Algorithm",
    "AlgorithmConfig",
    "RLModule",
    "RLModuleSpec",
    "MLPModule",
    "EnvRunnerGroup",
    "SingleAgentEnvRunner",
    "Learner",
    "LearnerGroup",
    "ReplayBuffer",
    "PrioritizedReplayBuffer",
    "APPO",
    "APPOConfig",
    "PPO",
    "PPOConfig",
    "IMPALA",
    "IMPALAConfig",
    "DQN",
    "DQNConfig",
]
